// mxnet_tpu_cpp — header-only C++ GRAPH API over the flat C ABI
// (ref cpp-package/include/mxnet-cpp Symbol/Executor over c_api.h
// MXSymbolCreateAtomicSymbol/MXSymbolCompose/MXExecutorSimpleBindEx).
//
// With predictor.hpp a C++ program can run exported artifacts; with this
// header it can BUILD a graph, bind an executor, and TRAIN:
//
//   using namespace mxnet_tpu_cpp;
//   Symbol data = Symbol::Variable("data");
//   Symbol fc = Symbol::Op("FullyConnected", R"({"num_hidden": 8})")
//                   .Compose("fc1", {{"data", data}});
//   Executor ex = fc.SimpleBind(R"({"data": [4, 3]})", "write");
//   ex.Forward(true, {{"data", batch}});
//   ex.Backward();
//   NDArray g = ex.ArgGrad("fc1_weight");
//
// Zero build-time dependencies: dlopen (MXTPU_PREDICT_LIB or
// "libmxtpu_predict.so" on the loader path); compile with `g++ app.cc -ldl`.
#pragma once

#include <dlfcn.h>

#include <cstdint>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace mxnet_tpu_cpp {

namespace graph_detail {

struct Api {
  void* so;
  const char* (*GetLastError)();
  int (*NDCreate)(const char*, const int64_t*, int, const void*, int64_t,
                  void**);
  int (*NDGetShape)(void*, int64_t*, int, int*);
  int (*NDGetData)(void*, void*, int64_t, int64_t*);
  int (*NDSetData)(void*, const char*, const void*, int64_t);
  int (*NDFree)(void*);
  int (*SymVariable)(const char*, void**);
  int (*SymAtomic)(const char*, const char*, void**);
  int (*SymCompose)(void*, const char*, int, const char**, void**);
  int (*SymListArguments)(void*, char*, int, int64_t*);
  int (*SymListOutputs)(void*, char*, int, int64_t*);
  int (*SymToJSON)(void*, char*, int, int64_t*);
  int (*SymFree)(void*);
  int (*ExSimpleBind)(void*, const char*, const char*, void**);
  int (*ExForward)(void*, int, int, const char**, void**);
  int (*ExNumOutputs)(void*, int*);
  int (*ExOutput)(void*, int, void**);
  int (*ExBackward)(void*, int, void**);
  int (*ExArg)(void*, const char*, void**);
  int (*ExArgGrad)(void*, const char*, void**);
  int (*ExFree)(void*);

  template <typename T>
  void Sym(T& fn, const char* name) {
    fn = reinterpret_cast<T>(dlsym(so, name));
    if (!fn)
      throw std::runtime_error(std::string("missing symbol ") + name);
  }

  static Api& Get() {
    static Api api = Load();
    return api;
  }

  static Api Load() {
    Api a;
    const char* path = std::getenv("MXTPU_PREDICT_LIB");
    a.so = dlopen(path ? path : "libmxtpu_predict.so", RTLD_NOW | RTLD_GLOBAL);
    if (!a.so)
      throw std::runtime_error(std::string("dlopen failed: ") + dlerror());
    a.Sym(a.GetLastError, "MXTPUNDGetLastError");
    a.Sym(a.NDCreate, "MXTPUNDCreate");
    a.Sym(a.NDGetShape, "MXTPUNDGetShape");
    a.Sym(a.NDGetData, "MXTPUNDGetData");
    a.Sym(a.NDSetData, "MXTPUNDSetData");
    a.Sym(a.NDFree, "MXTPUNDFree");
    a.Sym(a.SymVariable, "MXTPUSymbolCreateVariable");
    a.Sym(a.SymAtomic, "MXTPUSymbolCreateAtomic");
    a.Sym(a.SymCompose, "MXTPUSymbolCompose");
    a.Sym(a.SymListArguments, "MXTPUSymbolListArguments");
    a.Sym(a.SymListOutputs, "MXTPUSymbolListOutputs");
    a.Sym(a.SymToJSON, "MXTPUSymbolToJSON");
    a.Sym(a.SymFree, "MXTPUSymbolFree");
    a.Sym(a.ExSimpleBind, "MXTPUExecutorSimpleBind");
    a.Sym(a.ExForward, "MXTPUExecutorForward");
    a.Sym(a.ExNumOutputs, "MXTPUExecutorNumOutputs");
    a.Sym(a.ExOutput, "MXTPUExecutorOutput");
    a.Sym(a.ExBackward, "MXTPUExecutorBackward");
    a.Sym(a.ExArg, "MXTPUExecutorArg");
    a.Sym(a.ExArgGrad, "MXTPUExecutorArgGrad");
    a.Sym(a.ExFree, "MXTPUExecutorFree");
    return a;
  }
};

inline void Check(int rc, const char* what) {
  if (rc != 0)
    throw std::runtime_error(std::string(what) + ": " +
                             Api::Get().GetLastError());
}

}  // namespace graph_detail

// Owning wrapper over an ND ABI handle (float32 host interface).
class NDArray {
 public:
  NDArray() : h_(nullptr) {}
  NDArray(const std::vector<int64_t>& shape, const std::vector<float>& data) {
    graph_detail::Check(
        graph_detail::Api::Get().NDCreate(
            "float32", shape.data(), (int)shape.size(), data.data(),
            (int64_t)(data.size() * sizeof(float)), &h_),
        "NDCreate");
  }
  explicit NDArray(void* owned) : h_(owned) {}
  NDArray(NDArray&& o) noexcept : h_(o.h_) { o.h_ = nullptr; }
  NDArray& operator=(NDArray&& o) noexcept {
    std::swap(h_, o.h_);
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;
  ~NDArray() {
    if (h_) graph_detail::Api::Get().NDFree(h_);
  }

  void* handle() const { return h_; }

  std::vector<int64_t> Shape() const {
    int64_t dims[16];
    int nd = 0;
    graph_detail::Check(
        graph_detail::Api::Get().NDGetShape(h_, dims, 16, &nd), "NDGetShape");
    return std::vector<int64_t>(dims, dims + nd);
  }

  std::vector<float> Data() const {
    int64_t nbytes = 0;
    graph_detail::Check(graph_detail::Api::Get().NDGetData(h_, nullptr, 0,
                                                           &nbytes),
                        "NDGetData");
    std::vector<float> out(nbytes / sizeof(float));
    graph_detail::Check(
        graph_detail::Api::Get().NDGetData(h_, out.data(), nbytes, nullptr),
        "NDGetData");
    return out;
  }

  void SetData(const std::vector<float>& v) {
    graph_detail::Check(
        graph_detail::Api::Get().NDSetData(
            h_, "float32", v.data(), (int64_t)(v.size() * sizeof(float))),
        "NDSetData");
  }

 private:
  void* h_;
};

class Executor;

class Symbol {
 public:
  static Symbol Variable(const std::string& name) {
    void* h = nullptr;
    graph_detail::Check(graph_detail::Api::Get().SymVariable(name.c_str(), &h),
                        "SymbolCreateVariable");
    return Symbol(h);
  }

  // ≙ MXSymbolCreateAtomicSymbol; attrs is a JSON object string
  static Symbol Op(const std::string& op, const std::string& attrs_json) {
    void* h = nullptr;
    graph_detail::Check(
        graph_detail::Api::Get().SymAtomic(op.c_str(), attrs_json.c_str(), &h),
        "SymbolCreateAtomic");
    return Symbol(h);
  }

  // ≙ MXSymbolCompose (named operator inputs); rvalue-qualified: legal
  // only in the `Symbol fc = Symbol::Op(...).Compose(...)` chain — calling
  // it on a NAMED symbol would move its handle out and is a compile error
  Symbol&& Compose(
      const std::string& name,
      const std::vector<std::pair<std::string, const Symbol*>>& args) && {
    std::vector<const char*> keys;
    std::vector<void*> handles;
    for (auto& kv : args) {
      keys.push_back(kv.first.c_str());
      handles.push_back(kv.second->h_);
    }
    graph_detail::Check(
        graph_detail::Api::Get().SymCompose(h_, name.c_str(),
                                            (int)args.size(), keys.data(),
                                            handles.data()),
        "SymbolCompose");
    return std::move(*this);
  }

  std::string ListArguments() const { return Str_(graph_detail::Api::Get()
                                                      .SymListArguments); }
  std::string ListOutputs() const { return Str_(graph_detail::Api::Get()
                                                    .SymListOutputs); }
  std::string ToJSON() const { return Str_(graph_detail::Api::Get()
                                               .SymToJSON); }

  Executor SimpleBind(const std::string& shapes_json,
                      const std::string& grad_req) const;

  // raw ABI handle + adoption — the extras.hpp tier (kvstore, file io,
  // infer-shape) moves Symbols across the same C surface
  void* handle() const { return h_; }
  static Symbol FromHandle(void* owned) { return Symbol(owned); }

  Symbol(Symbol&& o) noexcept : h_(o.h_) { o.h_ = nullptr; }
  Symbol& operator=(Symbol&& o) noexcept {
    std::swap(h_, o.h_);
    return *this;
  }
  Symbol(const Symbol&) = delete;
  Symbol& operator=(const Symbol&) = delete;
  ~Symbol() {
    if (h_) graph_detail::Api::Get().SymFree(h_);
  }

 private:
  explicit Symbol(void* h) : h_(h) {}
  std::string Str_(int (*fn)(void*, char*, int, int64_t*)) const {
    // size-probe then fetch: no fixed cap, works for any graph size
    int64_t needed = 0;
    graph_detail::Check(fn(h_, nullptr, 0, &needed), "SymbolStr(probe)");
    std::vector<char> buf((size_t)needed);
    graph_detail::Check(fn(h_, buf.data(), (int)buf.size(), nullptr),
                        "SymbolStr");
    return std::string(buf.data());
  }
  void* h_;
  friend class Executor;
};

class Executor {
 public:
  void Forward(bool is_train,
               const std::vector<std::pair<std::string, const NDArray*>>&
                   feed) {
    std::vector<const char*> names;
    std::vector<void*> handles;
    for (auto& kv : feed) {
      names.push_back(kv.first.c_str());
      handles.push_back(kv.second->handle());
    }
    graph_detail::Check(
        graph_detail::Api::Get().ExForward(h_, is_train ? 1 : 0,
                                           (int)feed.size(), names.data(),
                                           handles.data()),
        "ExecutorForward");
  }

  int NumOutputs() const {
    int n = 0;
    graph_detail::Check(graph_detail::Api::Get().ExNumOutputs(h_, &n),
                        "ExecutorNumOutputs");
    return n;
  }

  NDArray Output(int i) const {
    void* h = nullptr;
    graph_detail::Check(graph_detail::Api::Get().ExOutput(h_, i, &h),
                        "ExecutorOutput");
    return NDArray(h);
  }

  void Backward() {
    graph_detail::Check(graph_detail::Api::Get().ExBackward(h_, 0, nullptr),
                        "ExecutorBackward");
  }

  NDArray Arg(const std::string& name) const {
    void* h = nullptr;
    graph_detail::Check(graph_detail::Api::Get().ExArg(h_, name.c_str(), &h),
                        "ExecutorArg");
    return NDArray(h);
  }

  NDArray ArgGrad(const std::string& name) const {
    void* h = nullptr;
    graph_detail::Check(
        graph_detail::Api::Get().ExArgGrad(h_, name.c_str(), &h),
        "ExecutorArgGrad");
    return NDArray(h);
  }

  Executor(Executor&& o) noexcept : h_(o.h_) { o.h_ = nullptr; }
  Executor& operator=(Executor&& o) noexcept {
    std::swap(h_, o.h_);
    return *this;
  }
  Executor(const Executor&) = delete;
  Executor& operator=(const Executor&) = delete;
  ~Executor() {
    if (h_) graph_detail::Api::Get().ExFree(h_);
  }

 private:
  explicit Executor(void* h) : h_(h) {}
  void* h_;
  friend class Symbol;
};

inline Executor Symbol::SimpleBind(const std::string& shapes_json,
                                   const std::string& grad_req) const {
  void* h = nullptr;
  graph_detail::Check(
      graph_detail::Api::Get().ExSimpleBind(h_, shapes_json.c_str(),
                                            grad_req.c_str(), &h),
      "ExecutorSimpleBind");
  return Executor(h);
}

}  // namespace mxnet_tpu_cpp
