// End-to-end GRAPH training from C++ (ref cpp-package/example/mlp.cpp):
// build a 2-layer MLP symbolically, simple_bind, and train it with SGD —
// no Python in the client program. Exercises the full graph C ABI:
// variable/atomic/compose, list_arguments, simple_bind, forward/backward,
// arg/arg-grad readout, and parameter writeback.
//
//   g++ -std=c++17 train_mlp.cc -ldl -o train_mlp && \
//   MXTPU_PREDICT_LIB=.../libmxtpu_predict.so ./train_mlp
#include <cmath>
#include <cstdio>
#include <random>
#include <string>
#include <vector>

#include "../include/mxnet_tpu_cpp/graph.hpp"

using mxnet_tpu_cpp::Executor;
using mxnet_tpu_cpp::NDArray;
using mxnet_tpu_cpp::Symbol;

int main() {
  const int B = 32, D = 8, H = 16;
  const float lr = 0.05f;

  // ---- symbolic graph: data -> FC(16) -> relu -> FC(1) -> L2 loss
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("y");
  Symbol fc1 = Symbol::Op("FullyConnected", "{\"num_hidden\": 16}")
                   .Compose("fc1", {{"data", &data}});
  Symbol act = Symbol::Op("Activation", "{\"act_type\": \"relu\"}")
                   .Compose("relu1", {{"data", &fc1}});
  Symbol fc2 = Symbol::Op("FullyConnected", "{\"num_hidden\": 1}")
                   .Compose("fc2", {{"data", &act}});
  Symbol out = Symbol::Op("LinearRegressionOutput", "{}")
                   .Compose("lro", {{"data", &fc2}, {"label", &label}});

  std::string args = out.ListArguments();
  std::printf("ARGS %s\n", args.c_str());
  // auto-created weights must be present (MXSymbolCompose parity)
  for (const char* need : {"fc1_weight", "fc1_bias", "fc2_weight",
                           "fc2_bias"})
    if (args.find(need) == std::string::npos) {
      std::fprintf(stderr, "missing auto arg %s\n", need);
      return 1;
    }

  char shapes[256];
  std::snprintf(shapes, sizeof(shapes),
                "{\"data\": [%d, %d], \"y\": [%d, 1],"
                " \"fc1_weight\": [%d, %d], \"fc1_bias\": [%d],"
                " \"fc2_weight\": [1, %d], \"fc2_bias\": [1]}",
                B, D, B, H, D, H, H);
  Executor ex = out.SimpleBind(shapes, "write");

  // ---- init params (Xavier-ish) + synthetic regression task
  std::mt19937 rng(0);
  std::normal_distribution<float> gauss(0.f, 1.f);
  auto randv = [&](size_t n, float scale) {
    std::vector<float> v(n);
    for (auto& x : v) x = gauss(rng) * scale;
    return v;
  };
  ex.Arg("fc1_weight").SetData(randv((size_t)H * D, 0.4f));
  ex.Arg("fc2_weight").SetData(randv((size_t)H, 0.4f));

  std::vector<float> xs = randv((size_t)B * D, 1.f);
  std::vector<float> ys((size_t)B);
  for (int i = 0; i < B; ++i) {
    float s = 0.f;
    for (int j = 0; j < D; ++j) s += xs[(size_t)i * D + j];
    ys[(size_t)i] = std::tanh(s) + 0.5f;
  }
  NDArray x({B, D}, xs);
  NDArray y({B, 1}, ys);

  const char* params[] = {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"};
  float first = -1.f, last = -1.f;
  for (int step = 0; step < 60; ++step) {
    ex.Forward(true, {{"data", &x}, {"y", &y}});
    ex.Backward();
    for (const char* p : params) {
      NDArray w = ex.Arg(p);
      NDArray g = ex.ArgGrad(p);
      std::vector<float> wv = w.Data(), gv = g.Data();
      for (size_t i = 0; i < wv.size(); ++i) wv[i] -= lr * gv[i] / B;
      w.SetData(wv);
    }
    std::vector<float> pred = ex.Output(0).Data();
    float mse = 0.f;
    for (int i = 0; i < B; ++i) {
      float d = pred[(size_t)i] - ys[(size_t)i];
      mse += d * d;
    }
    mse /= B;
    if (step == 0) first = mse;
    last = mse;
    if (step % 20 == 0) std::printf("STEP %d MSE %.5f\n", step, mse);
  }
  std::printf("FINAL MSE %.5f (from %.5f)\n", last, first);
  if (!(last < first * 0.2f)) {
    std::fprintf(stderr, "training did not converge\n");
    return 1;
  }
  std::printf("CPP GRAPH TRAIN OK\n");
  return 0;
}
