// Minimal C++ inference client (ref cpp-package/example/inference).
//
// Usage: predict <model.mxtpu> <input.bin>
// Reads input 0 as raw float32 bytes from input.bin, runs one forward,
// prints output 0 as one float per line (parsed by tests/test_cpp_package.py).
//
// Build: g++ -O3 -std=c++17 predict.cc -I../include -ldl -o predict
#include <cstdio>
#include <fstream>
#include <vector>

#include "mxnet_tpu_cpp/predictor.hpp"

int main(int argc, char** argv) {
  if (argc != 3) {
    std::fprintf(stderr, "usage: %s <model.mxtpu> <input.bin>\n", argv[0]);
    return 2;
  }
  try {
    mxnet_tpu_cpp::Predictor pred(argv[1]);

    std::ifstream in(argv[2], std::ios::binary);
    std::vector<char> buf((std::istreambuf_iterator<char>(in)),
                          std::istreambuf_iterator<char>());
    pred.SetInputBytes(0, buf.data(), static_cast<int64_t>(buf.size()));
    pred.Forward();

    std::vector<float> out = pred.GetOutput(0);
    for (float v : out) std::printf("%.6e\n", v);
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
