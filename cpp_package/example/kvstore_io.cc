// Extended-tier C++ example: kvstore push/pull, NDArray file round-trip,
// symbol JSON load + shape inference, op-registry listing — all through
// the flat C ABI (ref cpp-package/example over c_api.h MXKVStore*,
// MXNDArraySave/Load, MXSymbolInferShape, MXListAllOpNames).
//
// Build: g++ -O2 -std=c++17 kvstore_io.cc -I../include -ldl -o kvstore_io
// Run:   MXTPU_PREDICT_LIB=/path/to/libmxtpu_predict.so ./kvstore_io
#include <cmath>
#include <cstdio>
#include <string>
#include <vector>

#include "mxnet_tpu_cpp/extras.hpp"
#include "mxnet_tpu_cpp/graph.hpp"

using namespace mxnet_tpu_cpp;  // NOLINT

static bool almost(float a, float b) { return std::fabs(a - b) < 1e-5f; }

int main(int argc, char** argv) {
  const std::string dir = argc > 1 ? argv[1] : ".";
  try {
    RandomSeed(7);

    // ---- kvstore: init / push / pull
    KVStore kv("local");
    std::printf("kv type=%s rank=%d workers=%d\n", kv.Type().c_str(),
                kv.Rank(), kv.NumWorkers());
    NDArray w({4}, {1.f, 2.f, 3.f, 4.f});
    kv.Init({3}, {&w});
    NDArray g({4}, {10.f, 20.f, 30.f, 40.f});
    kv.Push({3}, {&g});
    NDArray out({4}, {0.f, 0.f, 0.f, 0.f});
    kv.Pull({3}, {&out});
    auto v = out.Data();
    if (!almost(v[1], 20.f)) {
      std::fprintf(stderr, "pull mismatch: %f\n", v[1]);
      return 1;
    }

    // ---- NDArray file round-trip
    const std::string params = dir + "/cpp_kv_io.params";
    SaveArrays(params, {"weight", "grad"}, {&w, &g});
    auto loaded = LoadArrays(params);
    if (loaded.size() != 2 || loaded[0].first != "weight" ||
        !almost(loaded[1].second.Data()[2], 30.f)) {
      std::fprintf(stderr, "load mismatch\n");
      return 1;
    }

    // ---- symbol: compose in C++, save, reload from JSON, infer shapes
    Symbol data = Symbol::Variable("data");
    Symbol fc = Symbol::Op("FullyConnected", R"({"num_hidden": 8})")
                    .Compose("fc1", {{"data", &data}});
    const std::string sym_file = dir + "/cpp_kv_io.json";
    SaveSymbol(fc, sym_file);
    Symbol re = SymbolFromJSON(fc.ToJSON());
    std::string shapes = InferShapeJSON(
        re, R"({"data": [2, 16], "fc1_weight": [8, 16], "fc1_bias": [8]})");
    if (shapes.find("[2, 8]") == std::string::npos &&
        shapes.find("[2,8]") == std::string::npos) {
      std::fprintf(stderr, "infer_shape wrong: %s\n", shapes.c_str());
      return 1;
    }

    // ---- registry listing
    std::string ops = ListAllOpNamesJSON();
    if (ops.find("Convolution") == std::string::npos) {
      std::fprintf(stderr, "op list missing Convolution\n");
      return 1;
    }

    std::printf("CPP EXT TIER OK\n");
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAIL: %s\n", e.what());
    return 1;
  }
}
