"""Generic object registry (ref python/mxnet/registry.py get/alias/create).

The reference generates register()/alias()/create() function triples for
optimizers, initializers, metrics, ...; the same factory lives here so
subsystems (and user libraries) share one idiom.
"""
from __future__ import annotations

import json

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]

_REGISTRIES = {}


def _registry(base_class, nickname):
    return _REGISTRIES.setdefault((base_class, nickname), {})


def get_register_func(base_class, nickname):
    """ref registry.py get_register_func."""
    reg = _registry(base_class, nickname)

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            "%s must subclass %s" % (klass, base_class)
        reg[(name or klass.__name__).lower()] = klass
        return klass

    register.__doc__ = "Register a %s" % nickname
    return register


def get_alias_func(base_class, nickname):
    """ref registry.py get_alias_func."""
    reg = _registry(base_class, nickname)

    def alias(name):
        def do(klass):
            reg[name.lower()] = klass
            return klass
        return do

    return alias


def get_create_func(base_class, nickname):
    """ref registry.py get_create_func — create('name', **kw), create('{json}'),
    or pass an instance through."""
    reg = _registry(base_class, nickname)

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            return args[0]
        name = args[0]
        args = args[1:]
        if name.startswith("{"):
            spec = json.loads(name)
            name = spec.pop("__name__" if "__name__" in spec else "name")
            kwargs = dict(spec, **kwargs)
        if name.lower() not in reg:
            raise ValueError("unknown %s %r (have: %s)"
                             % (nickname, name, sorted(reg)))
        return reg[name.lower()](*args, **kwargs)

    return create
