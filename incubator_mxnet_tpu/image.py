"""Image utilities & augmenters (ref python/mxnet/image/image.py + ImageIter).

Decode via PIL (the OpenCV analog); resize on device via jax.image; the
augmenter pipeline mirrors the reference's Augmenter list design.
"""
from __future__ import annotations

import io as _io
import os
import random as _random

import numpy as onp

from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize", "Augmenter",
           "ResizeAug", "RandomCropAug", "CenterCropAug", "HorizontalFlipAug",
           "CastAug", "ColorNormalizeAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "ColorJitterAug",
           "LightingAug", "RandomGrayAug", "CreateAugmenter", "ImageIter"]

# ITU-R BT.601 luma weights — single source for every color augmenter
_LUMA_COEF = onp.array([0.299, 0.587, 0.114], "float32")


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decode jpeg/png bytes → HWC uint8 NDArray (ref image.py imdecode)."""
    from PIL import Image

    pil = Image.open(_io.BytesIO(bytes(buf)))
    if flag == 0:
        pil = pil.convert("L")
        arr = onp.asarray(pil)[:, :, None]
    else:
        pil = pil.convert("RGB")
        arr = onp.asarray(pil)
    return nd.array(arr, dtype="uint8")


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w, h, interp=1):
    import jax
    a = src._data if isinstance(src, NDArray) else onp.asarray(src)
    out = jax.image.resize(a.astype("float32"), (h, w, a.shape[2]),
                           method="linear" if interp else "nearest")
    return NDArray(out.astype(a.dtype))


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = _random.randint(0, max(0, w - new_w))
    y0 = _random.randint(0, max(0, h - new_h))
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp), \
        (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp), \
        (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype("float32") - nd.array(mean)
    if std is not None:
        src = src / nd.array(std)
    return src


class Augmenter:
    """ref image.py Augmenter."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size if isinstance(size, tuple) else (size, size)
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size if isinstance(size, tuple) else (size, size)
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _random.random() < self.p:
            return nd.flip(src, axis=1)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    """ref image.py BrightnessJitterAug."""

    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + onp.random.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    """ref image.py ContrastJitterAug (luminance-anchored) — pure nd ops,
    no per-image device sync on the augmentation path."""

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        import jax.numpy as jnp
        from .ndarray import _apply
        alpha = 1.0 + onp.random.uniform(-self.contrast, self.contrast)
        src = src if isinstance(src, NDArray) else nd.array(src)

        def fn(a):
            gray = jnp.sum(a[..., :3] * _LUMA_COEF)
            return a * alpha + 3.0 * (1.0 - alpha) / a.size * gray

        return _apply(fn, src)


class SaturationJitterAug(Augmenter):
    """ref image.py SaturationJitterAug — pure nd ops (no device sync)."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        import jax.numpy as jnp
        from .ndarray import _apply
        alpha = 1.0 + onp.random.uniform(-self.saturation, self.saturation)
        src = src if isinstance(src, NDArray) else nd.array(src)

        def fn(a):
            gray = jnp.sum(a[..., :3] * _LUMA_COEF, axis=-1, keepdims=True)
            return a * alpha + gray * (1.0 - alpha)

        return _apply(fn, src)


class ColorJitterAug(Augmenter):
    """ref image.py ColorJitterAug — random-order composition."""

    def __init__(self, brightness=0, contrast=0, saturation=0):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self._augs = []
        if brightness:
            self._augs.append(BrightnessJitterAug(brightness))
        if contrast:
            self._augs.append(ContrastJitterAug(contrast))
        if saturation:
            self._augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        for i in onp.random.permutation(len(self._augs)):
            src = self._augs[i](src)
        return src


class LightingAug(Augmenter):
    """ref image.py LightingAug — AlexNet-style PCA noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, "float32")
        self.eigvec = onp.asarray(eigvec, "float32")

    def __call__(self, src):
        alpha = onp.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1).astype("float32")
        return src + nd.array(rgb)


class RandomGrayAug(Augmenter):
    """ref image.py RandomGrayAug — pure nd ops (no device sync)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        import jax.numpy as jnp
        from .ndarray import _apply
        if onp.random.rand() < self.p:
            src = src if isinstance(src, NDArray) else nd.array(src)

            def fn(a):
                gray = jnp.sum(a[..., :3] * _LUMA_COEF, axis=-1, keepdims=True)
                return jnp.repeat(gray, 3, axis=-1)

            return _apply(fn, src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, rand_gray=0,
                    **kwargs):
    """ref image.py CreateAugmenter."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size))
    else:
        auglist.append(CenterCropAug(crop_size))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = [55.46, 4.794, 1.148]
        eigvec = [[-0.5675, 0.7192, 0.4009],
                  [-0.5808, -0.0045, -0.8140],
                  [-0.5836, -0.6948, 0.4203]]
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is not None or std is not None:
        if mean is True:
            mean = onp.array([123.68, 116.28, 103.53])
        if std is True:
            std = onp.array([58.395, 57.12, 57.375])
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Python image iterator with augmenters (ref image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False, aug_list=None,
                 imglist=None, **kwargs):
        from .io import DataBatch, DataDesc
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._DataBatch = DataBatch
        self.provide_data = [DataDesc("data", (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc("softmax_label", (batch_size,))]
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self._items = []
        if path_imgrec:
            from .io import ImageRecordIter
            self._rec_iter = ImageRecordIter(
                path_imgrec=path_imgrec, data_shape=data_shape,
                batch_size=batch_size, shuffle=shuffle, **kwargs)
        else:
            self._rec_iter = None
            if imglist:
                for entry in imglist:
                    self._items.append((float(entry[0]), entry[1]))
            elif path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        self._items.append((float(parts[1]),
                                            os.path.join(path_root, parts[-1])))
        self._cursor = 0
        self._shuffle = shuffle

    def reset(self):
        if self._rec_iter is not None:
            self._rec_iter.reset()
        self._cursor = 0
        if self._shuffle:
            _random.shuffle(self._items)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self._rec_iter is not None:
            return self._rec_iter.next()
        if self._cursor >= len(self._items):
            raise StopIteration
        datas, labels = [], []
        for _ in range(self.batch_size):
            label, path = self._items[self._cursor % len(self._items)]
            self._cursor += 1
            img = imread(path)
            for aug in self.auglist:
                img = aug(img)
            datas.append(img.transpose((2, 0, 1)).asnumpy())
            labels.append(label)
        return self._DataBatch([nd.array(onp.stack(datas))],
                               [nd.array(onp.asarray(labels, "float32"))])


# ---------------------------------------------------------------- detection
# (ref python/mxnet/image/detection.py — bbox-aware augmenter pipeline)
class DetAugmenter:
    """Base detection augmenter: __call__(src, label) -> (src, label) with
    label (n_obj, 5) = [cls, x1, y1, x2, y2] normalized (ref detection.py
    DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the det pipeline
    (ref detection.py DetBorrowAug)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image AND x-coordinates with probability p
    (ref detection.py DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _random.random() < self.p:
            src = nd.array(src.asnumpy()[:, ::-1])
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping box centers inside; boxes are clipped and
    re-normalized (compact form of detection.py DetRandomCropAug's
    constraint sampling)."""

    def __init__(self, min_crop_scale=0.6, max_attempts=10):
        self.min_scale = min_crop_scale
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[0], src.shape[1]
        for _ in range(self.max_attempts):
            s = self.min_scale + (1 - self.min_scale) * _random.random()
            cw, ch = int(w * s), int(h * s)
            x0 = _random.randint(0, w - cw)
            y0 = _random.randint(0, h - ch)
            nx0, ny0 = x0 / w, y0 / h
            nx1, ny1 = (x0 + cw) / w, (y0 + ch) / h
            cx = (label[:, 1] + label[:, 3]) / 2
            cy = (label[:, 2] + label[:, 4]) / 2
            keep = (cx > nx0) & (cx < nx1) & (cy > ny0) & (cy < ny1)
            if not keep.any():
                continue
            new = label[keep].copy()
            new[:, (1, 3)] = (new[:, (1, 3)] - nx0) / (nx1 - nx0)
            new[:, (2, 4)] = (new[:, (2, 4)] - ny0) / (ny1 - ny0)
            new[:, 1:] = onp.clip(new[:, 1:], 0.0, 1.0)
            return fixed_crop(src, x0, y0, cw, ch), new
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0, rand_mirror=False,
                       mean=None, std=None, brightness=0, contrast=0,
                       saturation=0, **kwargs):
    """ref detection.py CreateDetAugmenter."""
    augs = []
    if resize > 0:
        augs.append(DetBorrowAug(ResizeAug(resize)))
    if rand_crop > 0:
        augs.append(DetRandomCropAug())
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    augs.append(DetBorrowAug(ResizeAug(max(data_shape[1], data_shape[2]))))
    augs.append(DetBorrowAug(CenterCropAug((data_shape[2], data_shape[1]))))
    if brightness or contrast or saturation:
        augs.append(DetBorrowAug(ColorJitterAug(brightness, contrast,
                                                saturation)))
    if mean is not None or std is not None:
        augs.append(DetBorrowAug(CastAug()))
        augs.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return augs


class ImageDetIter:
    """Detection iterator over an imglist (ref detection.py ImageDetIter):
    items are (label (n,5) ndarray, path); batches carry labels padded to
    (batch, label_pad, 5) with -1 rows."""

    def __init__(self, batch_size, data_shape, imglist=None, path_imgrec=None,
                 label_pad_width=16, shuffle=False, aug_list=None, **kwargs):
        from .io import DataBatch, DataDesc, ImageDetRecordIter
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_pad = label_pad_width
        self._DataBatch = DataBatch
        self.provide_data = [DataDesc("data", (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc("label",
                                       (batch_size, label_pad_width, 5))]
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **kwargs)
        if path_imgrec:
            self._rec = ImageDetRecordIter(
                path_imgrec=path_imgrec, batch_size=batch_size,
                data_shape=data_shape, shuffle=shuffle,
                label_pad_width=label_pad_width, **kwargs)
            self._items = None
        else:
            self._rec = None
            self._items = [(onp.asarray(lbl, "float32").reshape(-1, 5), p)
                           for lbl, p in (imglist or [])]
        self._cursor = 0
        self._shuffle = shuffle

    def reset(self):
        if self._rec is not None:
            self._rec.reset()
        self._cursor = 0
        if self._shuffle and self._items:
            _random.shuffle(self._items)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self._rec is not None:
            return self._rec.next()
        if self._cursor >= len(self._items):
            raise StopIteration
        datas, labels = [], []
        for _ in range(self.batch_size):
            label, path = self._items[self._cursor % len(self._items)]
            self._cursor += 1
            img = imread(path)
            for aug in self.auglist:
                img, label = aug(img, label)
            datas.append(img.asnumpy().transpose((2, 0, 1)))
            pad = onp.full((self.label_pad, 5), -1.0, "float32")
            pad[: min(len(label), self.label_pad)] = \
                label[: self.label_pad]
            labels.append(pad)
        return self._DataBatch([nd.array(onp.stack(datas))],
                               [nd.array(onp.stack(labels))])
