"""KVStore server-role entry (ref python/mxnet/kvstore_server.py).

The reference launches dedicated server processes (DMLC_ROLE=server) that
sit in a loop applying optimizer updates pushed by workers. The TPU-native
dist design is SYMMETRIC SPMD (see DistKVStore): every worker applies the
identical update to the identically-aggregated gradient, so there is no
separate server role to run. This module keeps the reference's API shape
so launch scripts that branch on the role keep working:

- ``KVStoreServer(kv).run()`` — registers the optimizer controller and
  returns immediately (there is nothing to serve);
- ``_init_kvstore_server_module()`` — the reference's process entry; here
  it logs the design note and returns.
"""
from __future__ import annotations

import logging
import os
import pickle

from ..config import get_env

__all__ = ["KVStoreServer"]


class KVStoreServer(object):
    """ref kvstore_server.py KVStoreServer."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.init_logging()

    def init_logging(self):
        self._verbose = get_env("MXTPU_KVSTORE_DEBUG")

    def _controller(self):
        """ref server_controller: head-0 commands (optimizer blob, sync
        mode). Commands apply to THIS process's store — the former server
        work (aggregate + update) runs here."""
        def server_controller(cmd_id, cmd_body):
            if cmd_id == 0:          # kController: optimizer payload
                optimizer = pickle.loads(cmd_body)
                self.kvstore.set_optimizer(optimizer)
            elif cmd_id == 1:        # kSetMultiPrecision
                pass                 # fused step handles master weights
            elif cmd_id == 2:        # kStopServer
                pass
            elif cmd_id == 3:        # kSyncMode
                pass                 # always sync (DistKVStore docstring)
            else:
                logging.warning("server got unknown command %s", cmd_id)
        return server_controller

    def run(self):
        """ref KVStoreServer.run — blocks in the reference; symmetric SPMD
        has no server loop, so this registers the controller and returns."""
        _ = self._controller()
        logging.info(
            "kvstore server role is a no-op in the symmetric SPMD design: "
            "updates run on every worker against the collectively-reduced "
            "gradient (see kvstore/kvstore.py DistKVStore)")


def _init_kvstore_server_module():
    """ref kvstore_server.py module entry (invoked when DMLC_ROLE=server)."""
    # DMLC_ROLE (reference launcher) wins; MXTPU_ROLE rides the typed
    # registry like every other framework knob (R002)
    role = os.environ.get("DMLC_ROLE") or get_env("MXTPU_ROLE")
    if role == "server":
        from . import kvstore as _kv
        server = KVStoreServer(_kv.KVStore("local"))
        server.run()
