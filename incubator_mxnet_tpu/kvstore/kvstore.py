"""KVStore implementations (ref python/mxnet/kvstore/kvstore.py:54,
src/kvstore/kvstore_local.h:69, src/kvstore/kvstore_dist.h:44)."""
from __future__ import annotations

import pickle

from .. import optimizer as opt
from .. import telemetry
from ..telemetry import flightrec, spans
from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = ["KVStore", "KVStoreBase", "create", "LocalKVStore", "DistKVStore",
           "DistAsyncKVStore"]

# Parameter-traffic observability: bytes through push/pull, labeled by the
# store type ('local', 'dist_sync', 'dist_async', ... — a bounded label).
# rate(push_bytes) vs the step rate is the gradient-traffic share of a run.
_PUSH_BYTES = telemetry.counter(
    "mxtpu_kvstore_push_bytes_total",
    "Payload bytes pushed into the kvstore (per-device values summed).",
    ("store",))
_PULL_BYTES = telemetry.counter(
    "mxtpu_kvstore_pull_bytes_total",
    "Payload bytes pulled out of the kvstore (per-device outs summed).",
    ("store",))


def _nbytes(v):
    """Best-effort payload size of one pushed/pulled value (NDArray, raw
    array, sparse, or a per-device list of them)."""
    if isinstance(v, (list, tuple)):
        return sum(_nbytes(x) for x in v)
    try:
        import numpy as onp
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is None or dtype is None:
            return 0
        n = 1
        for d in shape:
            n *= int(d)
        return n * onp.dtype(str(dtype)).itemsize
    except Exception:
        return 0


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


class KVStoreBase:
    """Registry base for custom stores (ref python/mxnet/kvstore/base.py)."""

    kv_registry = {}

    @staticmethod
    def register(klass):
        KVStoreBase.kv_registry[klass.__name__.lower()] = klass
        return klass


class KVStore(KVStoreBase):
    """Abstract Push/Pull API (ref include/mxnet/kvstore.h:59-466)."""

    def __init__(self, name="local"):
        self.name = name
        self._updater = None
        self._optimizer = None
        self._data = {}
        self._compression = None

    # ---- core API ----------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            self._data[k] = v.copy()

    def push(self, key, value, priority=0):
        from ..ndarray.sparse import BaseSparseNDArray
        keys, values = self._normalize(key, value)
        nbytes = sum(_nbytes(v) for v in values)
        _PUSH_BYTES.inc(nbytes, store=self.name)
        flightrec.record("kv_push", store=self.name, keys=len(keys),
                         nbytes=nbytes)
        with spans.span("kvstore:push", store=self.name, nbytes=nbytes):
            for k, v in zip(keys, values):
                agg = self._aggregate(v, k)
                if self._updater is not None:
                    self._updater(_key_int(k), agg, self._data[k])
                else:
                    # the store holds dense values (pull invariants); a
                    # pushed sparse aggregate is densified at store time
                    if isinstance(agg, BaseSparseNDArray):
                        agg = agg.tostype("default")
                    self._data[k] = agg

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        # Sharing the jax.Array is snapshot-correct: jax.Arrays are immutable,
        # and every NDArray "in-place" op rebinds ._data rather than mutating
        # the buffer, so neither side can observe the other's later updates
        # (regression-tested in tests/test_parallel.py::test_kvstore_pull_isolation).
        keys, outs = self._normalize(key, out)
        with spans.span("kvstore:pull", store=self.name):
            pulled = 0
            for k, o in zip(keys, outs):
                for oo in (o if isinstance(o, (list, tuple)) else [o]):
                    oo._data = self._data[k]._data
                    pulled += _nbytes(oo)
        # one inc per pull (not per out tensor): the shared counter lock
        # must not be contended O(keys x devices) in the step hot path
        _PULL_BYTES.inc(pulled, store=self.name)
        flightrec.record("kv_pull", store=self.name, keys=len(keys),
                         nbytes=pulled)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (ref kvstore.h:262 PullRowSparse).

        With a RowSparseNDArray ``out``, fills (indices, values) for
        ``row_ids``; with a dense out or no row_ids, falls back to full pull."""
        from ..ndarray.sparse import RowSparseNDArray

        def _has_sparse(o):
            if isinstance(o, (list, tuple)):
                return any(_has_sparse(x) for x in o)
            return isinstance(o, RowSparseNDArray)

        if row_ids is None:
            if _has_sparse(out):
                raise ValueError(
                    "row_sparse_pull into a RowSparseNDArray requires "
                    "row_ids (ref kvstore.h PullRowSparse)")
            return self.pull(key, out, priority)
        keys, outs = self._normalize(key, out)
        for ki, (k, o) in enumerate(zip(keys, outs)):
            w = self._data[k]
            oo_list = o if isinstance(o, (list, tuple)) else [o]
            # row_ids pairs 1:1 with the outs of each key (multi-device
            # pattern), or a single spec is shared by all of them
            if isinstance(row_ids, (list, tuple)):
                if len(row_ids) == len(oo_list):
                    rid_list = list(row_ids)
                elif len(row_ids) == len(keys):
                    rid_list = [row_ids[ki]] * len(oo_list)
                else:
                    raise ValueError(
                        "row_ids (len %d) must pair with out (len %d) or "
                        "keys (len %d)" % (len(row_ids), len(oo_list),
                                           len(keys)))
            else:
                rid_list = [row_ids] * len(oo_list)
            for oo, rid in zip(oo_list, rid_list):
                rid_arr = rid._data if isinstance(rid, NDArray) else rid
                if isinstance(oo, RowSparseNDArray):
                    oo.indices = NDArray(rid_arr)
                    oo.data = NDArray(w._data[rid_arr])
                    oo._shape = tuple(w.shape)
                else:
                    oo._data = w._data

    # ---- optimizer ----------------------------------------------------
    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    @property
    def type(self):
        return self.name

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def set_gradient_compression(self, compression_params):
        from ..parallel.compression import GradientCompression
        self._compression = GradientCompression(**compression_params)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states without an optimizer"
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        nd.waitall()

    def set_server_profiler_state(self, state="stop", **config):
        """ref include/mxnet/kvstore.h:49 KVStoreServerProfilerCommand /
        tests/nightly/test_server_profiling.py: workers command the server's
        profiler. There is no server role here (symmetric SPMD — see
        DistKVStore), so the command drives THIS process's profiler, which
        is where all former server work (aggregation + updates) now runs."""
        from .. import profiler
        if config:
            profiler.set_config(**config)
        profiler.set_state(state)

    # ---- helpers -------------------------------------------------------
    def _normalize(self, key, value):
        if isinstance(key, (str, int)):
            return [key], [value]
        return list(key), list(value)

    def _aggregate(self, v, key):
        """Sum gradients from a list of per-device values (ref comm.h Reduce).

        Sparse values skip compression (the reference's 2-bit compression is
        dense-only: gradient_compression.cc rejects non-default stype)."""
        from ..ndarray.sparse import BaseSparseNDArray

        def compress(x, k):
            if self._compression is None or isinstance(x, BaseSparseNDArray):
                return x
            return self._compression.compress_decompress(x, k)

        if isinstance(v, (list, tuple)):
            v = [compress(x, (key, i)) for i, x in enumerate(v)]
            if len(v) == 1:
                return v[0]
            # sparse values first: sparse+sparse merges O(nnz); sparse+dense
            # densifies; dense+sparse would raise (NDArray.__add__ rejects it)
            v = sorted(v, key=lambda x: not isinstance(x, BaseSparseNDArray))
            acc = v[0]
            for x in v[1:]:
                acc = acc + x
            return acc
        return compress(v, key)


@KVStoreBase.register
class LocalKVStore(KVStore):
    """'local'/'device' store (ref src/kvstore/kvstore_local.h)."""


@KVStoreBase.register
class DistKVStore(KVStore):
    """'dist_sync'/'dist_device_sync' over jax.distributed
    (ref src/kvstore/kvstore_dist.h:44).

    The parameter-server is replaced by symmetric SPMD: ``init`` broadcasts
    rank-0's values to every worker, ``push`` all-reduces the gradient across
    processes (DCN collective via the jax.distributed runtime), and the
    optimizer — when set via ``set_optimizer`` — runs identically on every
    worker against the identical aggregated gradient, which is semantically
    the reference's server-side optimizer (kvstore_dist_server.h:179) without
    a server role. ``dist_async`` (kvstore_dist_server.h:349) maps to
    DistAsyncKVStore below — bounded-staleness local updates + periodic
    model averaging, the collective-design analog of Hogwild.

    This facade is the COMPATIBILITY dist path (host-bounce collectives;
    the in-program jit TrainStep is the performance path). A multi-key push
    batches all dense keys of the call into ONE host allgather per dtype
    (instead of O(keys) round trips — r2 verdict weak #4).

    Exercised as real multi-process in tests/test_dist.py (the reference's own
    strategy, tests/nightly/dist_sync_kvstore.py:36-81).
    """

    def __init__(self, name="dist_sync"):
        super().__init__(name)
        import jax
        self._rank = jax.process_index() if jax.process_count() > 1 else 0
        self._num_workers = jax.process_count()

    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            data = v._data if isinstance(v, NDArray) else v
            if self._num_workers > 1:
                from jax.experimental import multihost_utils
                import jax.numpy as jnp
                data = jnp.asarray(
                    multihost_utils.broadcast_one_to_all(data))
            self._data[k] = NDArray(data) if not isinstance(data, NDArray) \
                else data.copy()

    def push(self, key, value, priority=0):
        if self._num_workers <= 1:
            return super().push(key, value, priority)
        keys, values = self._normalize(key, value)
        nbytes = sum(_nbytes(v) for v in values)
        _PUSH_BYTES.inc(nbytes, store=self.name)
        flightrec.record("kv_push", store=self.name, keys=len(keys),
                         nbytes=nbytes)
        with spans.span("kvstore:push", store=self.name, nbytes=nbytes):
            return self._push_sync(keys, values)

    def _push_sync(self, keys, values):
        from ..ndarray.sparse import BaseSparseNDArray
        # local (per-process) aggregation + compression first
        local = [KVStore._aggregate(self, v, k)
                 for k, v in zip(keys, values)]
        dense = [i for i, a in enumerate(local)
                 if not isinstance(a, BaseSparseNDArray)]
        summed = self._cross_sum_batch([local[i] for i in dense])
        for i, s in zip(dense, summed):
            local[i] = s
        for k, agg in zip(keys, local):
            if isinstance(agg, BaseSparseNDArray):
                agg = self._cross_sum_single(agg)
            if self._updater is not None:
                self._updater(_key_int(k), agg, self._data[k])
            else:
                if isinstance(agg, BaseSparseNDArray):
                    agg = agg.tostype("default")
                self._data[k] = agg

    def _cross_sum_single(self, agg):
        from ..ndarray.sparse import BaseSparseNDArray
        if isinstance(agg, BaseSparseNDArray):
            agg = agg.tostype("default")
        return self._cross_sum_batch([agg])[0]

    def _cross_sum_batch(self, args):
        """ONE host allgather per dtype for a list of dense values —
        the batched replacement for per-key round trips. Accepts NDArrays
        or raw jax/numpy arrays; each output keeps its input's type."""
        if not args or self._num_workers <= 1:
            return list(args)
        import numpy as onp
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        out = list(args)
        from ..config import get_env
        bigarray = get_env("MXTPU_KVSTORE_BIGARRAY_BOUND")
        by_dtype = {}
        for i, a in enumerate(args):
            # big values get their own allgather so the batched concat
            # buffer's peak host memory stays bounded
            # (MXNET_KVSTORE_BIGARRAY_BOUND analog)
            key = (onp.dtype(a.dtype).name,
                   i if getattr(a, "size", 0) >= bigarray else -1)
            by_dtype.setdefault(key, []).append(i)
        for (dt, _big), idxs in sorted(by_dtype.items()):
            flats = [onp.asarray(args[i]._data if isinstance(args[i], NDArray)
                                 else args[i]).ravel() for i in idxs]
            sizes = [f.size for f in flats]
            cat = onp.concatenate(flats) if len(flats) > 1 else flats[0]
            # allgather lands on host; reduce there, upload once
            summed = multihost_utils.process_allgather(cat).sum(axis=0)
            off = 0
            for i, sz in zip(idxs, sizes):
                seg = summed[off: off + sz].reshape(args[i].shape)
                off += sz
                arr = jnp.asarray(seg.astype(dt))
                out[i] = NDArray(arr) if isinstance(args[i], NDArray) else arr
        return out

    def barrier(self):
        if self._num_workers > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mxtpu_kv_barrier")
        nd.waitall()

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers


@KVStoreBase.register
class DistAsyncKVStore(DistKVStore):
    """'dist_async' — the bounded-staleness analog of the reference's async
    parameter server (ref src/kvstore/kvstore_dist_server.h:346-360) and of
    P3's priority propagation (ref src/kvstore/p3store_dist.h:40).

    TPU-native translation (local-SGD / periodic averaging): ``push``
    applies the update LOCALLY with no cross-process traffic — workers run
    at their own pace exactly like Hogwild workers against a stale server
    copy. Every ``staleness`` pushes of a key (MXTPU_ASYNC_STALENESS,
    default 4), the workers average that key's parameters across processes,
    which BOUNDS the divergence the reference's async mode leaves unbounded
    — the established collective-design equivalent (local SGD converges
    under the same assumptions as bounded-staleness async PS).

    P3's overlap idea maps to priority-ordered propagation: at sync time,
    keys are averaged in DESCENDING push-priority order (the reference
    slices and schedules high-priority — later-layer — tensors first), one
    batched allgather per priority class.

    The averaging collective requires every worker to REACH it.  Two
    contracts make that deadlock-free:

    * **Lockstep (default)**: workers run identical push loops (the
      standard data-parallel pattern) — every worker hits the same
      staleness boundaries.
    * **Uneven shards**: call ``begin_epoch(local_steps)`` at each epoch
      start (all workers present — a matched point).  It allgathers the
      workers' PLANNED step counts and caps this epoch's staleness rounds
      at ``min_steps // staleness`` — a schedule every worker can honor
      even with k fewer local steps, because min_steps bounds them all.
      Pushes past the cap apply locally with no collective.  ``sync()`` at
      the epoch end (again all-present) folds the stragglers' tails back
      in.  ``Module.fit`` wires both calls automatically when the iterator
      advertises its length.
    """

    def __init__(self, name="dist_async", staleness=None):
        super().__init__(name)
        if staleness is None:
            from ..config import get_env
            staleness = get_env("MXTPU_ASYNC_STALENESS") or 4
        self._staleness = max(1, int(staleness))
        self._push_count = {}
        self._key_priority = {}
        self._round_budget = None   # per-key staleness rounds this epoch
        self._rounds_done = {}

    def begin_epoch(self, local_steps):
        """Agree on this epoch's collective schedule (call on ALL workers
        at the epoch start, with each worker's own planned push-step
        count). Returns the agreed number of staleness rounds per key."""
        local_steps = int(local_steps)
        if self._num_workers > 1:
            from jax.experimental import multihost_utils
            import numpy as onp
            counts = multihost_utils.process_allgather(
                onp.array([local_steps], dtype=onp.int64))
            min_steps = int(counts.min())
        else:
            min_steps = local_steps
        self._round_budget = min_steps // self._staleness
        self._rounds_done = {}
        self._push_count = {}
        return self._round_budget

    def _aggregate(self, v, key):
        # local-only aggregation: the cross-process traffic happens solely
        # in the periodic _average_batch (that IS the async semantics)
        return KVStore._aggregate(self, v, key)

    def push(self, key, value, priority=0):
        """``priority`` may be a scalar or a per-key sequence (batched
        multi-key pushes keep their per-layer P3 ordering)."""
        KVStore.push(self, key, value, priority)   # local apply ONLY
        keys, _ = self._normalize(key, value)
        if isinstance(priority, (list, tuple)):
            if len(priority) != len(keys):
                raise ValueError("priority list length %d != %d keys"
                                 % (len(priority), len(keys)))
            prios = list(priority)
        else:
            prios = [priority] * len(keys)
        due = []
        for k, pr in zip(keys, prios):
            # first push SETS the priority (negative per-layer priorities
            # must register, not be clamped by a default 0); later pushes
            # keep the highest seen
            self._key_priority[k] = pr if k not in self._key_priority \
                else max(self._key_priority[k], pr)
            c = self._push_count.get(k, 0) + 1
            self._push_count[k] = c
            if c >= self._staleness:
                # under an epoch schedule, only rounds every worker can
                # reach run the collective; the tail stays local
                if self._round_budget is not None and \
                        self._rounds_done.get(k, 0) >= self._round_budget:
                    continue
                self._rounds_done[k] = self._rounds_done.get(k, 0) + 1
                due.append(k)
        if due:
            self._sync_keys(due)

    def sync(self):
        """Force a full parameter average (epoch/checkpoint boundary —
        a matched point on every worker). Resets the epoch schedule."""
        self._round_budget = None
        self._rounds_done = {}
        self._sync_keys(list(self._data))

    def _sync_keys(self, keys):
        for k in keys:
            self._push_count[k] = 0
        if self._num_workers <= 1:
            return
        groups = {}
        for k in keys:
            groups.setdefault(self._key_priority.get(k, 0), []).append(k)
        for pr in sorted(groups, reverse=True):   # high priority first (P3)
            self._average_batch(groups[pr])

    def _average_batch(self, keys):
        """Priority-class average with P3 tensor SLICING (ref
        p3store_dist.h:40): values are cut into slices of at most
        MXTPU_P3_SLICE elements and averaged in bounded-size collectives,
        so the time until the first (highest-priority) parameters finish
        is set by the slice bound — a later-layer update is never stuck
        behind one giant low-layer tensor in a single monolithic
        collective. Slices of small tensors batch together up to the same
        bound (one collective each would be worse, the r2->r3 lesson)."""
        import numpy as onp
        from ..config import get_env
        bound = max(1, get_env("MXTPU_P3_SLICE"))
        inv = 1.0 / self._num_workers

        flats = {k: onp.asarray(self._data[k]._data
                                if isinstance(self._data[k], NDArray)
                                else self._data[k]).ravel() for k in keys}
        # (key, start, stop) slices, key order preserved within the class
        slices = []
        for k in keys:
            n = flats[k].size
            for s in range(0, max(n, 1), bound):
                slices.append((k, s, min(s + bound, n)))
        # bounded batches of slices, in order
        batch, batch_n, batches = [], 0, []
        for item in slices:
            ln = item[2] - item[1]
            if batch and batch_n + ln > bound:
                batches.append(batch)
                batch, batch_n = [], 0
            batch.append(item)
            batch_n += ln
        if batch:
            batches.append(batch)
        out = {k: onp.empty_like(flats[k]) for k in keys}
        for b in batches:
            vals = [flats[k][s:e] for k, s, e in b]
            summed = self._cross_sum_batch(vals)
            for (k, s, e), v in zip(b, summed):
                out[k][s:e] = onp.asarray(
                    v._data if isinstance(v, NDArray) else v) * inv
        import jax as _jax
        for k in keys:
            # pass the dtype explicitly: nd.array() would silently demote
            # float64 payloads to the float32 default — and 64-bit dtypes
            # additionally need the x64 scope or jnp truncates them anyway
            dt = str(self._data[k].dtype) if isinstance(self._data[k], NDArray) \
                else str(onp.asarray(self._data[k]).dtype)
            from ..base import enable_x64
            with enable_x64(dt in ("float64", "int64", "uint64")):
                self._data[k] = nd.array(
                    out[k].reshape(self._data[k].shape), dtype=dt)


def create(name="local"):
    """ref python/mxnet/kvstore/kvstore.py create / src/kvstore/kvstore.cc Create."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name.startswith("dist_async") or name == "dist_device_async":
        return DistAsyncKVStore(name)
    if name.startswith("dist"):
        return DistKVStore(name)
    return LocalKVStore(name)
