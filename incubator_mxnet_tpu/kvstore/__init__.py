"""KVStore — Push/Pull API facade (ref python/mxnet/kvstore/, src/kvstore/).

TPU-native design (SURVEY §2.5 north-star): the reference's device/NCCL/dist
synchronisation becomes *in-program* XLA collectives over the ICI mesh; this
module keeps the KVStore Push/Pull/PushPull/Broadcast API as a compatibility
facade. ``local``/``device`` hold one logical copy (SPMD replication is a
sharding decision); ``dist_*`` map onto jax.distributed multi-host psum.
"""
from .kvstore import KVStore, KVStoreBase, create, LocalKVStore, DistKVStore  # noqa
from . import kvstore_server  # noqa  (server-role API compat)
from .kvstore_server import KVStoreServer  # noqa
