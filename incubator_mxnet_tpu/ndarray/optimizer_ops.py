"""Optimizer-as-op surface (ref src/operator/optimizer_op.cc — the
reference runs EVERY optimizer step as one of these fused device ops;
optimizer.py dispatches to them).

Here the hot path is the fused TrainStep (updates compiled into the step
program with donated buffers — jit.py), but the eager op API is kept for
custom training loops and kvstore updaters. In-place contract matches the
reference: state args are mutated, the new weight is written to ``out``
(usually the weight itself).

All formulas are stated in the docstrings; wd/rescale/clip handling
follows optimizer_op.cc: grad' = clip(rescale_grad * grad) then wd folds
in where the reference folds it.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ndarray import NDArray

__all__ = [
    "sgd_update", "sgd_mom_update", "mp_sgd_update", "mp_sgd_mom_update",
    "nag_mom_update", "mp_nag_mom_update", "adam_update", "rmsprop_update",
    "rmspropalex_update", "ftrl_update", "ftml_update", "signsgd_update",
    "signum_update", "lamb_update_phase1", "lamb_update_phase2",
    "mp_lamb_update_phase1", "mp_lamb_update_phase2",
    "multi_sgd_update", "multi_sgd_mom_update", "multi_mp_sgd_update",
    "multi_mp_sgd_mom_update", "preloaded_multi_sgd_update",
    "preloaded_multi_sgd_mom_update", "preloaded_multi_mp_sgd_update",
    "preloaded_multi_mp_sgd_mom_update", "multi_sum_sq", "multi_lars",
    "all_finite", "multi_all_finite", "reset_arrays",
]


def _rg(grad, rescale_grad, clip_gradient):
    g = grad._data.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


def _write(out, weight, val):
    tgt = out if out is not None else weight
    tgt._data = val.astype(tgt._data.dtype)
    return tgt


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
               lazy_update=True, out=None):
    """w -= lr * (grad' + wd*w)   (ref sgd_update)."""
    g = _rg(grad, rescale_grad, clip_gradient)
    w = weight._data.astype(jnp.float32)
    return _write(out, weight, w - lr * (g + wd * w))


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                   out=None):
    """mom = momentum*mom - lr*(grad' + wd*w); w += mom (ref sgd_mom_update)."""
    g = _rg(grad, rescale_grad, clip_gradient)
    w = weight._data.astype(jnp.float32)
    m = momentum * mom._data - lr * (g + wd * w)
    mom._data = m.astype(mom._data.dtype)
    return _write(out, weight, w + m)


def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, out=None):
    """Multi-precision: math on fp32 master weight32, weight = cast back
    (ref mp_sgd_update)."""
    g = _rg(grad, rescale_grad, clip_gradient)
    w32 = weight32._data - lr * (g + wd * weight32._data)
    weight32._data = w32
    return _write(out, weight, w32)


def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, out=None):
    g = _rg(grad, rescale_grad, clip_gradient)
    m = momentum * mom._data - lr * (g + wd * weight32._data)
    mom._data = m
    w32 = weight32._data + m
    weight32._data = w32
    return _write(out, weight, w32)


def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None):
    """Nesterov: g'' = grad' + wd*w; mom = momentum*mom + g'';
    w -= lr*(g'' + momentum*mom)   (ref nag_mom_update)."""
    g = _rg(grad, rescale_grad, clip_gradient)
    w = weight._data.astype(jnp.float32)
    g = g + wd * w
    m = momentum * mom._data + g
    mom._data = m.astype(mom._data.dtype)
    return _write(out, weight, w - lr * (g + momentum * m))


def mp_nag_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, out=None):
    g = _rg(grad, rescale_grad, clip_gradient) + wd * weight32._data
    m = momentum * mom._data + g
    mom._data = m
    w32 = weight32._data - lr * (g + momentum * m)
    weight32._data = w32
    return _write(out, weight, w32)


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, out=None):
    """m=b1*m+(1-b1)*g'; v=b2*v+(1-b2)*g'^2; w -= lr*m/(sqrt(v)+eps) with
    g' = grad'+wd*w. NO bias correction inside the op — the python
    Optimizer passes the corrected lr, exactly as the reference splits it
    (ref adam_update)."""
    w = weight._data.astype(jnp.float32)
    g = _rg(grad, rescale_grad, clip_gradient) + wd * w
    m = beta1 * mean._data + (1 - beta1) * g
    v = beta2 * var._data + (1 - beta2) * g * g
    mean._data = m
    var._data = v
    return _write(out, weight, w - lr * m / (jnp.sqrt(v) + epsilon))


def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0,
                   out=None):
    """n = (1-g1)*g'^2 + g1*n; w -= lr*g'/sqrt(n+eps) (ref rmsprop_update)."""
    w = weight._data.astype(jnp.float32)
    g = _rg(grad, rescale_grad, clip_gradient) + wd * w
    nn = (1 - gamma1) * g * g + gamma1 * n._data
    n._data = nn
    new_w = w - lr * g / jnp.sqrt(nn + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return _write(out, weight, new_w)


def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, out=None):
    """Graves' centered RMSProp (ref rmspropalex_update):
    n=(1-g1)gr^2+g1*n; g=(1-g1)gr+g1*g; delta=g2*delta - lr*gr/sqrt(n-g^2+eps);
    w += delta."""
    w = weight._data.astype(jnp.float32)
    gr = _rg(grad, rescale_grad, clip_gradient) + wd * w
    nn = (1 - gamma1) * gr * gr + gamma1 * n._data
    gg = (1 - gamma1) * gr + gamma1 * g._data
    d = gamma2 * delta._data - lr * gr / jnp.sqrt(nn - gg * gg + epsilon)
    n._data, g._data, delta._data = nn, gg, d
    new_w = w + d
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return _write(out, weight, new_w)


def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, out=None):
    """FTRL-proximal (ref ftrl_update):
    z += g' - (sqrt(n+g'^2)-sqrt(n))/lr * w; n += g'^2;
    w = -(z - sign(z)*l1) / ((beta+sqrt(n))/lr + wd)  where |z|>l1 else 0."""
    w = weight._data.astype(jnp.float32)
    g = _rg(grad, rescale_grad, clip_gradient)
    new_n = n._data + g * g
    z._data = z._data + g - (jnp.sqrt(new_n) - jnp.sqrt(n._data)) / lr * w
    n._data = new_n
    new_w = jnp.where(
        jnp.abs(z._data) > lamda1,
        -(z._data - jnp.sign(z._data) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd),
        0.0)
    return _write(out, weight, new_w)


def ftml_update(weight, grad, d, v, z, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0, out=None):
    """FTML (ref ftml_update, Zheng & Kwok 2017)."""
    w = weight._data.astype(jnp.float32)
    g = _rg(grad, rescale_grad, clip_grad) + wd * w
    new_v = beta2 * v._data + (1 - beta2) * g * g
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d._data
    new_z = beta1 * z._data + (1 - beta1) * g - sigma * w
    v._data, d._data, z._data = new_v, d_t, new_z
    return _write(out, weight, -new_z / d_t)


def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None):
    """w -= lr*(sign(g') + wd*w) (ref signsgd_update)."""
    g = _rg(grad, rescale_grad, clip_gradient)
    w = weight._data.astype(jnp.float32)
    return _write(out, weight, w - lr * (jnp.sign(g) + wd * w))


def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, out=None):
    """mom = momentum*mom - (1-momentum)*(g' + wd*w);
    w = (1 - lr*wd_lh)*w + lr*sign(mom)   (ref signum_update)."""
    w = weight._data.astype(jnp.float32)
    g = _rg(grad, rescale_grad, clip_gradient) + wd * w
    m = momentum * mom._data - (1 - momentum) * g
    mom._data = m.astype(mom._data.dtype)
    return _write(out, weight, (1 - lr * wd_lh) * w + lr * jnp.sign(m))


def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, out=None):
    """LAMB phase 1 (ref lamb_update_phase1): returns the raw update
    direction m̂/(sqrt(v̂)+eps) + wd*w; phase 2 applies the layer-wise
    trust ratio."""
    w = weight._data.astype(jnp.float32)
    g = _rg(grad, rescale_grad, clip_gradient)
    m = beta1 * mean._data + (1 - beta1) * g
    v = beta2 * var._data + (1 - beta2) * g * g
    mean._data, var._data = m, v
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    upd = m / (jnp.sqrt(v) + epsilon) + wd * w
    res = NDArray(upd) if out is None else _write(out, None, upd)
    return res


def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0, out=None):
    """LAMB phase 2 (ref lamb_update_phase2): w -= lr * (r1/r2) * g with
    r1=||w|| (optionally clipped to bounds), r2=||g||; ratio 1 when either
    norm is 0."""
    w = weight._data.astype(jnp.float32)
    r1v = r1._data if isinstance(r1, NDArray) else jnp.asarray(r1)
    r2v = r2._data if isinstance(r2, NDArray) else jnp.asarray(r2)
    if lower_bound is not None and lower_bound > 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where((r1v > 0) & (r2v > 0), r1v / r2v, 1.0)
    return _write(out, weight, w - lr * ratio * g._data)


mp_lamb_update_phase1 = lamb_update_phase1   # master weights are the fp32 ones
mp_lamb_update_phase2 = lamb_update_phase2


def _multi(fn, weights, grads, states_list, lrs, wds, out=None, **kw):
    outs = out if out is not None else weights
    for i, (w, g) in enumerate(zip(weights, grads)):
        st = [s[i] for s in states_list]
        fn(w, g, *st, lrs[i], wd=wds[i], out=outs[i], **kw)
    return outs


def multi_sgd_update(weights, grads, lrs, wds, rescale_grad=1.0,
                     clip_gradient=-1.0, out=None):
    """ref multi_sgd_update: one call, many tensors."""
    return _multi(lambda w, g, lr, wd, out: sgd_update(
        w, g, lr, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient, out=out), weights, grads, [], lrs, wds,
        out=out)


def multi_sgd_mom_update(weights, grads, moms, lrs, wds, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0, out=None):
    return _multi(lambda w, g, m, lr, wd, out: sgd_mom_update(
        w, g, m, lr, momentum=momentum, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient, out=out), weights, grads, [moms],
        lrs, wds, out=out)


def multi_mp_sgd_update(weights, grads, weights32, lrs, wds, rescale_grad=1.0,
                        clip_gradient=-1.0, out=None):
    return _multi(lambda w, g, w32, lr, wd, out: mp_sgd_update(
        w, g, w32, lr, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient, out=out), weights, grads, [weights32],
        lrs, wds, out=out)


def multi_mp_sgd_mom_update(weights, grads, moms, weights32, lrs, wds,
                            momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0, out=None):
    return _multi(lambda w, g, m, w32, lr, wd, out: mp_sgd_mom_update(
        w, g, m, w32, lr, momentum=momentum, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient, out=out), weights, grads,
        [moms, weights32], lrs, wds, out=out)


def _as_list_scalars(arr):
    import numpy as onp
    return [float(x) for x in onp.asarray(
        arr._data if isinstance(arr, NDArray) else arr)]


def preloaded_multi_sgd_update(weights, grads, lrs, wds, **kw):
    """lrs/wds live on device as tensors (ref preloaded_multi_sgd_update)."""
    return multi_sgd_update(weights, grads, _as_list_scalars(lrs),
                            _as_list_scalars(wds), **kw)


def preloaded_multi_sgd_mom_update(weights, grads, moms, lrs, wds, **kw):
    return multi_sgd_mom_update(weights, grads, moms, _as_list_scalars(lrs),
                                _as_list_scalars(wds), **kw)


def preloaded_multi_mp_sgd_update(weights, grads, weights32, lrs, wds, **kw):
    return multi_mp_sgd_update(weights, grads, weights32,
                               _as_list_scalars(lrs), _as_list_scalars(wds),
                               **kw)


def preloaded_multi_mp_sgd_mom_update(weights, grads, moms, weights32, lrs,
                                      wds, **kw):
    return multi_mp_sgd_mom_update(weights, grads, moms, weights32,
                                   _as_list_scalars(lrs),
                                   _as_list_scalars(wds), **kw)


def multi_sum_sq(*arrays, num_arrays=None):
    """Per-array sum of squares, one (n,) result (ref multi_sum_sq — feeds
    multi_lars)."""
    arrs = arrays[:num_arrays] if num_arrays else arrays
    return NDArray(jnp.stack([jnp.sum(jnp.square(a._data.astype(jnp.float32)))
                              for a in arrs]))


def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001, eps=1e-8,
               rescale_grad=1.0, out=None):
    """LARS layer-wise lr adjustment (ref multi_lars):
    lr_i *= eta*||w||/(||g||*rescale + wd*||w|| + eps) when ||w||,||g|| > 0."""
    w_n = jnp.sqrt(weights_sum_sq._data)
    g_n = jnp.sqrt(grads_sum_sq._data) * rescale_grad
    ratio = eta * w_n / (g_n + wds._data * w_n + eps)
    new = jnp.where((w_n > 0) & (g_n > 0), lrs._data * ratio, lrs._data)
    if out is not None:
        out._data = new
        return out
    return NDArray(new)


def all_finite(data, init_output=True, out=None):
    """1.0 iff every element is finite (ref all_finite — AMP overflow
    check)."""
    ok = jnp.isfinite(data._data).all().astype(jnp.float32).reshape(1)
    if out is not None:
        out._data = ok if init_output else out._data * ok
        return out
    return NDArray(ok)


def multi_all_finite(*arrays, num_arrays=None, init_output=True, out=None):
    arrs = arrays[:num_arrays] if num_arrays else arrays
    ok = jnp.stack([jnp.isfinite(a._data).all() for a in arrs]) \
        .all().astype(jnp.float32).reshape(1)
    if out is not None:
        out._data = ok if init_output else out._data * ok
        return out
    return NDArray(ok)


def reset_arrays(*arrays, num_arrays=None):
    """Zero every array in place (ref reset_arrays — grad clearing)."""
    arrs = arrays[:num_arrays] if num_arrays else arrays
    for a in arrs:
        a._data = jnp.zeros_like(a._data)
