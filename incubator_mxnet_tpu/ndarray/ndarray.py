"""NDArray — eager tensor over jax.Array, plus the ``nd`` op namespace.

Reference parity: python/mxnet/ndarray/ndarray.py:220 (NDArray class),
src/ndarray/ndarray.cc (C++ NDArray), and the generated op namespace
(python/mxnet/ndarray/register.py:265). Operator-style ops (FullyConnected,
Convolution, BatchNorm, ...) mirror src/operator/nn/*.

TPU-native design: there is no dependency engine and no per-op kernels —
every op is a pure JAX function executed eagerly (XLA-compiled & cached by
PJRT). Async semantics come for free: jax.Array is a future-like buffer;
``wait_to_read`` maps to ``block_until_ready`` (ref engine WaitForVar,
include/mxnet/engine.h:229). Autograd taping hooks into ``_apply``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

from .. import autograd
from ..context import Context, current_context
from .. import base as _base

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange", "eye", "concat",
           "concatenate", "stack", "dot", "batch_dot", "waitall"]


def _ctx_put(data, ctx):
    if ctx is None:
        ctx = current_context()
    return jax.device_put(data, ctx.jax_device)


def _dtype_of(dtype, default=onp.float32):
    if dtype is None:
        return default
    return onp.dtype(dtype) if not isinstance(dtype, str) or dtype != "bfloat16" else jnp.bfloat16


class NDArray:
    """Eager tensor bound to a device context (ref ndarray.py:220)."""

    __slots__ = ("_data", "_ctx", "_in_graph", "_grad_req", "grad_buf", "__weakref__")
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        self._data = data if isinstance(data, jax.Array) else jnp.asarray(data)
        self._ctx = ctx
        self._in_graph = False
        self._grad_req = "write"
        self.grad_buf = None

    # ------------------------------------------------------------- basics
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        try:
            dev = list(self._data.devices())[0]
            plat = dev.platform
        except Exception:
            return current_context()
        if plat in ("tpu", "axon"):
            return Context("tpu", dev.id)
        if plat in ("gpu", "cuda", "rocm"):
            return Context("gpu", dev.id)
        return Context("cpu", dev.id)

    ctx = context

    @property
    def stype(self):
        return "default"  # sparse stypes: dense-only on TPU (SURVEY §7 hard part f)

    def __getstate__(self):
        # pickle as host numpy: crosses process boundaries (DataLoader
        # multiprocessing workers) without dragging device buffers along.
        # NB: a pickle round-trip (or deepcopy) lands on the DEFAULT device
        # — device placement is process-local state, not data
        return {"data": self.asnumpy()}

    def __setstate__(self, state):
        self._data = jnp.asarray(state["data"])
        self._ctx = None
        self._in_graph = False
        self._grad_req = "write"
        self.grad_buf = None

    def asnumpy(self):
        return onp.asarray(self._data)

    def asscalar(self):
        return self.asnumpy().item()

    def item(self):
        return self.asnumpy().item()

    def __float__(self):
        return float(self.asnumpy())

    def __int__(self):
        return int(self.asnumpy())

    def __bool__(self):
        return bool(self.asnumpy())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            onp.asarray(self._data), "x".join(str(s) for s in self.shape), self.context)

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def wait_to_read(self):
        """Block until the buffer is ready (≙ Engine::WaitForVar)."""
        self._data.block_until_ready()

    wait_to_write = wait_to_read

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate gradient buffer and mark for autograd (ref ndarray.py attach_grad)."""
        grad = NDArray(jnp.zeros_like(self._data), ctx=self._ctx)
        autograd.mark_variables([self], [grad], grad_req)

    @property
    def grad(self):
        return self.grad_buf

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph, train_mode)

    # ------------------------------------------------------------- movement
    def copy(self):
        # identity through _apply: gradients flow through copies (the
        # reference's _copy op is differentiable too)
        out = _apply(lambda x: jnp.array(x), self)
        out._ctx = self._ctx
        return out

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, other.context.jax_device)
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device), ctx=other)
        raise TypeError("copyto expects NDArray or Context")

    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device), ctx=ctx)

    as_in_ctx = as_in_context

    def astype(self, dtype, copy=True):
        return _apply(lambda x: x.astype(_np_dtype(dtype)), self)

    def as_nd_ndarray(self):
        return self

    @property
    def stype(self):
        return "default"

    def tostype(self, stype):
        """Convert to a storage type (ref ndarray.py cast_storage).

        Compiled programs never need this: row_sparse grads are an XLA
        scatter in the fused step (see ndarray/sparse.py). Conversion is
        eager (data-dependent nnz can't live under jit)."""
        if stype == "default":
            return self
        from . import sparse as _sp
        if stype == "row_sparse":
            return _sp.row_sparse_array(self)
        if stype == "csr":
            return _sp.csr_matrix(self)
        raise ValueError("unknown stype %r" % stype)

    # ------------------------------------------------------------- indexing
    @staticmethod
    def _key_past_int32(key):
        """Integer indices beyond int32 range need a scoped x64 enable —
        jax passes dynamic index scalars as int32 by default, which
        overflows on >2^31-element axes (the int64-tensor-size story)."""
        lim = 2 ** 31 - 1
        # NOTE: module-level `abs` is the nd operator — plain comparisons
        def big(v):
            return isinstance(v, int) and (v > lim or v < -lim)

        for k in key if isinstance(key, tuple) else (key,):
            if big(k):
                return True
            if isinstance(k, slice) and any(
                    big(v) for v in (k.start, k.stop, k.step)
                    if v is not None):
                return True
        return False

    def __getitem__(self, key):
        key = _index_fixup(key)
        if self._key_past_int32(key):
            with _base.enable_x64(True):
                return _apply(lambda x: x[key], self)
        return _apply(lambda x: x[key], self)

    def __setitem__(self, key, value):
        key = _index_fixup(key)
        if isinstance(value, NDArray):
            value = value._data
        if self._key_past_int32(key):
            with _base.enable_x64(True):
                self._data = self._data.at[key].set(value)
        else:
            self._data = self._data.at[key].set(value)

    def take(self, indices, axis=0, mode="clip"):
        from . import op as _op  # noqa
        return take(self, indices, axis=axis, mode=mode)

    # ------------------------------------------------------------- arithmetic
    def _binop(self, other, fn, reverse=False):
        if isinstance(other, NDArray):
            if reverse:
                return _apply(lambda b, a: fn(a, b), other, self)
            return _apply(fn, self, other)
        # scalar
        if reverse:
            return _apply(lambda a: fn(other, a), self)
        return _apply(lambda a: fn(a, other), self)

    def __add__(self, o): return self._binop(o, jnp.add)
    def __radd__(self, o): return self._binop(o, jnp.add, True)
    def __sub__(self, o): return self._binop(o, jnp.subtract)
    def __rsub__(self, o): return self._binop(o, jnp.subtract, True)
    def __mul__(self, o): return self._binop(o, jnp.multiply)
    def __rmul__(self, o): return self._binop(o, jnp.multiply, True)
    def __div__(self, o): return self._binop(o, jnp.divide)
    def __truediv__(self, o): return self._binop(o, jnp.divide)
    def __rtruediv__(self, o): return self._binop(o, jnp.divide, True)
    def __mod__(self, o): return self._binop(o, jnp.mod)
    def __rmod__(self, o): return self._binop(o, jnp.mod, True)
    def __pow__(self, o): return self._binop(o, jnp.power)
    def __rpow__(self, o): return self._binop(o, jnp.power, True)
    def __floordiv__(self, o): return self._binop(o, jnp.floor_divide)
    def __matmul__(self, o): return self._binop(o, jnp.matmul)

    def __iadd__(self, o):
        self._data = (self + o)._data
        return self

    def __isub__(self, o):
        self._data = (self - o)._data
        return self

    def __imul__(self, o):
        self._data = (self * o)._data
        return self

    def __itruediv__(self, o):
        self._data = (self / o)._data
        return self

    def __neg__(self): return _apply(jnp.negative, self)
    def __abs__(self): return _apply(jnp.abs, self)

    def __eq__(self, o): return self._binop(o, lambda a, b: (a == b).astype(a.dtype))
    def __ne__(self, o): return self._binop(o, lambda a, b: (a != b).astype(a.dtype))
    def __lt__(self, o): return self._binop(o, lambda a, b: (a < b).astype(a.dtype))
    def __le__(self, o): return self._binop(o, lambda a, b: (a <= b).astype(a.dtype))
    def __gt__(self, o): return self._binop(o, lambda a, b: (a > b).astype(a.dtype))
    def __ge__(self, o): return self._binop(o, lambda a, b: (a >= b).astype(a.dtype))

    def __hash__(self):
        return id(self)

    # ------------------------------------------------------------- shape ops
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if "shape" in kwargs:
            shape = kwargs["shape"]
            if isinstance(shape, int):
                shape = (shape,)
        new_shape = _mx_reshape(self.shape, tuple(shape))
        return _apply(lambda x: x.reshape(new_shape), self)

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def flatten(self):
        """MXNet Flatten: collapse all but first axis (ref tensor/matrix_op.cc)."""
        n = self.shape[0] if self.ndim > 0 else 1
        return _apply(lambda x: x.reshape(n, -1), self)

    @property
    def T(self):
        return _apply(jnp.transpose, self)

    def transpose(self, axes=None):
        return _apply(lambda x: jnp.transpose(x, axes), self)

    def swapaxes(self, dim1, dim2):
        return _apply(lambda x: jnp.swapaxes(x, dim1, dim2), self)

    def expand_dims(self, axis):
        return _apply(lambda x: jnp.expand_dims(x, axis), self)

    def squeeze(self, axis=None):
        return _apply(lambda x: jnp.squeeze(x, axis), self)

    def broadcast_to(self, shape):
        return _apply(lambda x: jnp.broadcast_to(x, shape), self)

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def tile(self, reps):
        return _apply(lambda x: jnp.tile(x, reps), self)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return split(self, num_outputs, axis, squeeze_axis)

    def slice(self, begin, end, step=None):
        return slice_op(self, begin, end, step)

    def slice_axis(self, axis, begin, end):
        return slice_axis(self, axis, begin, end)

    def pick(self, index, axis=-1, keepdims=False):
        return pick(self, index, axis, keepdims)

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        return one_hot(self, depth, on_value, off_value, dtype)

    # ------------------------------------------------------------- reductions
    def _reduce(self, fn, axis=None, keepdims=False):
        ax = _norm_axis(axis)
        return _apply(lambda x: fn(x, axis=ax, keepdims=keepdims), self)

    def sum(self, axis=None, keepdims=False, **kw): return self._reduce(jnp.sum, axis, keepdims)
    def mean(self, axis=None, keepdims=False, **kw): return self._reduce(jnp.mean, axis, keepdims)
    def max(self, axis=None, keepdims=False, **kw): return self._reduce(jnp.max, axis, keepdims)
    def min(self, axis=None, keepdims=False, **kw): return self._reduce(jnp.min, axis, keepdims)
    def prod(self, axis=None, keepdims=False, **kw): return self._reduce(jnp.prod, axis, keepdims)

    def _argreduce(self, jfn, axis, keepdims):
        # MXNet convention: float indices. Past 2^24 the float32 mantissa
        # can no longer hold exact indices (and jax's default int32 index
        # dtype wraps past 2^31) — large extents compute under a scoped
        # x64 enable and return float64 (the int64-tensor-size story,
        # ref USE_INT64_TENSOR_SIZE / tests/nightly/test_large_vector.py)
        extent = self.size if axis is None else self.shape[axis]
        if extent > (1 << 24):
            with _base.enable_x64(True):
                return _apply(lambda x: jfn(x, axis=axis, keepdims=keepdims)
                              .astype(onp.float64), self)
        return _apply(lambda x: jfn(x, axis=axis, keepdims=keepdims)
                      .astype(onp.float32), self)

    def argmax(self, axis=None, keepdims=False):
        return self._argreduce(jnp.argmax, axis, keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._argreduce(jnp.argmin, axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return norm(self, ord, axis, keepdims)

    def clip(self, a_min=None, a_max=None):
        return _apply(lambda x: jnp.clip(x, a_min, a_max), self)

    # unary math conveniences
    def abs(self): return _apply(jnp.abs, self)
    def exp(self): return _apply(jnp.exp, self)
    def log(self): return _apply(jnp.log, self)
    def sqrt(self): return _apply(jnp.sqrt, self)
    def square(self): return _apply(jnp.square, self)
    def sign(self): return _apply(jnp.sign, self)
    def round(self): return _apply(jnp.round, self)
    def floor(self): return _apply(jnp.floor, self)
    def ceil(self): return _apply(jnp.ceil, self)
    def sigmoid(self): return _apply(jax.nn.sigmoid, self)
    def tanh(self): return _apply(jnp.tanh, self)
    def relu(self): return _apply(jax.nn.relu, self)
    def softmax(self, axis=-1): return _apply(lambda x: jax.nn.softmax(x, axis=axis), self)
    def log_softmax(self, axis=-1): return _apply(lambda x: jax.nn.log_softmax(x, axis=axis), self)

    def dot(self, other):
        return dot(self, other)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return topk(self, axis, k, ret_typ, is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return sort(self, axis, is_ascend)

    def argsort(self, axis=-1, is_ascend=True):
        return argsort(self, axis, is_ascend)


# =================================================================== helpers

def _np_dtype(dtype):
    if dtype in ("bfloat16", jnp.bfloat16):
        return jnp.bfloat16
    return onp.dtype(dtype)


def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def _index_fixup(key):
    def fix(k):
        if isinstance(k, NDArray):
            return k._data
        return k
    if isinstance(key, tuple):
        return tuple(fix(k) for k in key)
    return fix(key)


def _mx_reshape(old, new):
    """MXNet reshape special codes: 0 = copy dim, -1 = infer, -2 = copy rest,
    -3 = merge two dims, -4 = split (ref tensor/matrix_op.cc Reshape)."""
    if -2 not in new and -3 not in new and -4 not in new:
        return tuple(old[i] if d == 0 else d for i, d in enumerate(new))
    out, i = [], 0
    it = iter(range(len(new)))
    j = 0
    while j < len(new):
        d = new[j]
        if d == 0:
            out.append(old[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1
        elif d == -2:
            out.extend(old[i:]); i = len(old)
        elif d == -3:
            out.append(old[i] * old[i + 1]); i += 2
        elif d == -4:
            d1, d2 = new[j + 1], new[j + 2]
            if d1 == -1:
                d1 = old[i] // d2
            if d2 == -1:
                d2 = old[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(d); i += 1
        j += 1
    return tuple(out)


def _apply(fn, *inputs):
    """Execute a pure JAX function on NDArray inputs, eagerly; tape if recording.

    This is the single choke point every op goes through — the TPU analog of
    Imperative::Invoke (src/imperative/imperative.cc:89). When the profiler
    runs with profile_imperative, every op is timed (synced) and aggregated
    — the per-op engine instrumentation of the reference's profiler.
    """
    from .. import profiler as _prof
    profiling = _prof.imperative_active()
    if profiling:
        # epoch-anchored monotonic us (NTP-step safe; profiler.now_us)
        t0 = _prof.now_us()
    data = [x._data for x in inputs]
    out = fn(*data)
    if profiling:
        name = getattr(fn, "__qualname__", None) or \
            getattr(fn, "__name__", "op")
        _prof.record_op(name, t0,
                        list(out) if isinstance(out, (tuple, list)) else [out])
    if isinstance(out, (tuple, list)):
        outs = [NDArray(o) for o in out]
        if autograd.is_recording():
            autograd._record_op(fn, inputs, outs)
        return outs if isinstance(out, list) else tuple(outs)
    res = NDArray(out)
    if autograd.is_recording():
        autograd._record_op(fn, inputs, [res])
    return res


def _to_nd(x, ctx=None, dtype=None):
    if isinstance(x, NDArray):
        return x
    return array(x, ctx=ctx, dtype=dtype)


# =================================================================== creation

def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        data = source_array._data
    elif isinstance(source_array, (list, tuple, int, float)) and dtype is None:
        # MXNet semantics: python containers default to float32
        data = onp.asarray(source_array, dtype=onp.float32)
    else:
        data = onp.asarray(source_array)
        if dtype is None and data.dtype == onp.float64:
            data = data.astype(onp.float32)
    if dtype is not None:
        data = jnp.asarray(data, dtype=_np_dtype(dtype))
    return NDArray(_ctx_put(data, ctx), ctx=ctx)


def zeros(shape, ctx=None, dtype="float32", **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_ctx_put(jnp.zeros(shape, _np_dtype(dtype)), ctx), ctx=ctx)


def ones(shape, ctx=None, dtype="float32", **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_ctx_put(jnp.ones(shape, _np_dtype(dtype)), ctx), ctx=ctx)


def full(shape, val, ctx=None, dtype="float32", **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_ctx_put(jnp.full(shape, val, _np_dtype(dtype)), ctx), ctx=ctx)


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx, dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = jnp.arange(start, stop, step, _np_dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return NDArray(_ctx_put(out, ctx), ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return NDArray(_ctx_put(jnp.eye(N, M if M else None, k, dtype=_np_dtype(dtype)), ctx), ctx=ctx)


def zeros_like(a):
    return _apply(jnp.zeros_like, a)


def ones_like(a):
    return _apply(jnp.ones_like, a)


def waitall():
    """Block until all launched work is done (≙ Engine::WaitForAll)."""
    try:
        (jax.device_put(0.0) + 0).block_until_ready()
        jax.effects_barrier()
    except Exception:
        pass


# =================================================================== op tables
# Unary ops: one-liner parity with src/operator/tensor/elemwise_unary_op_basic.cc
_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "round": jnp.round, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x), "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10, "log2": jnp.log2,
    "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "reciprocal": jnp.reciprocal, "negative": jnp.negative,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "sigmoid": jax.nn.sigmoid, "relu": jax.nn.relu,
    "softsign": jax.nn.soft_sign,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
}

_BINARY = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "modulo": jnp.mod, "power": jnp.power,
    "maximum": jnp.maximum, "minimum": jnp.minimum,
    "hypot": jnp.hypot, "arctan2": jnp.arctan2,
    "equal": lambda a, b: (a == b).astype(jnp.result_type(a, b)),
    "not_equal": lambda a, b: (a != b).astype(jnp.result_type(a, b)),
    "greater": lambda a, b: (a > b).astype(jnp.result_type(a, b)),
    "greater_equal": lambda a, b: (a >= b).astype(jnp.result_type(a, b)),
    "lesser": lambda a, b: (a < b).astype(jnp.result_type(a, b)),
    "lesser_equal": lambda a, b: (a <= b).astype(jnp.result_type(a, b)),
    "logical_and": lambda a, b: ((a != 0) & (b != 0)).astype(jnp.result_type(a, b)),
    "logical_or": lambda a, b: ((a != 0) | (b != 0)).astype(jnp.result_type(a, b)),
    "logical_xor": lambda a, b: ((a != 0) ^ (b != 0)).astype(jnp.result_type(a, b)),
}


def _make_unary(fn):
    def op(data, **kwargs):
        return _apply(fn, _to_nd(data))
    return op


def _make_binary(fn, name):
    def op(lhs, rhs, **kwargs):
        if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
            return _apply(fn, lhs, rhs)
        if isinstance(lhs, NDArray):
            return _apply(lambda a: fn(a, rhs), lhs)
        return _apply(lambda b: fn(lhs, b), rhs)
    op.__name__ = name
    return op


_g = globals()
for _name, _fn in _UNARY.items():
    _g[_name] = _make_unary(_fn)
    __all__.append(_name)
for _name, _fn in _BINARY.items():
    _g[_name] = _make_binary(_fn, _name)
    __all__.append(_name)
    # broadcast_* aliases (MXNet families map to the same XLA broadcasting op)
    _g["broadcast_" + _name] = _g[_name]
    __all__.append("broadcast_" + _name)

# extra broadcast family aliases used by MXNet code
broadcast_sub = _g["broadcast_subtract"]
broadcast_mul = _g["broadcast_multiply"]
broadcast_div = _g["broadcast_divide"]
broadcast_mod = _g["broadcast_modulo"]
broadcast_plus = _g["broadcast_add"]
broadcast_minus = _g["broadcast_subtract"]
__all__ += ["broadcast_sub", "broadcast_mul", "broadcast_div", "broadcast_mod",
            "broadcast_plus", "broadcast_minus", "mod",
            "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div"]
elemwise_add = _g["add"]
elemwise_sub = _g["subtract"]
elemwise_mul = _g["multiply"]
elemwise_div = _g["divide"]
mod = _g["modulo"]


# =================================================================== shape ops

def reshape(data, shape, **kwargs):
    return data.reshape(shape)


def reshape_like(lhs, rhs):
    return lhs.reshape(rhs.shape)


def flatten(data):
    return data.flatten()


def transpose(data, axes=None):
    return data.transpose(axes)


def swapaxes(data, dim1=0, dim2=1):
    return data.swapaxes(dim1, dim2)


SwapAxis = swapaxes


def expand_dims(data, axis):
    return data.expand_dims(axis)


def squeeze(data, axis=None):
    return data.squeeze(axis)


def broadcast_to(data, shape):
    return data.broadcast_to(shape)


def broadcast_like(lhs, rhs):
    return lhs.broadcast_to(rhs.shape)


def broadcast_axis(data, axis=None, size=None):
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    sizes = size if isinstance(size, (list, tuple)) else (size,)
    shape = list(data.shape)
    for a, s in zip(axes, sizes):
        shape[a] = s
    return data.broadcast_to(tuple(shape))


def tile(data, reps):
    return data.tile(reps)


def repeat(data, repeats, axis=None):
    return _apply(lambda x: jnp.repeat(x, repeats, axis=axis), data)


def pad(data, mode="constant", pad_width=None, constant_value=0):
    """ref src/operator/pad.cc — pad_width in MXNet flat (before,after)*ndim order."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return _apply(lambda x: jnp.pad(x, pw, mode="constant", constant_values=constant_value), data)
    return _apply(lambda x: jnp.pad(x, pw, mode=jmode), data)


def flip(data, axis):
    return _apply(lambda x: jnp.flip(x, axis), data)


reverse = flip


def concat(*data, dim=1, **kwargs):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    axis = kwargs.get("axis", dim)
    return _apply(lambda *xs: jnp.concatenate(xs, axis=axis), *data)


Concat = concat


def concatenate(arrays, axis=0):
    return concat(*arrays, dim=axis)


def stack(*data, axis=0, **kwargs):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _apply(lambda *xs: jnp.stack(xs, axis=axis), *data)


def split(data, num_outputs, axis=1, squeeze_axis=False):
    """ref src/operator/slice_channel.cc (SliceChannel)."""
    def fn(x):
        parts = jnp.split(x, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return parts
    out = _apply(fn, data)
    return out if num_outputs > 1 else out[0]


SliceChannel = split


def slice_op(data, begin, end, step=None):
    """ref src/operator/tensor/matrix_op.cc Slice."""
    nd_ = data.ndim
    begin = list(begin) + [None] * (nd_ - len(begin))
    end = list(end) + [None] * (nd_ - len(end))
    step = list(step) + [None] * (nd_ - len(step)) if step else [None] * nd_
    idx = tuple(builtins_slice(b, e, s) for b, e, s in zip(begin, end, step))
    return _apply(lambda x: x[idx], data)


builtins_slice = slice  # keep python builtin accessible


def slice_axis(data, axis, begin, end):
    idx = [builtins_slice(None)] * data.ndim
    if end is None or end == 0 and begin < 0:
        end = None
    idx[axis] = builtins_slice(begin, end)
    idx = tuple(idx)
    return _apply(lambda x: x[idx], data)


def slice_like(data, shape_like, axes=None):
    tgt = shape_like.shape
    idx = [builtins_slice(None)] * data.ndim
    axes_ = axes if axes is not None else range(data.ndim)
    for a in axes_:
        idx[a] = builtins_slice(0, tgt[a])
    idx = tuple(idx)
    return _apply(lambda x: x[idx], data)


# =================================================================== reductions

def _make_reduce(fn, name):
    def op(data, axis=None, keepdims=False, exclude=False, **kwargs):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            axs = (ax,) if isinstance(ax, int) else tuple(ax)
            ax = tuple(i for i in range(data.ndim) if i not in axs)
        return _apply(lambda x: fn(x, axis=ax, keepdims=keepdims), data)
    op.__name__ = name
    return op


sum = _make_reduce(jnp.sum, "sum")
mean = _make_reduce(jnp.mean, "mean")
prod = _make_reduce(jnp.prod, "prod")
nansum = _make_reduce(jnp.nansum, "nansum")
nanprod = _make_reduce(jnp.nanprod, "nanprod")
max = _make_reduce(jnp.max, "max")
min = _make_reduce(jnp.min, "min")
sum_axis = sum
max_axis = max
min_axis = min


def norm(data, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    def fn(x):
        if ord == 1:
            return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))
    return _apply(fn, data)


L2Normalization = None  # defined below


def argmax(data, axis=None, keepdims=False):
    return data.argmax(axis, keepdims)


def argmin(data, axis=None, keepdims=False):
    return data.argmin(axis, keepdims)


def clip(data, a_min, a_max):
    return data.clip(a_min, a_max)


def where(condition, x, y):
    return _apply(lambda c, a, b: jnp.where(c != 0, a, b), condition, x, y)


def maximum_scalar(data, scalar):
    return _apply(lambda x: jnp.maximum(x, scalar), data)


# =================================================================== linalg-ish

def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """MXNet dot: contract last axis of lhs with first axis of rhs
    (ref src/operator/tensor/dot-inl.h) — maps straight onto the MXU."""
    def fn(a, b):
        if transpose_a:
            a = jnp.transpose(a)
        if transpose_b:
            b = jnp.transpose(b)
        if a.ndim == 1 and b.ndim == 1:
            return jnp.dot(a, b)
        return jnp.tensordot(a, b, axes=1)
    return _apply(fn, lhs, rhs)


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """ref src/operator/tensor/dot-inl.h batch_dot → batched MXU matmul."""
    def fn(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return _apply(fn, lhs, rhs)


linalg_gemm2 = batch_dot


def khatri_rao(*args):
    def fn(*ms):
        out = ms[0]
        for m in ms[1:]:
            out = jnp.einsum("i...,j...->ij...", out, m).reshape(-1, out.shape[-1])
        return out
    return _apply(fn, *args)


# =================================================================== indexing ops

def take(a, indices, axis=0, mode="clip"):
    """ref src/operator/tensor/indexing_op.cc Take."""
    def fn(x, idx):
        i = idx.astype(jnp.int32)
        if mode == "clip":
            i = jnp.clip(i, 0, x.shape[axis] - 1)
        elif mode == "wrap":
            i = jnp.mod(i, x.shape[axis])
        return jnp.take(x, i, axis=axis)
    return _apply(fn, a, _to_nd(indices))


def Embedding(data, weight, input_dim=None, output_dim=None, dtype=None, sparse_grad=False, **kw):
    """ref src/operator/tensor/indexing_op.cc Embedding — gather rows."""
    return _apply(lambda idx, w: jnp.take(w, idx.astype(jnp.int32), axis=0), data, weight)


def gather_nd(data, indices):
    def fn(x, idx):
        idx = idx.astype(jnp.int32)
        return x[tuple(idx[i] for i in range(idx.shape[0]))]
    return _apply(fn, data, indices)


def scatter_nd(data, indices, shape):
    def fn(d, idx):
        idx = idx.astype(jnp.int32)
        out = jnp.zeros(shape, d.dtype)
        return out.at[tuple(idx[i] for i in range(idx.shape[0]))].set(d)
    return _apply(fn, data, indices)


def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    """ref src/operator/tensor/broadcast_reduce_op.h Pick."""
    def fn(x, idx):
        i = jnp.clip(idx.astype(jnp.int32), 0, x.shape[axis] - 1)
        picked = jnp.take_along_axis(x, jnp.expand_dims(i, axis), axis=axis)
        return picked if keepdims else jnp.squeeze(picked, axis=axis)
    return _apply(fn, data, _to_nd(index))


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    def fn(idx):
        oh = jax.nn.one_hot(idx.astype(jnp.int32), depth, dtype=_np_dtype(dtype))
        return oh * (on_value - off_value) + off_value
    return _apply(fn, _to_nd(indices))


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """ref src/operator/tensor/ordering_op-inl.h TopK."""
    def fn(x):
        xm = jnp.moveaxis(x, axis, -1)
        neg = xm if is_ascend else -xm
        vals, idxs = lax.top_k(-neg, k) if is_ascend else lax.top_k(xm, k)
        if is_ascend:
            vals = -vals
        vals = jnp.moveaxis(vals, -1, axis)
        idxs = jnp.moveaxis(idxs, -1, axis).astype(_np_dtype(dtype))
        if ret_typ == "value":
            return vals
        if ret_typ == "both":
            return (vals, idxs)
        return idxs
    return _apply(fn, data)


def sort(data, axis=-1, is_ascend=True):
    def fn(x):
        s = jnp.sort(x, axis=axis)
        return s if is_ascend else jnp.flip(s, axis=axis)
    return _apply(fn, data)


def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    def fn(x):
        s = jnp.argsort(x, axis=axis)
        if not is_ascend:
            s = jnp.flip(s, axis=axis)
        return s.astype(_np_dtype(dtype))
    return _apply(fn, data)


def shuffle(data):
    from . import random as _rnd
    def fn(x):
        return jax.random.permutation(_rnd._next_key(), x, axis=0)
    return _apply(fn, data)


def diag(data, k=0):
    return _apply(lambda x: jnp.diag(x, k) if x.ndim <= 2 else jnp.diagonal(x, k), data)


def cast(data, dtype):
    return data.astype(dtype)


Cast = cast


def amp_cast(data, dtype):
    """ref src/operator/tensor/amp_cast.cc — AMP-inserted cast."""
    return data.astype(dtype)


def amp_multicast(*data, num_outputs=None):
    dtypes = [d.dtype for d in data]
    widest = jnp.result_type(*dtypes)
    return [d.astype(widest) for d in data]


# =================================================================== neural ops
# Operator-style ops, parity with src/operator/nn/* — all lower to XLA HLO that
# the TPU compiler fuses onto MXU/VPU. Gluon layers call these.

def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False, flatten=True, **kw):
    """ref src/operator/nn/fully_connected.cc — y = x W^T + b (MXU matmul)."""
    def fn_b(x, w, b):
        xx = x.reshape(x.shape[0], -1) if flatten else x
        y = jnp.matmul(xx, w.T)
        return y + b
    def fn_nb(x, w):
        xx = x.reshape(x.shape[0], -1) if flatten else x
        return jnp.matmul(xx, w.T)
    if no_bias or bias is None:
        return _apply(fn_nb, data, weight)
    return _apply(fn_b, data, weight, bias)


def _tuple2(v):
    if v is None:
        return None
    if isinstance(v, int):
        return (v, v)
    return tuple(v)


def Convolution(data, weight, bias=None, kernel=None, stride=(1, 1), dilate=(1, 1),
                pad=(0, 0), num_filter=None, num_group=1, no_bias=False, layout="NCHW", **kw):
    """ref src/operator/nn/convolution-inl.h — lax.conv_general_dilated on MXU.

    API is NCHW like MXNet; XLA's TPU backend internally picks optimal layout.
    Supports 1D (NCW) and 2D (NCHW) and 3D (NCDHW) via kernel rank.
    """
    n = len(kernel)
    stride = tuple(stride)[:n] if stride else (1,) * n
    dilate = tuple(dilate)[:n] if dilate else (1,) * n
    pad_ = tuple(pad)[:n] if pad else (0,) * n
    if len(stride) < n: stride = stride + (1,) * (n - len(stride))
    if len(dilate) < n: dilate = dilate + (1,) * (n - len(dilate))
    if len(pad_) < n: pad_ = pad_ + (0,) * (n - len(pad_))
    spatial = "".join("DHW"[3 - n:][i] for i in range(n))
    dn_str = ("NC" + spatial, "OI" + spatial, "NC" + spatial)

    def conv(x, w):
        dn = lax.conv_dimension_numbers(x.shape, w.shape, dn_str)
        # bf16 operands accumulate in fp32 on the MXU natively; keeping the
        # output dtype == input dtype keeps the VJP dtype-consistent
        return lax.conv_general_dilated(
            x, w, window_strides=stride, padding=[(p, p) for p in pad_],
            rhs_dilation=dilate, dimension_numbers=dn, feature_group_count=num_group)

    if no_bias or bias is None:
        return _apply(conv, data, weight)

    def fn(x, w, b):
        y = conv(x, w)
        return y + b.reshape((1, -1) + (1,) * n)
    return _apply(fn, data, weight, bias)


def Deconvolution(data, weight, bias=None, kernel=None, stride=(1, 1), dilate=(1, 1),
                  pad=(0, 0), adj=(0, 0), num_filter=None, num_group=1, no_bias=False,
                  target_shape=None, **kw):
    """ref src/operator/nn/deconvolution-inl.h — transposed conv expressed as
    the gradient-of-conv: input dilation by stride + flipped kernel, which XLA
    lowers to the same MXU conv kernels as the forward pass."""
    n = len(kernel)
    stride = tuple(stride)[:n] if stride else (1,) * n
    if len(stride) < n:
        stride = stride + (1,) * (n - len(stride))
    dilate = tuple(dilate)[:n] if dilate else (1,) * n
    if len(dilate) < n:
        dilate = dilate + (1,) * (n - len(dilate))
    pad_ = tuple(pad)[:n] if pad else (0,) * n
    if len(pad_) < n:
        pad_ = pad_ + (0,) * (n - len(pad_))
    adj_ = tuple(adj)[:n] if adj else (0,) * n
    if len(adj_) < n:
        adj_ = adj_ + (0,) * (n - len(adj_))
    spatial = "".join("DHW"[3 - n:][i] for i in range(n))
    dn_str = ("NC" + spatial, "IO" + spatial, "NC" + spatial)

    def conv_t(x, w):
        dn = lax.conv_dimension_numbers(x.shape, w.shape, dn_str)
        w_flip = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        pads = [(d * (k - 1) - p, d * (k - 1) - p + a)
                for k, p, a, d in zip(kernel, pad_, adj_, dilate)]
        return lax.conv_general_dilated(
            x, w_flip, window_strides=(1,) * n, padding=pads,
            lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group)

    def fn(x, w, *maybe_b):
        y = conv_t(x, w)
        if maybe_b:
            y = y + maybe_b[0].reshape((1, -1) + (1,) * n)
        return y
    if no_bias or bias is None:
        return _apply(fn, data, weight)
    return _apply(fn, data, weight, bias)


def Activation(data, act_type="relu", **kw):
    """ref src/operator/nn/activation.cc."""
    fns = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
           "softrelu": jax.nn.softplus, "softsign": jax.nn.soft_sign,
           "log_sigmoid": jax.nn.log_sigmoid, "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x))}
    return _apply(fns[act_type], data)


def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
              upper_bound=0.334, **kw):
    """ref src/operator/leaky_relu.cc (leaky/prelu/elu/selu/gelu/rrelu)."""
    if act_type == "leaky":
        return _apply(lambda x: jnp.where(x >= 0, x, slope * x), data)
    if act_type == "elu":
        return _apply(lambda x: jnp.where(x >= 0, x, slope * jnp.expm1(x)), data)
    if act_type == "selu":
        return _apply(jax.nn.selu, data)
    if act_type == "gelu":
        return _apply(lambda x: jax.nn.gelu(x, approximate=False), data)
    if act_type == "prelu":
        def fn(x, g):
            gb = g.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 and g.ndim == 1 else g
            return jnp.where(x >= 0, x, gb * x)
        return _apply(fn, data, gamma)
    if act_type == "rrelu":
        s = (lower_bound + upper_bound) / 2.0
        return _apply(lambda x: jnp.where(x >= 0, x, s * x), data)
    raise ValueError(act_type)


def softmax(data, axis=-1, temperature=None, length=None, **kw):
    """ref src/operator/nn/softmax-inl.h."""
    def fn(x):
        xx = x / temperature if temperature else x
        return jax.nn.softmax(xx, axis=axis)
    if length is not None:
        def fnl(x, ln):
            xx = x / temperature if temperature else x
            mask = jnp.arange(x.shape[axis]) < jnp.expand_dims(ln.astype(jnp.int32), axis)
            xx = jnp.where(mask, xx, -jnp.inf)
            out = jax.nn.softmax(xx, axis=axis)
            return jnp.where(mask, out, 0.0)
        return _apply(fnl, data, length)
    return _apply(fn, data)


def log_softmax(data, axis=-1, temperature=None, **kw):
    def fn(x):
        xx = x / temperature if temperature else x
        return jax.nn.log_softmax(xx, axis=axis)
    return _apply(fn, data)


def softmin(data, axis=-1, **kw):
    return _apply(lambda x: jax.nn.softmax(-x, axis=axis), data)


def SoftmaxActivation(data, mode="instance"):
    axis = -1 if mode == "instance" else 1
    return softmax(data, axis=axis)


@functools.lru_cache(maxsize=64)
def _softmax_output_fn(grad_scale, ignore_label, use_ignore, normalization):
    """Custom-VJP op matching src/operator/softmax_output.cc: forward =
    softmax(data); backward = (softmax - one_hot(label)) * grad_scale,
    independent of the incoming head gradient (loss-layer semantics)."""

    @jax.custom_vjp
    def op(x, lbl):
        return jax.nn.softmax(x, axis=-1)

    def fwd(x, lbl):
        probs = jax.nn.softmax(x, axis=-1)
        return probs, (probs, lbl)

    def bwd(res, g):
        probs, lbl = res
        oh = jax.nn.one_hot(lbl.astype(jnp.int32), probs.shape[-1],
                            dtype=probs.dtype)
        grad = (probs - oh) * grad_scale
        if use_ignore:
            mask = (lbl != ignore_label).astype(probs.dtype)
            grad = grad * jnp.expand_dims(mask, -1)
        if normalization == "valid" and use_ignore:
            n = jnp.maximum(jnp.sum(lbl != ignore_label), 1).astype(probs.dtype)
            grad = grad / n
        elif normalization == "batch":
            grad = grad / probs.shape[0]
        return grad, None

    op.defvjp(fwd, bwd)
    return op


def SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1, use_ignore=False,
                  multi_output=False, preserve_shape=False, normalization="null",
                  out_grad=False, smooth_alpha=0.0, **kw):
    """ref src/operator/softmax_output.cc (loss-layer backward semantics)."""
    op = _softmax_output_fn(float(grad_scale), int(ignore_label), bool(use_ignore),
                            str(normalization))
    return _apply(op, data, label)


@functools.lru_cache(maxsize=16)
def _regression_output_fn(kind, grad_scale):
    """ref src/operator/regression_output.cc Linear/Logistic/MAE."""

    @jax.custom_vjp
    def op(x, lbl):
        return jax.nn.sigmoid(x) if kind == "logistic" else x

    def fwd(x, lbl):
        out = jax.nn.sigmoid(x) if kind == "logistic" else x
        return out, (out, lbl)

    def bwd(res, g):
        out, lbl = res
        lblr = lbl.reshape(out.shape)
        if kind == "mae":
            grad = jnp.sign(out - lblr) * grad_scale
        else:
            grad = (out - lblr) * grad_scale
        return grad, None

    op.defvjp(fwd, bwd)
    return op


def LinearRegressionOutput(data, label, grad_scale=1.0, **kw):
    return _apply(_regression_output_fn("linear", float(grad_scale)), data, label)


def LogisticRegressionOutput(data, label, grad_scale=1.0, **kw):
    return _apply(_regression_output_fn("logistic", float(grad_scale)), data, label)


def MAERegressionOutput(data, label, grad_scale=1.0, **kw):
    return _apply(_regression_output_fn("mae", float(grad_scale)), data, label)


def Pooling(data, kernel=None, pool_type="max", global_pool=False, stride=None,
            pad=None, pooling_convention="valid", count_include_pad=True, layout=None, **kw):
    """ref src/operator/nn/pooling.cc — lax.reduce_window on VPU."""
    nd_sp = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return _apply(lambda x: jnp.max(x, axis=axes, keepdims=True), data)
        return _apply(lambda x: jnp.mean(x, axis=axes, keepdims=True), data)
    kernel = tuple(kernel)
    stride = tuple(stride) if stride else (1,) * nd_sp
    pad = tuple(pad) if pad else (0,) * nd_sp
    dims = (1, 1) + kernel
    strides = (1, 1) + stride
    spad = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)

    if pooling_convention == "full":
        # ceil-mode: pad extra on the high side so last partial window counts
        extra = []
        for i in range(nd_sp):
            in_sz = data.shape[2 + i] + 2 * pad[i]
            rem = (in_sz - kernel[i]) % stride[i]
            extra.append((stride[i] - rem) % stride[i] if rem else 0)
        spad = ((0, 0), (0, 0)) + tuple((p, p + e) for p, e in zip(pad, extra))

    if pool_type == "max":
        def fn(x):
            # init must carry the operand dtype (an int python literal binds
            # as int32 and reduce_window rejects the mismatch for int8/int16)
            init = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                    else x.dtype.type(jnp.iinfo(x.dtype).min))
            return lax.reduce_window(x, init, lax.max, dims, strides, spad)
        return _apply(fn, data)
    if pool_type in ("avg", "sum"):
        def fn(x):
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, spad)
            if pool_type == "sum":
                return s
            if count_include_pad:
                denom = 1.0
                for k in kernel:
                    denom *= k
                return s / denom
            ones_ = jnp.ones_like(x)
            cnt = lax.reduce_window(ones_, 0.0, lax.add, dims, strides, spad)
            return s / cnt
        return _apply(fn, data)
    if pool_type == "lp":
        p = kw.get("p_value", 2)
        def fn(x):
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, spad)
            return s ** (1.0 / p)
        return _apply(fn, data)
    raise ValueError(pool_type)


def Dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False, **kw):
    """ref src/operator/nn/dropout-inl.h — jax.random bernoulli mask."""
    if not autograd.is_training() or p <= 0:
        return data
    from . import random as _rnd
    def fn(x):
        shape = list(x.shape)
        for a in axes or ():
            shape[a] = 1
        keep = 1.0 - p
        mask = jax.random.bernoulli(_rnd._next_key(), keep, tuple(shape)).astype(x.dtype)
        return x * mask / keep
    return _apply(fn, data)


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5, momentum=0.9,
              fix_gamma=False, use_global_stats=False, output_mean_var=False, axis=1,
              cudnn_off=False, **kw):
    """ref src/operator/nn/batch_norm.cc.

    Training mode computes batch statistics and UPDATES moving_mean/moving_var
    in place (matching MXNet's aux-state side effect); inference uses them.
    """
    training = autograd.is_training() and not use_global_stats
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(1 if i != axis else data.shape[axis] for i in range(data.ndim))

    if training:
        # side-effect on aux states: eager writes normally; collected (returned
        # as extra outputs) when tracing inside a compiled program
        from ..gluon import _functional

        def _stats(x, shift):
            # SHIFTED one-pass batch stats: E[(x-s)^2] - (E[x]-s)^2 in fp32,
            # s = running mean (a resident (C,) vector, so the broadcast
            # subtraction fuses and both reductions happen in a single read
            # of the activation — ~19% faster than two-pass mean/var on TPU,
            # which is bandwidth-bound here). In steady state s ~= m keeps
            # the subtraction free of catastrophic cancellation even when
            # |mean| >> std (the failure mode of naive E[x^2]-E[x]^2); for
            # the first steps after init (s=0) this degrades to the naive
            # form, which only loses precision for |mean|/std > ~1000 —
            # not reachable with standard inits. (A slice-derived shift was
            # tried and defeated XLA's fusion: 2112 vs 2568 img/s.)
            xf = x.astype(jnp.float32)
            s = lax.stop_gradient(shift.astype(jnp.float32)).reshape(bshape)
            m = jnp.mean(xf, axis=red_axes)
            d2 = jnp.mean(jnp.square(xf - s), axis=red_axes)
            v = d2 - jnp.square(m - s.reshape(m.shape))
            return m, jnp.maximum(v, 0.0)

        x = data._data
        mean_, var_ = _stats(x, moving_mean._data)
        new_mm = (momentum * moving_mean._data + (1 - momentum) * mean_).astype(moving_mean.dtype)
        new_mv = (momentum * moving_var._data + (1 - momentum) * var_).astype(moving_var.dtype)
        if _functional.in_functional_mode():
            _functional.collect_aux_update(moving_mean, new_mm)
            _functional.collect_aux_update(moving_var, new_mv)
        else:
            moving_mean._data = new_mm
            moving_var._data = new_mv

        def fn(x, g, b, mm):
            m, v = _stats(x, mm)
            m = m.reshape(bshape)
            v = v.reshape(bshape)
            gg = jnp.ones_like(g) if fix_gamma else g
            out = (x.astype(jnp.float32) - m) * lax.rsqrt(v + eps) \
                * gg.reshape(bshape) + b.reshape(bshape)
            return out.astype(x.dtype)
        return _apply(fn, data, gamma, beta, moving_mean)

    def fn(x, g, b, mm, mv):
        gg = jnp.ones_like(g) if fix_gamma else g
        scale = gg.reshape(bshape) * lax.rsqrt(mv.reshape(bshape) + eps)
        out = (x - mm.reshape(bshape)) * scale + b.reshape(bshape)
        return out.astype(x.dtype)
    return _apply(fn, data, gamma, beta, moving_mean, moving_var)


def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False, **kw):
    """ref src/operator/nn/layer_norm.cc — fused by XLA on TPU."""
    def fn(x, g, b):
        xf = x.astype(jnp.float32)
        m = jnp.mean(xf, axis=axis, keepdims=True)
        v = jnp.var(xf, axis=axis, keepdims=True)
        shp = [1] * x.ndim
        shp[axis if axis >= 0 else x.ndim + axis] = x.shape[axis]
        out = (xf - m) * lax.rsqrt(v + eps) * g.reshape(shp) + b.reshape(shp)
        return out.astype(x.dtype)
    return _apply(fn, data, gamma, beta)


def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5, **kw):
    """ref src/operator/nn/group_norm.cc (NCHW)."""
    def fn(x, g, b):
        n, c = x.shape[0], x.shape[1]
        rest = x.shape[2:]
        xf = x.astype(jnp.float32).reshape((n, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, xf.ndim))
        m = jnp.mean(xf, axis=axes, keepdims=True)
        v = jnp.var(xf, axis=axes, keepdims=True)
        xn = ((xf - m) * lax.rsqrt(v + eps)).reshape(x.shape)
        shp = (1, c) + (1,) * (x.ndim - 2)
        return (xn * g.reshape(shp) + b.reshape(shp)).astype(x.dtype)
    return _apply(fn, data, gamma, beta)


def InstanceNorm(data, gamma, beta, eps=1e-3, **kw):
    """ref src/operator/instance_norm.cc."""
    def fn(x, g, b):
        axes = tuple(range(2, x.ndim))
        m = jnp.mean(x, axis=axes, keepdims=True)
        v = jnp.var(x, axis=axes, keepdims=True)
        shp = (1, x.shape[1]) + (1,) * (x.ndim - 2)
        out = (x - m) * lax.rsqrt(v + eps) * g.reshape(shp) + b.reshape(shp)
        return out.astype(x.dtype)
    return _apply(fn, data, gamma, beta)


def L2Normalization(data, eps=1e-10, mode="instance"):
    """ref src/operator/l2_normalization.cc."""
    def fn(x):
        if mode == "instance":
            axes = tuple(range(1, x.ndim))
        elif mode == "channel":
            axes = (1,)
        else:  # spatial
            axes = tuple(range(2, x.ndim))
        nrm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
        return x / nrm
    return _apply(fn, data)


def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """ref src/operator/nn/lrn.cc — local response norm across channels."""
    def fn(x):
        sq = jnp.square(x)
        half = nsize // 2
        pad_sq = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (x.ndim - 2))
        acc = jnp.zeros_like(x)
        for i in range(nsize):
            acc = acc + lax.dynamic_slice_in_dim(pad_sq, i, x.shape[1], axis=1)
        return x / jnp.power(knorm + alpha * acc / nsize, beta)
    return _apply(fn, data)


def UpSampling(*data, scale=2, sample_type="nearest", num_args=1, **kw):
    """ref src/operator/upsampling.cc (nearest via repeat)."""
    x = data[0]
    def fn(x):
        return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    return _apply(fn, x)


def BilinearResize2D(data, height=None, width=None, scale_height=None, scale_width=None, **kw):
    """ref src/operator/contrib/bilinear_resize.cc → jax.image.resize."""
    def fn(x):
        h = height or int(x.shape[2] * scale_height)
        w = width or int(x.shape[3] * scale_width)
        return jax.image.resize(x, (x.shape[0], x.shape[1], h, w), method="linear")
    return _apply(fn, data)


def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    """ref src/operator/sequence_mask.cc (time-major by default)."""
    if not use_sequence_length or sequence_length is None:
        return data
    def fn(x, slen):
        T = x.shape[axis]
        pos = jnp.arange(T)
        shp = [1] * x.ndim
        shp[axis] = T
        pos = pos.reshape(shp)
        batch_axis = 1 - axis if axis in (0, 1) else 0
        lshp = [1] * x.ndim
        lshp[batch_axis] = x.shape[batch_axis]
        mask = pos < slen.astype(jnp.int32).reshape(lshp)
        return jnp.where(mask, x, value)
    return _apply(fn, data, sequence_length)


SequenceMask = sequence_mask


def SequenceLast(data, sequence_length=None, use_sequence_length=False, axis=0):
    """ref src/operator/sequence_last.cc."""
    if not use_sequence_length or sequence_length is None:
        return slice_axis(data, axis, -1, None).squeeze(axis)
    def fn(x, slen):
        idx = (slen.astype(jnp.int32) - 1)
        xm = jnp.moveaxis(x, axis, 0)
        return xm[idx, jnp.arange(xm.shape[1])]
    return _apply(fn, data, sequence_length)


def SequenceReverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    """ref src/operator/sequence_reverse.cc."""
    if not use_sequence_length or sequence_length is None:
        return flip(data, axis)
    def fn(x, slen):
        T = x.shape[0]
        pos = jnp.arange(T)[:, None]
        ln = slen.astype(jnp.int32)[None, :]
        rev_idx = jnp.where(pos < ln, ln - 1 - pos, pos)
        return jnp.take_along_axis(x, rev_idx.reshape((T, x.shape[1]) + (1,) * (x.ndim - 2)), axis=0)
    return _apply(fn, data, sequence_length)


def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """ref src/operator/make_loss.cc."""
    return data * grad_scale if grad_scale != 1.0 else data


def BlockGrad(data):
    """ref src/operator/tensor/elemwise_unary_op_basic.cc BlockGrad."""
    return _apply(lax.stop_gradient, data)


stop_gradient = BlockGrad


def identity(data):
    return _apply(lambda x: x, data)


def moments(data, axes=None, keepdims=False):
    ax = _norm_axis(axes)
    return _apply(lambda x: (jnp.mean(x, axis=ax, keepdims=keepdims),
                             jnp.var(x, axis=ax, keepdims=keepdims)), data)


def CTCLoss(data, label, data_lengths=None, label_lengths=None,
            use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    """ref src/operator/nn/ctc_loss.cc — forward-backward in log space via scan."""
    from ..ops.ctc import ctc_loss as _ctc
    def fn(x, lbl, *rest):
        dl = rest[0] if use_data_lengths else None
        ll = rest[1] if use_label_lengths and len(rest) > 1 else (
            rest[0] if use_label_lengths else None)
        return _ctc(x, lbl, dl, ll, blank_label)
    args = [data, label]
    if use_data_lengths and data_lengths is not None:
        args.append(data_lengths)
    if use_label_lengths and label_lengths is not None:
        args.append(label_lengths)
    return _apply(fn, *args)


ctc_loss = CTCLoss


# =================================================================== loading
def save(fname, data):
    """Save dict/list of NDArray in the reference's binary list format
    (ref src/ndarray/ndarray.cc:1841-1849) — files are interchangeable with
    upstream MXNet ``.params`` checkpoints. See serialization.py."""
    from . import serialization
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        arrays = [d.asnumpy() for d in data]
        names = []
    else:
        names = list(data.keys())
        arrays = [data[k].asnumpy() for k in names]
    serialization.save_ndarray_list(fname, arrays, names)


def load(fname):
    """Load a ``.params`` file (reference binary format, with npz fallback
    for files written by older versions of this package)."""
    from . import serialization
    if serialization.is_ndarray_list_file(fname):
        arrays, names = serialization.load_ndarray_list(fname)
        if names:
            return {k: array(v) for k, v in zip(names, arrays)}
        return [array(v) for v in arrays]
    with open(fname, "rb") as fh:
        if fh.read(2) != b"PK":  # not an npz archive either
            raise ValueError(
                "%s is neither a binary NDArray list file (magic 0x112) nor "
                "an .npz archive" % fname)
    with onp.load(fname, allow_pickle=False) as f:
        fmt = str(f["__mx_format__"]) if "__mx_format__" in f else "dict"
        items = {k: array(f[k]) for k in f.files if k != "__mx_format__"}
    if fmt == "list":
        return [items[str(i)] for i in range(len(items))]
    return items


def smooth_l1(data, scalar=1.0, **kw):
    """ref tensor/elemwise_unary_op.cc smooth_l1 (Huber with sigma=scalar)."""
    s2 = float(scalar) ** 2

    def fn(x):
        ax = jnp.abs(x)
        return jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)

    return _apply(fn, data)


def hard_sigmoid(data, alpha=0.2, beta=0.5, **kw):
    """ref elemwise_unary_op: clip(alpha*x + beta, 0, 1)."""
    return _apply(lambda x: jnp.clip(alpha * x + beta, 0.0, 1.0), data)


def softmax_cross_entropy(data, label, **kw):
    """ref loss_binary_op.cc softmax_cross_entropy — summed batch loss."""

    def fn(x, y):
        logp = jax.nn.log_softmax(x, axis=-1)
        picked = jnp.take_along_axis(logp, y.astype(jnp.int32)[:, None],
                                     axis=-1)
        return -jnp.sum(picked)

    return _apply(fn, data, _to_nd(label))


def digamma(data, **kw):
    """ref elemwise_unary_op psi/digamma."""
    import jax.scipy.special as jss
    return _apply(jss.digamma, data)


def khatri_rao(*args, **kw):
    """ref contrib/krprod.cc khatri_rao — column-wise Kronecker product."""

    def fn(*mats):
        out = mats[0]
        for m in mats[1:]:
            out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[-1])
        return out

    return _apply(fn, *args)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    """ref init_op linspace."""
    return NDArray(jnp.linspace(start, stop, int(num), endpoint=endpoint,
                                dtype=_np_dtype(dtype)))


def trace(data, offset=0, axis1=0, axis2=1, **kw):
    return _apply(lambda x: jnp.trace(x, offset, axis1, axis2), data)


def meshgrid(*arrays, indexing="xy"):
    outs = jnp.meshgrid(*[a._data for a in arrays], indexing=indexing)
    return [NDArray(o) for o in outs]


def unravel_index(data, shape=None, **kw):
    """ref ravel.cc unravel_index: flat ids -> (ndim, N) coordinates."""

    def fn(x):
        coords = jnp.unravel_index(x.astype(jnp.int32), shape)
        return jnp.stack(coords, axis=0)

    return _apply(fn, data)


def ravel_multi_index(data, shape=None, **kw):
    """ref ravel.cc ravel_multi_index: (ndim, N) coords -> flat ids."""

    def fn(x):
        idx = tuple(x[i].astype(jnp.int32) for i in range(x.shape[0]))
        return jnp.ravel_multi_index(idx, shape, mode="clip")

    return _apply(fn, data)


def multinomial(data, shape=1, get_prob=False, dtype="int32", **kw):
    """ref sample_multinomial_op — rows of probabilities -> samples."""
    from . import random as _rnd
    n = shape if isinstance(shape, int) else int(onp.prod(shape))

    def fn(p, key):
        logits = jnp.log(jnp.maximum(p, 1e-37))
        return jax.random.categorical(key, logits, axis=-1,
                                      shape=(n,) + p.shape[:-1]).T

    key = _rnd._next_key()
    out = _apply(lambda p: fn(p, key), data)
    return out.astype(dtype) if dtype != "int32" else out


def arange_like(data, start=0.0, step=1.0, axis=None, **kw):
    from .contrib import arange_like as _al
    return _al(data, start, step, axis)


__all__ += ["smooth_l1", "hard_sigmoid", "softmax_cross_entropy", "digamma",
            "khatri_rao", "linspace", "trace", "meshgrid", "unravel_index",
            "ravel_multi_index", "multinomial", "arange_like"]


def im2col(data, kernel, stride=None, dilate=None, pad=None):
    """ref src/operator/tensor/im2col.cc: (N,C,*spatial) -> sliding patches
    (N, C*prod(kernel), L). Lowered to lax.conv_general_dilated_patches —
    XLA's native patch extraction, MXU-layout friendly."""
    kernel = tuple(kernel)
    d = len(kernel)
    stride = tuple(stride) if stride else (1,) * d
    dilate = tuple(dilate) if dilate else (1,) * d
    pad = tuple(pad) if pad else (0,) * d

    def fn(x):
        out = lax.conv_general_dilated_patches(
            x, filter_shape=kernel, window_strides=stride,
            padding=[(p, p) for p in pad], rhs_dilation=dilate)
        return out.reshape(out.shape[0], out.shape[1], -1)
    return _apply(fn, data)


def col2im(data, output_size, kernel, stride=None, dilate=None, pad=None):
    """ref src/operator/tensor/im2col.cc col2im: scatter-add patches back to
    (N, C, *output_size) — computed as the exact linear transpose (jax.vjp)
    of im2col, which IS the reference's definition of the op."""
    kernel = tuple(kernel)
    output_size = tuple(output_size)
    d = len(kernel)
    stride = tuple(stride) if stride else (1,) * d
    dilate = tuple(dilate) if dilate else (1,) * d
    pad = tuple(pad) if pad else (0,) * d
    k_prod = 1
    for k in kernel:
        k_prod *= k

    def fn(col):
        N = col.shape[0]
        C = col.shape[1] // k_prod

        def fwd(img):
            out = lax.conv_general_dilated_patches(
                img, filter_shape=kernel, window_strides=stride,
                padding=[(p, p) for p in pad], rhs_dilation=dilate)
            return out.reshape(out.shape[0], out.shape[1], -1)

        import jax as _jax
        _, vjp = _jax.vjp(fwd, jnp.zeros((N, C) + output_size, col.dtype))
        return vjp(col)[0]
    return _apply(fn, data)


__all__ += ["im2col", "col2im"]


def add_n(*args, **kw):
    """Sum of all inputs (ref tensor/elemwise_sum.cc add_n)."""
    import functools
    import operator
    return _apply(lambda *xs: functools.reduce(operator.add, xs), *args)


def batch_take(a, indices):
    """out[i] = a[i, indices[i]] (ref tensor/indexing_op.cc batch_take)."""
    def fn(x, idx):
        return jnp.take_along_axis(
            x, idx.astype(jnp.int32).reshape(-1, 1), axis=1)[:, 0]
    return _apply(fn, a, _to_nd(indices))


def depth_to_space(data, block_size):
    """(N, C*b^2, H, W) -> (N, C, H*b, W*b) (ref tensor/matrix_op.cc
    depth_to_space, DCR order)."""
    b = block_size

    def fn(x):
        N, C, H, W = x.shape
        c = C // (b * b)
        y = x.reshape(N, b, b, c, H, W)
        y = y.transpose(0, 3, 4, 1, 5, 2)
        return y.reshape(N, c, H * b, W * b)
    return _apply(fn, data)


def space_to_depth(data, block_size):
    """(N, C, H*b, W*b) -> (N, C*b^2, H, W), inverse of depth_to_space."""
    b = block_size

    def fn(x):
        N, C, Hb, Wb = x.shape
        H, W = Hb // b, Wb // b
        y = x.reshape(N, C, H, b, W, b)
        y = y.transpose(0, 3, 5, 1, 2, 4)
        return y.reshape(N, C * b * b, H, W)
    return _apply(fn, data)


def shape_array(data):
    """Shape as a TRUE int64 array (ref tensor/matrix_op.cc shape_array) —
    created under a scoped x64 enable so dims past 2^31 don't truncate to
    int32 (jax's default without jax_enable_x64)."""
    with _base.enable_x64(True):
        return NDArray(jnp.asarray(data.shape, jnp.int64))


def size_array(data):
    """Element count as a (1,) TRUE int64 array (ref size_array; see
    shape_array for the x64 scoping)."""
    with _base.enable_x64(True):
        return NDArray(jnp.asarray([data.size], jnp.int64))


def argmax_channel(data):
    """argmax over axis 1 (ref broadcast_reduce_op_index.cc argmax_channel)."""
    return _apply(lambda x: jnp.argmax(x, axis=1).astype(x.dtype), data)


def cast_storage(data, stype):
    """dense <-> row_sparse/csr conversion (ref tensor/cast_storage.cc);
    delegates to the sparse storage classes (nd.sparse)."""
    return data.tostype(stype)


def IdentityAttachKLSparseReg(data, sparseness_target=0.1, penalty=0.001,
                              momentum=0.9, **kw):
    """ref plugin sparse-reg op: identity forward; the KL sparseness
    penalty contributed to the backward is not replicated (document-level
    parity — penalty scheduling belongs in the loss here)."""
    return _apply(lambda x: x, data)


__all__ += ["add_n", "batch_take", "depth_to_space", "space_to_depth",
            "shape_array", "size_array", "argmax_channel", "cast_storage",
            "IdentityAttachKLSparseReg"]


def Correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Cost volume between two feature maps (ref src/operator/correlation.cc
    CorrelationForward + correlation-inl.h shape inference; FlowNet).

    Output (N, D*D, top_h, top_w) with D = 2*(max_displacement//stride2)+1,
    top_h = ceil((H + 2*pad_size - 2*border)/stride1),
    border = max_displacement + (kernel_size-1)//2.  Channel
    tc = dy_idx*D + dx_idx holds, per output pixel, the sum over the
    kernel_size x kernel_size window and input channels of
    x1*x2_displaced (is_multiply) or |x1 - x2_displaced|, divided by
    kernel_size^2 * C — exactly the reference's sumelems normalization.
    The displacement/kernel loops are static and XLA-unrolled into fused
    strided-slice multiplies."""
    if kernel_size % 2 != 1:
        raise ValueError("kernel_size must be odd")
    gr = max_displacement // stride2
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    md = max_displacement

    def fn(x1, x2):
        N, C, H, W = x1.shape
        p = pad_size
        ph, pw = H + 2 * p, W + 2 * p
        top_h = -((2 * border - ph) // stride1)
        top_w = -((2 * border - pw) // stride1)
        if top_h < 1 or top_w < 1:
            raise ValueError("Correlation: input too small for "
                             "max_displacement/kernel_size")
        x1p = jnp.pad(x1, ((0, 0), (0, 0), (p, p), (p, p)))
        x2p = jnp.pad(x2, ((0, 0), (0, 0), (p, p), (p, p)))

        def tap(src, y0, x0):
            return src[:, :, y0: y0 + (top_h - 1) * stride1 + 1: stride1,
                       x0: x0 + (top_w - 1) * stride1 + 1: stride1]

        outs = []
        for dy in range(-gr, gr + 1):
            for dx in range(-gr, gr + 1):
                s2p, s2o = dy * stride2, dx * stride2
                acc = None
                for h in range(kernel_size):
                    for w in range(kernel_size):
                        a = tap(x1p, md + h, md + w)
                        b = tap(x2p, md + s2p + h, md + s2o + w)
                        t = a * b if is_multiply else jnp.abs(a - b)
                        t = t.sum(axis=1)
                        acc = t if acc is None else acc + t
                outs.append(acc / (kernel_size * kernel_size * C))
        return jnp.stack(outs, axis=1)
    return _apply(fn, data1, data2)


def Crop(*data, offset=(0, 0), h_w=(0, 0), center_crop=False, num_args=None,
         **kw):
    """Legacy crop op (ref src/operator/crop.cc): crop data[0] to h_w, or
    to data[1]'s spatial shape when two inputs are given."""
    x = data[0]
    if len(data) == 2:
        th, tw = data[1].shape[2], data[1].shape[3]
    else:
        th, tw = h_w
    H, W = x.shape[2], x.shape[3]
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = offset
    return _apply(lambda a: a[:, :, oy: oy + th, ox: ox + tw], x)


__all__ += ["Correlation", "Crop"]


def moveaxis(data, source, destination):
    """ref ndarray.py moveaxis."""
    return _apply(lambda x: jnp.moveaxis(x, source, destination), data)


def onehot_encode(indices, out):
    """ref ndarray.py onehot_encode (legacy): writes one-hot rows into out."""
    depth = out.shape[1]
    res = _apply(lambda i: jax.nn.one_hot(i.astype(jnp.int32), depth,
                                          dtype=out.dtype), indices)
    out._data = res._data
    return out


def true_divide(lhs, rhs):
    return divide(lhs, rhs)


def histogram(a, bins=10, range=None):
    """ref tensor/histogram.cc: returns (counts, bin_edges)."""
    import builtins
    rng = range if range is not None else (
        float(a.min().asscalar()), float(builtins.max(
            float(a.max().asscalar()),
            float(a.min().asscalar()) + 1e-6)))
    if isinstance(bins, NDArray):
        cnt, edges = jnp.histogram(a._data, bins=bins._data)
    else:
        cnt, edges = jnp.histogram(a._data, bins=bins, range=rng)
    return NDArray(cnt), NDArray(edges)


def split_v2(ary, indices_or_sections=1, axis=0, squeeze_axis=False):
    """ref matrix_op.cc split_v2: numpy-style sections OR index points."""
    sections = tuple(indices_or_sections) \
        if isinstance(indices_or_sections, (list, tuple)) \
        else indices_or_sections

    def go(x):
        parts = jnp.split(x, sections, axis=axis)
        if squeeze_axis:
            parts = [p.squeeze(axis) for p in parts]
        return parts
    return _apply(go, ary)


def from_numpy(ndarray_np, zero_copy=True):
    """ref ndarray.py from_numpy (dlpack family) — device_put is the copy."""
    return NDArray(jnp.asarray(ndarray_np))


def to_dlpack_for_read(data):
    """ref to_dlpack_for_read: export via the dlpack protocol. Returns the
    protocol-bearing object (modern consumers call __dlpack__ themselves —
    torch.from_dlpack / np.from_dlpack accept it directly)."""
    return data._data


def to_dlpack_for_write(data):
    """jax buffers are immutable; writable export is a host-copy contract."""
    return data._data


def from_dlpack(dlpack):
    import jax.dlpack as jdl
    return NDArray(jdl.from_dlpack(dlpack))


__all__ += ["moveaxis", "onehot_encode", "true_divide", "histogram",
            "split_v2", "from_numpy", "to_dlpack_for_read",
            "to_dlpack_for_write", "from_dlpack"]
