"""Binary NDArray list serialization, byte-compatible with the reference.

Implements the exact on-disk format of the reference's NDArray::Save/Load
(ref src/ndarray/ndarray.cc:1596-1868):

    uint64  kMXAPINDArrayListMagic (0x112)
    uint64  reserved (0)
    uint64  number of arrays
    per array (dense, V2):
        uint32  NDARRAY_V2_MAGIC (0xF993fac9)
        int32   storage type (0 = kDefaultStorage; ndarray.h:61-65)
        int32   ndim; int64 x ndim        (TShape, tuple.h:731-740)
        int32   dev_type; int32 dev_id    (Context::Save, base.h:157-160)
        int32   type_flag                 (mshadow base.h:334-346)
        raw little-endian buffer
    uint64  number of names
    per name: uint64 length; bytes

so ``.params`` files written here load in upstream MXNet and vice versa.
Also reads V1/legacy (magic = ndim, uint32 dims) and V3 (np-shape) records,
and row_sparse/CSR records (aux types/shapes/data per ndarray.cc:1654-1678).
"""
from __future__ import annotations

import struct

import numpy as onp

kMXAPINDArrayListMagic = 0x112
NDARRAY_V1_MAGIC = 0xF993FAC8
NDARRAY_V2_MAGIC = 0xF993FAC9
NDARRAY_V3_MAGIC = 0xF993FACA

# mshadow type flags (ref 3rdparty/mshadow/mshadow/base.h:334-346)
_FLAG2DTYPE = {
    0: onp.float32, 1: onp.float64, 2: onp.float16, 3: onp.uint8,
    4: onp.int32, 5: onp.int8, 6: onp.int64, 7: onp.bool_,
}
_DTYPE2FLAG = {onp.dtype(v): k for k, v in _FLAG2DTYPE.items()}
_BFLOAT16_FLAG = 12

kDefaultStorage = 0
kRowSparseStorage = 1
kCSRStorage = 2


def _write_shape(out, shape):
    out.append(struct.pack("<i", len(shape)))
    out.append(struct.pack("<%dq" % len(shape), *shape))


def _save_dense(out, arr):
    """One dense ndarray in V2 framing."""
    a = onp.ascontiguousarray(arr)
    if str(a.dtype) == "bfloat16":
        flag = _BFLOAT16_FLAG
    elif a.dtype in _DTYPE2FLAG:
        flag = _DTYPE2FLAG[a.dtype]
    else:
        a = a.astype(onp.float32)
        flag = 0
    out.append(struct.pack("<I", NDARRAY_V2_MAGIC))
    out.append(struct.pack("<i", kDefaultStorage))
    _write_shape(out, a.shape)
    out.append(struct.pack("<ii", 1, 0))  # Context: kCPU, dev_id 0
    out.append(struct.pack("<i", flag))
    out.append(a.tobytes())


def save_ndarray_list(fname, arrays, names):
    """Write arrays (list of numpy) + names in the reference list format."""
    out = [struct.pack("<QQ", kMXAPINDArrayListMagic, 0),
           struct.pack("<Q", len(arrays))]
    for a in arrays:
        _save_dense(out, a)
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode("utf-8")
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    with open(fname, "wb") as f:
        f.write(b"".join(out))


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def read(self, fmt):
        vals = struct.unpack_from("<" + fmt, self.buf, self.pos)
        self.pos += struct.calcsize("<" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def read_tuple(self, fmt):
        vals = struct.unpack_from("<" + fmt, self.buf, self.pos)
        self.pos += struct.calcsize("<" + fmt)
        return vals

    def read_bytes(self, n):
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def read_shape_i64(self):
        ndim = self.read("i")
        return self.read_tuple("%dq" % ndim) if ndim else ()

    def read_shape_u32(self, ndim):
        return self.read_tuple("%dI" % ndim) if ndim else ()


def _load_one(r):
    magic = r.read("I")
    if magic in (NDARRAY_V2_MAGIC, NDARRAY_V3_MAGIC):
        stype = r.read("i")
        nad = {kDefaultStorage: 0, kRowSparseStorage: 1, kCSRStorage: 2}[stype]
        storage_shape = r.read_shape_i64() if nad > 0 else None
        shape = r.read_shape_i64()
        if len(shape) == 0 and magic == NDARRAY_V2_MAGIC:
            return onp.zeros((), onp.float32)  # is_none() placeholder
        r.read("ii")  # context
        flag = r.read("i")
        aux = []
        if nad > 0:
            aux_meta = []
            for _ in range(nad):
                aflag = r.read("i")
                ashape = r.read_shape_i64()
                aux_meta.append((aflag, ashape))
        dtype, isize = _decode_flag(flag)
        data_shape = storage_shape if nad > 0 else shape
        n = int(onp.prod(data_shape)) if data_shape else 1
        data = onp.frombuffer(r.read_bytes(n * isize), dtype=dtype).reshape(
            data_shape).copy()
        if nad > 0:
            for aflag, ashape in aux_meta:
                adt, asz = _decode_flag(aflag)
                cnt = int(onp.prod(ashape)) if ashape else 1
                aux.append(onp.frombuffer(r.read_bytes(cnt * asz),
                                          dtype=adt).reshape(ashape).copy())
            return _densify(stype, shape, data, aux)
        return data
    # legacy V1 / raw-ndim framing (ndarray.cc LegacyLoad)
    if magic == NDARRAY_V1_MAGIC:
        shape = r.read_shape_i64()
    else:
        shape = r.read_shape_u32(magic)  # magic IS ndim in the oldest format
    if len(shape) == 0:
        return onp.zeros((), onp.float32)
    r.read("ii")
    flag = r.read("i")
    dtype, isize = _decode_flag(flag)
    n = int(onp.prod(shape))
    return onp.frombuffer(r.read_bytes(n * isize), dtype=dtype).reshape(
        shape).copy()


def _decode_flag(flag):
    if flag == _BFLOAT16_FLAG:
        try:
            import ml_dtypes
            return onp.dtype(ml_dtypes.bfloat16), 2
        except ImportError:
            return onp.dtype(onp.uint16), 2
    dt = onp.dtype(_FLAG2DTYPE[flag])
    return dt, dt.itemsize


def _densify(stype, shape, data, aux):
    """Materialize a sparse record densely (we load sparse files; our runtime
    representation converts via sparse.py when asked)."""
    out = onp.zeros(shape, dtype=data.dtype)
    if stype == kRowSparseStorage:
        idx = aux[0]
        if idx.size:
            out[idx] = data
    elif stype == kCSRStorage:
        indptr, indices = aux[0], aux[1]
        for i in range(shape[0]):
            lo, hi = int(indptr[i]), int(indptr[i + 1])
            out[i, indices[lo:hi]] = data[lo:hi]
    return out


def load_ndarray_list(fname):
    """Returns (list_of_numpy, list_of_names) from a reference .params file."""
    with open(fname, "rb") as f:
        buf = f.read()
    r = _Reader(buf)
    header, _reserved = r.read("QQ")
    if header != kMXAPINDArrayListMagic:
        raise ValueError("not an NDArray list file (bad magic 0x%x)" % header)
    n = r.read("Q")
    arrays = [_load_one(r) for _ in range(n)]
    n_names = r.read("Q")
    names = []
    for _ in range(n_names):
        ln = r.read("Q")
        names.append(r.read_bytes(ln).decode("utf-8"))
    return arrays, names


def is_ndarray_list_file(fname):
    try:
        with open(fname, "rb") as f:
            head = f.read(8)
        return len(head) == 8 and struct.unpack("<Q", head)[0] == \
            kMXAPINDArrayListMagic
    except OSError:
        return False
