"""Linear-algebra ops (ref src/operator/tensor/la_op.cc — potrf/gemm/trsm/...)."""
from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .ndarray import NDArray, _apply

__all__ = ["gemm", "gemm2", "potrf", "potri", "trsm", "trmm", "syrk", "gelqf",
           "sumlogdiag", "extractdiag", "makediag", "inverse", "det", "slogdet",
           "svd", "syevd", "extracttrian", "maketrian"]


def gemm(A, B, C, alpha=1.0, beta=1.0, transpose_a=False, transpose_b=False, axis=-2):
    def fn(a, b, c):
        aa = jnp.swapaxes(a, -1, -2) if transpose_a else a
        bb = jnp.swapaxes(b, -1, -2) if transpose_b else b
        return alpha * jnp.matmul(aa, bb) + beta * c
    return _apply(fn, A, B, C)


def gemm2(A, B, alpha=1.0, transpose_a=False, transpose_b=False, axis=-2):
    def fn(a, b):
        aa = jnp.swapaxes(a, -1, -2) if transpose_a else a
        bb = jnp.swapaxes(b, -1, -2) if transpose_b else b
        return alpha * jnp.matmul(aa, bb)
    return _apply(fn, A, B)


def potrf(A, lower=True):
    return _apply(lambda a: jnp.linalg.cholesky(a) if lower
                  else jnp.swapaxes(jnp.linalg.cholesky(a), -1, -2), A)


def potri(A, lower=True):
    """Inverse of the ORIGINAL matrix from its Cholesky factor (ref la_op.cc
    potri): lower factor L means B = L L^T, upper factor U means B = U^T U."""
    def fn(a):
        at = jnp.swapaxes(a, -1, -2)
        b = jnp.matmul(a, at) if lower else jnp.matmul(at, a)
        return jnp.linalg.inv(b)
    return _apply(fn, A)


def trsm(A, B, alpha=1.0, transpose=False, rightside=False, lower=True):
    def fn(a, b):
        aa = jnp.swapaxes(a, -1, -2) if transpose else a
        if rightside:
            x = jnp.swapaxes(jsl.solve_triangular(
                jnp.swapaxes(aa, -1, -2), jnp.swapaxes(b, -1, -2),
                lower=not lower if transpose else lower), -1, -2)
        else:
            x = jsl.solve_triangular(aa, b, lower=not lower if transpose else lower)
        return alpha * x
    return _apply(fn, A, B)


def trmm(A, B, alpha=1.0, transpose=False, rightside=False, lower=True):
    def fn(a, b):
        aa = jnp.swapaxes(a, -1, -2) if transpose else a
        return alpha * (jnp.matmul(b, aa) if rightside else jnp.matmul(aa, b))
    return _apply(fn, A, B)


def syrk(A, alpha=1.0, transpose=False):
    def fn(a):
        aa = jnp.swapaxes(a, -1, -2) if transpose else a
        return alpha * jnp.matmul(aa, jnp.swapaxes(aa, -1, -2))
    return _apply(fn, A)


def gelqf(A):
    def fn(a):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
        return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)
    return _apply(fn, A)


def sumlogdiag(A):
    return _apply(lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1), A)


def extractdiag(A, offset=0):
    return _apply(lambda a: jnp.diagonal(a, offset, axis1=-2, axis2=-1), A)


def makediag(A, offset=0):
    return _apply(lambda a: _mkdiag(a, offset), A)


def _mkdiag(a, offset):
    import jax
    n = a.shape[-1] + abs(offset)
    if a.ndim == 1:
        return jnp.diag(a, k=offset)
    flat = a.reshape((-1, a.shape[-1]))
    out = jax.vmap(lambda v: jnp.diag(v, k=offset))(flat)
    return out.reshape(a.shape[:-1] + (n, n))


def inverse(A):
    return _apply(jnp.linalg.inv, A)


def det(A):
    return _apply(jnp.linalg.det, A)


def slogdet(A):
    return _apply(lambda a: tuple(jnp.linalg.slogdet(a)), A)


def svd(A):
    return _apply(lambda a: tuple(jnp.linalg.svd(a, full_matrices=False)), A)


def syevd(A):
    """ref la_op.cc syevd: symmetric eigendecomposition (U, L)."""

    def fn(a):
        w, v = jnp.linalg.eigh(a)
        return v.swapaxes(-1, -2), w

    return _apply(fn, A)


def _tri_side(offset, lower):
    """ref la_op semantics: offset>0 selects the upper triangle, offset<0
    the lower; ``lower`` only decides at offset 0."""
    if offset > 0:
        return False
    if offset < 0:
        return True
    return lower


def _tri_indices(n, offset, lower):
    import numpy as onp
    return onp.tril_indices(n, offset) if _tri_side(offset, lower) \
        else onp.triu_indices(n, offset)


def extracttrian(A, offset=0, lower=True):
    """ref la_op.cc extracttrian: packed triangle of a square matrix."""
    i0, i1 = _tri_indices(A.shape[-1], offset, lower)
    return _apply(lambda a: a[..., i0, i1], A)


def maketrian(A, offset=0, lower=True):
    """ref la_op.cc maketrian: inverse of extracttrian."""
    import math
    k = A.shape[-1]
    # packed length of an n x n triangle at |offset| o is (n-o)(n-o+1)/2
    n = int((math.isqrt(8 * k + 1) - 1) // 2) + abs(offset)
    i0, i1 = _tri_indices(n, offset, lower)

    def fn(a):
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        return out.at[..., i0, i1].set(a)

    return _apply(fn, A)
