"""nd namespace — eager ops on NDArray (ref python/mxnet/ndarray/__init__.py)."""
from .ndarray import *  # noqa
from .ndarray import NDArray, _apply, _to_nd, _np_dtype  # noqa
from . import random  # noqa
from . import linalg  # noqa
from .ndarray import sum, max, min, mean, prod, sort, argsort, topk, norm, clip  # noqa
from .ndarray import (  # noqa
    reshape, reshape_like, flatten, transpose, swapaxes, expand_dims, squeeze,
    broadcast_to, broadcast_like, broadcast_axis, tile, repeat, pad, flip, reverse,
    split, slice_axis, slice_like, take, pick, one_hot, gather_nd, scatter_nd,
    where, cast, amp_cast, amp_multicast, diag, shuffle, identity, moments,
    zeros_like, ones_like, argmax, argmin,
    FullyConnected, Convolution, Deconvolution, Activation, LeakyReLU,
    softmax, log_softmax, softmin, SoftmaxActivation, SoftmaxOutput, Pooling,
    Dropout, BatchNorm, LayerNorm, GroupNorm, InstanceNorm, L2Normalization, LRN,
    UpSampling, BilinearResize2D, sequence_mask, SequenceMask, SequenceLast,
    SequenceReverse, make_loss, BlockGrad, stop_gradient, Embedding, CTCLoss,
    ctc_loss, save, load, Cast, Concat, SliceChannel, SwapAxis,
    elemwise_add, elemwise_sub, elemwise_mul, elemwise_div,
    LinearRegressionOutput, LogisticRegressionOutput, MAERegressionOutput,
)
from .ndarray import slice_op as slice  # noqa  (MXNet nd.slice)

# flat linalg_* aliases (ref src/operator/tensor/la_op.cc registers each op
# under BOTH mx.nd.linalg.<name> and the flat mx.nd.linalg_<name> —
# e.g. nd.linalg_gemm2 in the reference's pytorch-migration docs); the
# unified registry then mirrors them into mx.sym automatically
for _n in ("gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "sumlogdiag",
           "extractdiag", "makediag", "extracttrian", "maketrian", "syrk",
           "gelqf", "syevd", "det", "slogdet", "inverse", "svd"):
    if hasattr(linalg, _n):
        globals()["linalg_" + _n] = getattr(linalg, _n)
del _n
from . import contrib  # noqa  (control flow: foreach/while_loop/cond)
from . import sparse  # noqa  (row_sparse/csr storage types)


def Custom(*inputs, op_type, **kwargs):
    """Dispatch a registered custom op (ref mx.nd.Custom; operator.py)."""
    from ..operator import Custom as _custom
    return _custom(*inputs, op_type=op_type, **kwargs)
from .optimizer_ops import *  # noqa
from . import optimizer_ops as _optimizer_ops  # noqa
# legacy top-level CamelCase ops (ref mx.nd namespace: roi_pooling.cc,
# bilinear_sampler.cc, spatial_transformer.cc, batch_norm_v1.cc aliases)
from ..ops.detection import (  # noqa: E402
    roi_pooling as ROIPooling,
    bilinear_sampler as BilinearSampler,
    grid_generator as GridGenerator,
    spatial_transformer as SpatialTransformer,
)
from .ndarray import BatchNorm as BatchNorm_v1  # noqa: E402  (v1 ≡ modern here)
from .ndarray import Convolution as Convolution_v1  # noqa: E402
from .ndarray import Pooling as Pooling_v1  # noqa: E402
from .rnn_op import RNN, rnn_param_size  # noqa: E402
CuDNNBatchNorm = BatchNorm_v1  # ref cudnn_batch_norm.cc — backend alias here


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0, channels=3,
             mean=None, **kw):
    """ref ndarray.py imdecode (legacy C-API image decode) — delegates to
    the image module's decoder."""
    from ..image import imdecode as _imd
    return _imd(str_img, flag=1 if channels == 3 else 0)


def load_frombuffer(buf):
    """ref ndarray/utils.py load_frombuffer: deserialize from bytes."""
    import io as _io
    from . import serialization as _ser
    return _ser.load_buffer(buf) if hasattr(_ser, "load_buffer") else \
        _load_from_bytes(buf)


def _load_from_bytes(buf):
    import io as _io
    import numpy as _onp
    import zipfile
    bio = _io.BytesIO(buf)
    with _onp.load(bio, allow_pickle=False) as z:
        data = {k: z[k] for k in z.files}
    from .ndarray import NDArray
    import jax.numpy as _jnp
    out = {k: NDArray(_jnp.asarray(v)) for k, v in data.items()}
    if set(out) == {"__list_%d" % i for i in range(len(out))}:
        return [out["__list_%d" % i] for i in range(len(out))]
    return out
