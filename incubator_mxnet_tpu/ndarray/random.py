"""Random sampling ops (ref src/operator/random/sample_op.cc, python/mxnet/random.py).

TPU-native design: a global threefry PRNG key (jax.random) split per call —
the stateful-global-seed UX of MXNet over JAX's functional counter-based RNG,
which vectorises on the VPU with no sequential state.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as onp

from .ndarray import NDArray, _apply, _ctx_put, _np_dtype, _to_nd

__all__ = ["seed", "uniform", "normal", "randn", "randint", "exponential", "gamma",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "bernoulli", "shuffle"]


class _RngState(threading.local):
    def __init__(self):
        self.key = jax.random.PRNGKey(0)


_RNG = _RngState()


def seed(seed_state, ctx="all"):
    """ref python/mxnet/random.py:seed — reseed the global generator."""
    _RNG.key = jax.random.PRNGKey(int(seed_state))


def _next_key():
    # inside a compiled (hybridized/jitted) program, randomness must come from
    # the per-call key argument, not the global python-side state
    from ..gluon import _functional
    if _functional.in_functional_mode():
        return _functional.next_functional_key()
    _RNG.key, sub = jax.random.split(_RNG.key)
    return sub


def _copy_out(res, out=None):
    if out is not None:
        out._data = res._data
        return out
    return res


def _shape_of(shape, *arrs):
    if shape is None:
        for a in arrs:
            if isinstance(a, NDArray):
                return a.shape
        return (1,)
    if isinstance(shape, int):
        shape = (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    shp = _shape_of(shape, low, high)
    key = _next_key()
    if isinstance(low, NDArray) or isinstance(high, NDArray):
        low, high = _to_nd(low), _to_nd(high)
        def fn(lo, hi):
            u = jax.random.uniform(key, shp + lo.shape, _np_dtype(dtype))
            return lo + u * (hi - lo)
        return _apply(fn, low, high)
    data = jax.random.uniform(key, shp, _np_dtype(dtype), low, high)
    res = NDArray(_ctx_put(data, ctx), ctx=ctx)
    return _copy_out(res, out)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    shp = _shape_of(shape, loc, scale)
    key = _next_key()
    data = loc + scale * jax.random.normal(key, shp, _np_dtype(dtype))
    res = NDArray(_ctx_put(data, ctx), ctx=ctx)
    return _copy_out(res, out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None, **kw):
    return normal(loc, scale, shape or (1,), dtype, ctx)


def randint(low, high=None, shape=(1,), dtype="int32", ctx=None, out=None, **kw):
    if high is None:
        low, high = 0, low
    key = _next_key()
    data = jax.random.randint(key, _shape_of(shape), int(low), int(high), _np_dtype(dtype))
    return _copy_out(NDArray(_ctx_put(data, ctx), ctx=ctx), out)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    key = _next_key()
    data = scale * jax.random.exponential(key, _shape_of(shape, scale), _np_dtype(dtype))
    return _copy_out(NDArray(_ctx_put(data, ctx), ctx=ctx), out)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    key = _next_key()
    data = beta * jax.random.gamma(key, alpha, _shape_of(shape, alpha, beta), _np_dtype(dtype))
    return _copy_out(NDArray(_ctx_put(data, ctx), ctx=ctx), out)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    key = _next_key()
    data = jax.random.poisson(key, lam, _shape_of(shape, lam)).astype(_np_dtype(dtype))
    return _copy_out(NDArray(_ctx_put(data, ctx), ctx=ctx), out)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    key1, key2 = jax.random.split(_next_key())
    g = jax.random.gamma(key1, k, _shape_of(shape)) * (1 - p) / p
    data = jax.random.poisson(key2, g).astype(_np_dtype(dtype))
    return _copy_out(NDArray(_ctx_put(data, ctx), ctx=ctx), out)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype="float32",
                                  ctx=None, out=None, **kw):
    k = 1.0 / alpha
    p = k / (k + mu)
    return negative_binomial(k, p, shape, dtype, ctx, out)


def bernoulli(prob=None, logit=None, shape=None, dtype="float32", ctx=None, **kw):
    key = _next_key()
    if prob is None:
        prob = jax.nn.sigmoid(logit._data if isinstance(logit, NDArray) else logit)
    if isinstance(prob, NDArray):
        prob = prob._data
    data = jax.random.bernoulli(key, prob, _shape_of(shape) if shape else None)
    return NDArray(_ctx_put(data.astype(_np_dtype(dtype)), ctx), ctx=ctx)


def multinomial(data, shape=(1,), get_prob=False, dtype="int32", **kw):
    """ref src/operator/random/sample_multinomial_op.cc — sample from pmf rows."""
    key = _next_key()
    if isinstance(shape, int):
        shape = (shape,)
    n = 1
    for s in shape:
        n *= s
    def fn(p):
        logits = jnp.log(jnp.maximum(p, 1e-37))
        if p.ndim == 1:
            out = jax.random.categorical(key, logits, shape=(n,))
            return out.reshape(shape).astype(_np_dtype(dtype)) if shape != (1,) else out[0].astype(_np_dtype(dtype)).reshape(())
        out = jax.random.categorical(key, logits[:, None, :], axis=-1, shape=(p.shape[0], n))
        return out.reshape((p.shape[0],) + shape).astype(_np_dtype(dtype))
    return _apply(fn, data)


def shuffle(data, **kw):
    key = _next_key()
    return _apply(lambda x: jax.random.permutation(key, x, axis=0), data)
