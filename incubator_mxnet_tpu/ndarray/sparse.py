"""Sparse storage types — row_sparse and csr (ref include/mxnet/ndarray.h:61-65,
python/mxnet/ndarray/sparse.py).

TPU compatibility decision (SURVEY §7f): inside compiled programs, "row_sparse
gradients" are an XLA scatter — the VJP of the embedding gather IS the
reference's row_sparse grad, fused by the compiler with static shapes, so the
hot path needs no sparse storage class. These classes exist for the parts of
the API where sparse STORAGE (not compute) is the contract: kvstore
row_sparse_pull, optimizer lazy/sparse updates, IO interchange, and
`tostype`. They live on host+device as (indices, values) / (data, indices,
indptr) arrays; conversion to/from dense happens eagerly (data-dependent
shapes cannot live under jit).

dist_async-style delayed sparse aggregation is intentionally out of scope —
see DistKVStore's docstring.
"""
from __future__ import annotations

import numpy as onp

import jax.numpy as jnp

from .ndarray import NDArray, array as _dense_array, zeros as _dense_zeros

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "array", "zeros", "dot",
           "retain", "embedding_backward"]


class BaseSparseNDArray:
    stype = None

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def wait_to_read(self):
        self.data.wait_to_read()

    def __repr__(self):
        return "<%s %s @%s>" % (type(self).__name__, "x".join(map(str, self._shape)),
                                self.stype)


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values): values[i] is row indices[i] of the dense array
    (ref ndarray.h kRowSparseStorage). Indices are unique and sorted."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, NDArray) else _dense_array(data)
        self.indices = indices if isinstance(indices, NDArray) else \
            _dense_array(indices, dtype="int32")
        self._shape = tuple(shape)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            dense = jnp.zeros(self._shape, self.data._data.dtype)
            dense = dense.at[self.indices._data].set(self.data._data)
            return NDArray(dense)
        raise ValueError("cannot convert row_sparse to %r" % stype)

    def retain(self, row_ids):
        """Rows of self present in row_ids; absent rows drop (ref sparse_retain)."""
        row_ids = row_ids if isinstance(row_ids, NDArray) else \
            _dense_array(row_ids, dtype="int32")
        keep = jnp.isin(self.indices._data, row_ids._data)
        idx = onp.asarray(self.indices._data)[onp.asarray(keep)]
        vals = onp.asarray(self.data._data)[onp.asarray(keep)]
        return RowSparseNDArray(vals, idx, self._shape)

    def copy(self):
        return RowSparseNDArray(self.data.copy(), self.indices.copy(), self._shape)

    def __mul__(self, other):
        if isinstance(other, (int, float)):
            return RowSparseNDArray(self.data * other, self.indices, self._shape)
        return self.tostype("default") * other

    __rmul__ = __mul__

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            # O(nnz) merge: concatenate and reduce by unique row id — never
            # materializes the dense array (vocab x dim grads stay small)
            idx = onp.concatenate([onp.asarray(self.indices._data),
                                   onp.asarray(other.indices._data)])
            vals = onp.concatenate([onp.asarray(self.data._data),
                                    onp.asarray(other.data._data)])
            uniq, inv = onp.unique(idx, return_inverse=True)
            merged = onp.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
            onp.add.at(merged, inv, vals)
            return RowSparseNDArray(merged, uniq.astype("int32"), self._shape)
        return self.tostype("default") + other


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row (ref ndarray.h kCSRStorage): 2-D only."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self.data = data if isinstance(data, NDArray) else _dense_array(data)
        self.indices = indices if isinstance(indices, NDArray) else \
            _dense_array(indices, dtype="int32")
        self.indptr = indptr if isinstance(indptr, NDArray) else \
            _dense_array(indptr, dtype="int32")
        self._shape = tuple(shape)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            n_rows, _ = self._shape
            ptr = self.indptr._data
            # row id per nnz: count of indptr entries <= k (static nnz shape)
            nnz = self.data.shape[0]
            k = jnp.arange(nnz)
            rows = jnp.searchsorted(ptr[1:], k, side="right")
            dense = jnp.zeros(self._shape, self.data._data.dtype)
            dense = dense.at[rows, self.indices._data].set(self.data._data)
            return NDArray(dense)
        if stype == "row_sparse":
            return self.tostype("default").tostype("row_sparse")
        raise ValueError("cannot convert csr to %r" % stype)

    def __getitem__(self, i):
        # row slice (ref sparse.py CSRNDArray.__getitem__ for int keys)
        lo = int(self.indptr._data[i])
        hi = int(self.indptr._data[i + 1])
        row = onp.zeros((self._shape[1],), dtype=str(self.data.dtype))
        cols = onp.asarray(self.indices._data[lo:hi])
        row[cols] = onp.asarray(self.data._data[lo:hi])
        return _dense_array(row)


def _dense_to_row_sparse(arr):
    a = onp.asarray(arr._data if isinstance(arr, NDArray) else arr)
    nz = onp.where(a.reshape(a.shape[0], -1).any(axis=1))[0]
    return RowSparseNDArray(a[nz], nz.astype("int32"), a.shape)


def _dense_to_csr(arr):
    a = onp.asarray(arr._data if isinstance(arr, NDArray) else arr)
    assert a.ndim == 2, "csr is 2-D only"
    rows, cols = onp.nonzero(a)
    data = a[rows, cols]
    indptr = onp.zeros(a.shape[0] + 1, "int32")
    onp.add.at(indptr, rows + 1, 1)
    indptr = onp.cumsum(indptr)
    return CSRNDArray(data, cols.astype("int32"), indptr, a.shape)


# ------------------------------------------------------------ constructors
def row_sparse_array(arg, shape=None, ctx=None, dtype=None):
    """row_sparse_array((data, indices), shape=...) or from dense/another."""
    if isinstance(arg, RowSparseNDArray):
        return arg
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        if shape is None:
            raise ValueError("shape required with (data, indices)")
        return RowSparseNDArray(data, indices, shape)
    return _dense_to_row_sparse(arg if isinstance(arg, NDArray)
                                else _dense_array(arg, dtype=dtype))


def csr_matrix(arg, shape=None, ctx=None, dtype=None):
    """csr_matrix((data, indices, indptr), shape=...) or from dense."""
    if isinstance(arg, CSRNDArray):
        return arg
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        if shape is None:
            raise ValueError("shape required with (data, indices, indptr)")
        return CSRNDArray(data, indices, indptr, shape)
    return _dense_to_csr(arg if isinstance(arg, NDArray)
                         else _dense_array(arg, dtype=dtype))


def array(source_array, stype="default", **kwargs):
    if stype == "default":
        return _dense_array(source_array, **kwargs)
    if stype == "row_sparse":
        return row_sparse_array(source_array, **kwargs)
    if stype == "csr":
        return csr_matrix(source_array, **kwargs)
    raise ValueError("unknown stype %r" % stype)


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "default":
        return _dense_zeros(shape, dtype=dtype)
    if stype == "row_sparse":
        d = dtype or "float32"
        return RowSparseNDArray(onp.zeros((0,) + tuple(shape[1:]), d),
                                onp.zeros((0,), "int32"), shape)
    if stype == "csr":
        d = dtype or "float32"
        return CSRNDArray(onp.zeros((0,), d), onp.zeros((0,), "int32"),
                          onp.zeros((shape[0] + 1,), "int32"), shape)
    raise ValueError("unknown stype %r" % stype)


def retain(data, indices):
    """ref mx.nd.sparse.retain."""
    return data.retain(indices)


def dot(lhs, rhs, transpose_a=False):
    """csr @ dense (ref dot(csr, default) — the LibSVM linear-model path).

    O(nnz * k): gathers rhs rows per nonzero and scatter-adds into the
    output; the CSR matrix is never densified."""
    if isinstance(lhs, CSRNDArray):
        r = rhs._data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        data = lhs.data._data
        cols = lhs.indices._data
        nnz = data.shape[0]
        rows = jnp.searchsorted(lhs.indptr._data[1:], jnp.arange(nnz),
                                side="right")
        if transpose_a:
            # csr.T @ rhs: rhs indexed by ROW of the csr entry
            contrib = data[:, None] * r[rows]                # (nnz, k)
            out = jnp.zeros((lhs.shape[1],) + r.shape[1:], contrib.dtype)
            return NDArray(out.at[cols].add(contrib))
        contrib = data[:, None] * r[cols]                    # (nnz, k)
        out = jnp.zeros((lhs.shape[0],) + r.shape[1:], contrib.dtype)
        return NDArray(out.at[rows].add(contrib))
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return NDArray(lhs._data @ rhs._data)
    raise TypeError("sparse.dot supports (csr, dense)")


def embedding_backward(tokens, out_grad, vocab_size):
    """The embedding-gradient-as-row_sparse helper (ref sparse_grad=True on
    mx.nd.Embedding): rows = unique token ids, values = summed output grads.

    Inside TrainStep this is an XLA scatter (gather VJP) — use this only for
    eager/kvstore pipelines that want the sparse storage form.
    """
    tok = onp.asarray(tokens._data if isinstance(tokens, NDArray) else tokens
                      ).reshape(-1)
    og = onp.asarray(out_grad._data if isinstance(out_grad, NDArray)
                     else out_grad)
    og = og.reshape(-1, og.shape[-1])
    uniq, inv = onp.unique(tok, return_inverse=True)
    vals = onp.zeros((len(uniq), og.shape[-1]), og.dtype)
    onp.add.at(vals, inv, og)
    return RowSparseNDArray(vals, uniq.astype("int32"),
                            (vocab_size, og.shape[-1]))


def _sparse_elemwise(fn_name):
    def op(lhs, rhs):
        """Module-level elemwise op on sparse/dense operands (ref
        sparse.py add/subtract/multiply/divide): computes on dense values,
        returns sparse when sparsity is preserved (add/sub of same-pattern
        row_sparse), else dense."""
        from . import ndarray as _nd_mod
        l = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
        r = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
        return getattr(_nd_mod, fn_name)(l, r)
    op.__name__ = fn_name
    return op


add = _sparse_elemwise("add")
subtract = _sparse_elemwise("subtract")
multiply = _sparse_elemwise("multiply")
divide = _sparse_elemwise("divide")
