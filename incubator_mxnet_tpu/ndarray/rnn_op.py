"""Monolithic fused RNN op (ref src/operator/rnn-inl.h / rnn.cc — the
stateful cuDNN-backed `RNN` op the reference's gluon rnn_layer rides).

TPU-native: the packed flat parameter vector keeps the reference's cuDNN
layout (all weights layer-major then all biases — see _unpack), and the
recurrence is the same `lax.scan` lowering the gluon layer uses; under
jit the whole multi-layer stack compiles to one XLA while-loop program.
Layout is TNC, matching the reference op's requirement.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd as _ag
from .ndarray import NDArray, _apply, _to_nd

__all__ = ["RNN", "rnn_param_size"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}


def _dims(mode, input_size, state_size, num_layers, bidirectional):
    G = _GATES[mode]
    D = 2 if bidirectional else 1
    shapes = []      # (kind, layer, dir, shape) in PACKING ORDER: weights first
    for layer in range(num_layers):
        isz = input_size if layer == 0 else D * state_size
        for d in range(D):
            shapes.append(("wi", layer, d, (G * state_size, isz)))
            shapes.append(("wh", layer, d, (G * state_size, state_size)))
    for layer in range(num_layers):
        for d in range(D):
            shapes.append(("bi", layer, d, (G * state_size,)))
            shapes.append(("bh", layer, d, (G * state_size,)))
    return shapes


def rnn_param_size(mode, input_size, state_size, num_layers=1,
                   bidirectional=False):
    """Flat parameter count (ref rnn-inl.h GetRnnParamSize)."""
    total = 0
    for _, _, _, shp in _dims(mode, input_size, state_size, num_layers,
                              bidirectional):
        n = 1
        for s in shp:
            n *= s
        total += n
    return total


def _unpack(params, shapes):
    out = {}
    off = 0
    for kind, layer, d, shp in shapes:
        n = 1
        for s in shp:
            n *= s
        out[(kind, layer, d)] = params[off: off + n].reshape(shp)
        off += n
    return out, off


def RNN(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=False, **kw):
    """data (T, N, I); parameters flat packed vector; state (L*D, N, H);
    state_cell (lstm only). Returns output (T, N, D*H), or with
    state_outputs=True the [output, hy(, cy)] list (ref rnn.cc outputs)."""
    assert mode in _GATES, mode
    assert state_size, "state_size required"
    T, N, I = data.shape
    D = 2 if bidirectional else 1
    if state is None:   # cuDNN convention: absent initial state = zeros
        from .ndarray import zeros as _nd_zeros
        state = _nd_zeros((num_layers * D, N, state_size),
                          dtype=str(data.dtype))
    if state_cell is None and mode == "lstm":
        from .ndarray import zeros as _nd_zeros
        state_cell = _nd_zeros((num_layers * D, N, state_size),
                               dtype=str(data.dtype))
    shapes = _dims(mode, I, state_size, num_layers, bidirectional)
    act = "relu" if mode == "rnn_relu" else "tanh"
    has_cell = mode == "lstm"
    # Inter-layer dropout (ref rnn-inl.h: applied between stacked layers,
    # never after the last).  Training state and PRNG keys are resolved
    # EAGERLY here — fn below is replayed by autograd's vjp, so anything
    # read inside it must be a closure constant or gradients would be
    # computed for a different function than the forward pass.
    drop_keys = None
    if p > 0 and num_layers > 1 and _ag.is_training():
        from . import random as _rnd
        drop_keys = [_rnd._next_key() for _ in range(num_layers - 1)]

    def fn(x, params, h0, *maybe_c):
        from ..gluon.rnn.rnn_layer import _lstm_step, _gru_step, _rnn_step
        c0 = maybe_c[0] if maybe_c else None
        w, used = _unpack(params, shapes)
        out = x
        h_out, c_out = [], []
        for layer in range(num_layers):
            dir_outs = []
            for d in range(D):
                idx = layer * D + d
                seq = out if d == 0 else jnp.flip(out, 0)
                wi, wh = w[("wi", layer, d)], w[("wh", layer, d)]
                bi, bh = w[("bi", layer, d)], w[("bh", layer, d)]
                if has_cell:
                    def step(carry, x_t, _wi=wi, _wh=wh, _bi=bi, _bh=bh):
                        h, c = carry
                        h2, c2 = _lstm_step(h, c, x_t, _wi, _wh, _bi, _bh)
                        return (h2, c2), h2
                    (hT, cT), ys = lax.scan(step, (h0[idx], c0[idx]), seq)
                    c_out.append(cT)
                elif mode == "gru":
                    def step(h, x_t, _wi=wi, _wh=wh, _bi=bi, _bh=bh):
                        h2 = _gru_step(h, x_t, _wi, _wh, _bi, _bh)
                        return h2, h2
                    hT, ys = lax.scan(step, h0[idx], seq)
                else:
                    def step(h, x_t, _wi=wi, _wh=wh, _bi=bi, _bh=bh):
                        h2 = _rnn_step(h, x_t, _wi, _wh, _bi, _bh, act)
                        return h2, h2
                    hT, ys = lax.scan(step, h0[idx], seq)
                h_out.append(hT)
                if d == 1:
                    ys = jnp.flip(ys, 0)
                dir_outs.append(ys)
            out = dir_outs[0] if D == 1 else jnp.concatenate(dir_outs, -1)
            if drop_keys is not None and layer != num_layers - 1:
                keep = 1.0 - p
                mask = jax.random.bernoulli(
                    drop_keys[layer], keep, out.shape).astype(out.dtype)
                out = out * mask / keep
        hy = jnp.stack(h_out, 0)
        if has_cell:
            return out, hy, jnp.stack(c_out, 0)
        return out, hy

    args = [data, _to_nd(parameters), state] + ([state_cell] if has_cell else [])
    res = _apply(lambda *a: fn(*a), *args)
    if has_cell:
        out, hy, cy = res
        return [out, hy, cy] if state_outputs else out
    out, hy = res
    return [out, hy] if state_outputs else out
