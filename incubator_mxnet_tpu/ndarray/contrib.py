"""Control-flow operators: foreach / while_loop / cond.

TPU-native analog of the reference's control-flow ops
(ref src/operator/control_flow.cc:1089 _foreach, :1150 _while_loop,
:1211 _cond; frontend python/mxnet/ndarray/contrib.py:139,235,403).

Design (tpu-first, not a translation):
- Eager mode runs real Python loops — exactly the reference's own eager
  semantics — so the autograd tape records through loop bodies and
  gradients flow to closed-over parameters naturally.
- Traced mode (inside hybridize / TrainStep / jit) lowers to XLA-native
  structured control flow: foreach -> lax.scan, cond -> lax.cond, and
  while_loop -> a MASKED lax.scan over max_iterations steps. The masked
  scan (rather than lax.while_loop) keeps the op reverse-mode
  differentiable — XLA cannot differentiate a dynamic while — at the cost
  of always executing max_iterations steps; rows past the dynamic stop
  are zero-filled (the return signature matches the reference:
  (outputs, final_loop_vars)).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax import tree_util as jtu

from .ndarray import NDArray, _apply, _to_nd
from .. import autograd

__all__ = ["foreach", "while_loop", "cond", "isinf", "isnan", "isfinite"]


def _is_nd(v):
    return isinstance(v, NDArray)


def _flatten(tree):
    return jtu.tree_flatten(tree, is_leaf=_is_nd)


def _traced(leaves):
    from ..gluon import _functional
    if _functional.in_functional_mode():
        return True
    return any(isinstance(x._data, jax.core.Tracer) for x in leaves)


def _stack_trees(trees):
    """Stack a list of identically-structured NDArray trees along axis 0,
    through _apply so the autograd tape sees it."""
    leaves0, treedef = _flatten(trees[0])
    cols = [[_flatten(t)[0][i] for t in trees] for i in range(len(leaves0))]
    stacked = [_apply(lambda *ds: jnp.stack(ds, 0), *c) for c in cols]
    return jtu.tree_unflatten(treedef, stacked)


def foreach(body, data, init_states):
    """Loop body over dim 0 of data (ref ndarray/contrib.py:139).

    body(data_i, states) -> (out, new_states). Returns (outs, final_states)
    with outs stacked along a new axis 0. Lowers to lax.scan when traced.
    """
    data_leaves, data_def = _flatten(data)
    state_leaves, state_def = _flatten(init_states)
    if not data_leaves:
        raise ValueError("foreach needs at least one input array")
    n = data_leaves[0].shape[0]

    if not _traced(data_leaves + state_leaves):
        if n == 0:
            raise ValueError("foreach over zero-length data: outputs are "
                             "undefined in eager mode (shape unknown)")
        states = init_states
        outs = []
        for i in range(n):
            sl = jtu.tree_unflatten(data_def, [d[i] for d in data_leaves])
            out, states = body(sl, states)
            outs.append(out)
        return _stack_trees(outs), states

    out_def_box = []

    def scan_body(carry, xs):
        states = jtu.tree_unflatten(state_def, [NDArray(c) for c in carry])
        sl = jtu.tree_unflatten(data_def, [NDArray(x) for x in xs])
        out, new_states = body(sl, states)
        o_leaves, o_def = _flatten(out)
        s_leaves, _ = _flatten(new_states)
        out_def_box.clear()
        out_def_box.append(o_def)
        return [s._data for s in s_leaves], [o._data for o in o_leaves]

    carry0 = [s._data for s in state_leaves]
    xs = [d._data for d in data_leaves]
    carry_t, ys = lax.scan(scan_body, carry0, xs)
    outs = jtu.tree_unflatten(out_def_box[0], [NDArray(y) for y in ys])
    states = jtu.tree_unflatten(state_def, [NDArray(c) for c in carry_t])
    return outs, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """While loop (ref ndarray/contrib.py:235).

    cond(*loop_vars) -> scalar; func(*loop_vars) -> (step_output,
    new_loop_vars). Returns (outputs, final_loop_vars); outputs stacked
    along axis 0. Eager: exact number of executed steps. Traced:
    max_iterations is REQUIRED, outputs have shape[0] == max_iterations
    with rows past the dynamic stop zero-filled (masked-scan lowering,
    reverse-differentiable).
    """
    loop_vars = list(loop_vars)
    var_leaves, var_def = _flatten(loop_vars)

    if not _traced(var_leaves):
        outs = []
        steps = 0
        while (max_iterations is None or steps < max_iterations) and \
                bool(_to_nd(cond(*loop_vars)).asscalar()):
            step_out, new_vars = func(*loop_vars)
            loop_vars = list(new_vars)
            outs.append(step_out)
            steps += 1
        if not outs:
            raise ValueError("while_loop executed zero steps — outputs "
                             "undefined (reference raises here too)")
        return _stack_trees(outs), loop_vars

    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations when traced "
                         "(static shapes; see module docstring)")

    # shape-infer the step output so the masked branch can emit zeros
    def _step_datas(datas):
        vs = jtu.tree_unflatten(var_def, [NDArray(d) for d in datas])
        out, new_vars = func(*vs)
        o_leaves, o_def = _flatten(out)
        v_leaves, _ = _flatten(list(new_vars))
        return [o._data for o in o_leaves], [v._data for v in v_leaves], o_def

    datas0 = [v._data for v in var_leaves]
    out_def_box = []

    def _probe(ds):
        vs = jtu.tree_unflatten(var_def, [NDArray(d) for d in ds])
        out, _ = func(*vs)
        leaves, o_def = _flatten(out)
        out_def_box.append(o_def)
        return [o._data for o in leaves]

    o_shapes = jax.eval_shape(_probe, datas0)
    out_def = out_def_box[0]

    def scan_body(carry, _):
        datas, active = carry
        vs = jtu.tree_unflatten(var_def, [NDArray(d) for d in datas])
        pred = _to_nd(cond(*vs))._data.reshape(()).astype(bool)
        run = jnp.logical_and(active, pred)

        def do(ds):
            o, v, _ = _step_datas(ds)
            return v, o

        def skip(ds):
            return list(ds), [jnp.zeros(s.shape, s.dtype) for s in o_shapes]

        new_datas, out_datas = lax.cond(run, do, skip, datas)
        return (new_datas, run), (out_datas, run)

    (final_datas, _), (ys, _valid) = lax.scan(
        scan_body, (datas0, jnp.bool_(True)), None, length=max_iterations)
    outs = jtu.tree_unflatten(out_def, [NDArray(y) for y in ys])
    final_vars = jtu.tree_unflatten(var_def, [NDArray(d) for d in final_datas])
    return outs, final_vars


def cond(pred, then_func, else_func):
    """If-then-else (ref ndarray/contrib.py:403). Branch outputs must have
    identical structure/shape/dtype. Lowers to lax.cond when traced."""
    pred = _to_nd(pred)
    if not _traced([pred]):
        return then_func() if bool(pred.asscalar()) else else_func()

    defs = []

    def _branch(f):
        def run(_):
            out = f()
            leaves, tdef = _flatten(out)
            defs.append(tdef)
            return [o._data for o in leaves]
        return run

    p = pred._data.reshape(()).astype(bool)
    ys = lax.cond(p, _branch(then_func), _branch(else_func), 0)
    if defs[0] != defs[-1]:
        raise ValueError("cond branches returned different structures")
    return jtu.tree_unflatten(defs[0], [NDArray(y) for y in ys])


# ---- misc contrib ops the reference exposes alongside control flow ------
def isinf(data):
    return _apply(lambda x: jnp.isinf(x).astype(jnp.float32), _to_nd(data))


def isnan(data):
    return _apply(lambda x: jnp.isnan(x).astype(jnp.float32), _to_nd(data))


def isfinite(data):
    return _apply(lambda x: jnp.isfinite(x).astype(jnp.float32), _to_nd(data))


# ---------------------------------------------------------------- detection
# (ref src/operator/contrib/: ROIAlign, MultiProposal, fft; tensor/
#  bounding_box.cc: box_nms/box_iou/bipartite_matching)
def ROIAlign(data, rois, pooled_size, spatial_scale, sample_ratio=-1,
             position_sensitive=False, aligned=True):
    """ref contrib/roi_align.cc. sample_ratio=-1 (the reference's adaptive
    per-bin count) is mapped to a fixed 2x2 grid — sample counts must be
    static under XLA."""
    if position_sensitive:
        raise NotImplementedError(
            "position_sensitive (PSRoIAlign) is not implemented")
    from ..ops.detection import roi_align
    return roi_align(data, rois, pooled_size, spatial_scale,
                     sample_ratio if sample_ratio > 0 else 2)


def MultiProposal(cls_prob, bbox_pred, im_info, **kw):
    from ..ops.detection import multi_proposal
    return multi_proposal(cls_prob, bbox_pred, im_info, **kw)


def box_iou(lhs, rhs, format="corner"):
    from ..ops import detection
    return detection.box_iou(lhs, rhs, format)


def box_nms(data, **kw):
    from ..ops import detection
    return detection.box_nms(data, **kw)


def bipartite_matching(data, is_ascend=False, threshold=None, topk=-1):
    """ref tensor/bounding_box.cc — NOTE the reference's positional order
    is (data, is_ascend, threshold, topk)."""
    if threshold is None:
        raise ValueError("bipartite_matching requires threshold")
    from ..ops import detection
    return detection.bipartite_matching(data, threshold, is_ascend, topk)


def fft(data, compute_size=None):
    from ..ops import detection
    return detection.fft(data, compute_size)


def ifft(data, compute_size=None):
    from ..ops import detection
    return detection.ifft(data, compute_size)


# ---------------------------------------------------------------- misc
# (ref src/operator/contrib/: adaptive_avg_pooling, boolean_mask,
#  index_copy, gradient multiplier, quadratic, allclose, arange_like)
def AdaptiveAvgPooling2D(data, output_size=1):
    """ref contrib/adaptive_avg_pooling.cc — NCHW adaptive average pool."""
    import jax.numpy as jnp
    from .ndarray import _apply
    osz = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def fn(x):
        B, C, H, W = x.shape
        oh, ow = osz
        # reference bin edges: start=floor(i*H/oh), end=ceil((i+1)*H/oh)
        # (bins OVERLAP when H % oh != 0)
        rows = [jnp.mean(x[:, :, (i * H) // oh: -(-((i + 1) * H) // oh), :],
                         axis=2, keepdims=True) for i in range(oh)]
        xr = jnp.concatenate(rows, axis=2)
        cols = [jnp.mean(xr[:, :, :, (j * W) // ow: -(-((j + 1) * W) // ow)],
                         axis=3, keepdims=True) for j in range(ow)]
        return jnp.concatenate(cols, axis=3)

    return _apply(fn, data)


def boolean_mask(data, index, axis=0):
    """ref contrib/boolean_mask.cc — dynamic-shape op, eager only. The
    mask is resolved on host (data-dependent shape), but the gather runs
    through _apply so the tape records it and backward scatters into the
    kept rows (the reference op's backward)."""
    import numpy as onp
    from .ndarray import NDArray
    mask = onp.asarray(index._data if isinstance(index, NDArray) else index
                       ).astype(bool)
    idx = jnp.asarray(onp.nonzero(mask)[0])
    return _apply(lambda d: jnp.take(d, idx, axis=axis), _to_nd(data))


def index_copy(old_tensor, index_vector, new_tensor):
    """ref contrib/index_copy.cc — rows of new_tensor written at index_vector."""
    from .ndarray import _apply

    def fn(old, idx, new):
        return old.at[idx.astype("int32")].set(new)

    return _apply(fn, old_tensor, index_vector, new_tensor)


def gradientmultiplier(data, scalar=1.0):
    """ref contrib/gradient_multiplier_op.cc — identity fwd, scaled grad."""
    import jax
    from .ndarray import _apply

    @jax.custom_vjp
    def gm(x):
        return x

    gm.defvjp(lambda x: (x, None), lambda _, g: (g * scalar,))
    return _apply(gm, data)


def quadratic(data, a=0.0, b=0.0, c=0.0):
    """ref contrib/quadratic_op.cc — a*x^2 + b*x + c (the tutorial op)."""
    from .ndarray import _apply
    return _apply(lambda x: a * x * x + b * x + c, data)


def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    """ref contrib/allclose_op.cc — scalar 1/0 output."""
    import jax.numpy as jnp
    from .ndarray import _apply
    return _apply(lambda x, y: jnp.allclose(x, y, rtol, atol, equal_nan)
                  .astype(jnp.float32), a, b)


def arange_like(data, start=0.0, step=1.0, axis=None):
    """ref contrib/arange_like — arange shaped like data (or its axis)."""
    import jax.numpy as jnp
    from .ndarray import _apply

    def fn(x):
        if axis is None:
            n = x.size
            return (start + step * jnp.arange(n)).reshape(x.shape)
        return start + step * jnp.arange(x.shape[axis])

    return _apply(fn, data)


# ---------------------------------------------------------------- batch 4:
# reference _contrib_* op-surface parity (NNVM registry names)
def _alias_ops():
    """MultiBox*/SyncBatchNorm/SparseEmbedding exist as blocks/ops
    elsewhere; the reference ALSO registers them as nd.contrib ops."""
    from ..ops.multibox import MultiBoxPrior, MultiBoxTarget, MultiBoxDetection
    return MultiBoxPrior, MultiBoxTarget, MultiBoxDetection


MultiBoxPrior, MultiBoxTarget, MultiBoxDetection = _alias_ops()


def SyncBatchNorm(data, gamma, beta, moving_mean, moving_var, **kw):
    """ref contrib/sync_batch_norm-inl.h: cross-device BN. On an SPMD mesh
    batch stats are already computed over the global (sharded) batch inside
    the compiled program, so this IS BatchNorm here (documented in
    gluon/nn SyncBatchNorm)."""
    from .ndarray import BatchNorm
    kw.pop("ndev", None)
    kw.pop("key", None)
    return BatchNorm(data, gamma, beta, moving_mean, moving_var, **kw)


def SparseEmbedding(data, weight, input_dim=None, output_dim=None, **kw):
    """ref contrib SparseEmbedding op: embedding with row_sparse grad; the
    gather VJP is already a scatter (see gluon.contrib.nn.SparseEmbedding)."""
    from .ndarray import Embedding
    return Embedding(data, weight, input_dim=input_dim, output_dim=output_dim)


def index_array(data, axes=None):
    """Coordinates of every element (ref contrib/index_array.cc):
    shape data.shape + (len(axes),), int64."""
    import numpy as onp
    shp = tuple(data.shape)
    axes_ = tuple(range(len(shp))) if axes is None else tuple(axes)
    grids = onp.indices(shp)
    out = onp.stack([grids[a] for a in axes_], axis=-1).astype(onp.int64)
    from . import array as _array
    return _array(out)


def getnnz(data, axis=None):
    """Stored-value count of a CSR (ref contrib/nnz.cc)."""
    import numpy as onp
    from .sparse import CSRNDArray
    assert isinstance(data, CSRNDArray), "getnnz expects CSR storage"
    from . import array as _array
    if axis is None:
        return _array(onp.asarray([data.data.shape[0]], onp.int64))
    assert axis == 0, "getnnz supports axis=None or 0"
    ptr = onp.asarray(data.indptr._data)
    return _array((ptr[1:] - ptr[:-1]).astype(onp.int64))


def edge_id(data, u, v):
    """CSR edge lookup (ref contrib/edge_id op, DGL): out[i] = value at
    (u[i], v[i]) or -1 when absent. Eager host op (data-dependent)."""
    import numpy as onp
    ptr = onp.asarray(data.indptr._data).astype(onp.int64)
    idx = onp.asarray(data.indices._data).astype(onp.int64)
    val = onp.asarray(data.data._data)
    uu = onp.asarray(u._data).astype(onp.int64)
    vv = onp.asarray(v._data).astype(onp.int64)
    out = onp.full(uu.shape, -1.0, onp.float32)
    for i, (r, c) in enumerate(zip(uu, vv)):
        lo, hi = ptr[r], ptr[r + 1]
        pos = lo + onp.searchsorted(idx[lo:hi], c)
        if pos < hi and idx[pos] == c:
            out[i] = val[pos]
    from . import array as _array
    return _array(out)


def group_adagrad_update(weight, grad, history, lr, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5, out=None):
    """Row-wise AdaGrad (ref contrib/optimizer_op.cc group_adagrad_update):
    history += mean_dim(grad^2); w -= lr * grad / sqrt(history + eps)."""
    g = grad._data * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    h = history._data + jnp.mean(g * g, axis=tuple(range(1, g.ndim)),
                                 keepdims=True)
    history._data = h
    new_w = weight._data - lr * g / jnp.sqrt(h + epsilon)
    tgt = out if out is not None else weight
    tgt._data = new_w.astype(tgt._data.dtype)
    return tgt


# interleaved MHA matmuls (ref contrib/transformer.cc:
# _contrib_interleaved_matmul_* — the reference's fused-attention helpers).
def interleaved_matmul_selfatt_qk(queries_keys_values, heads):
    """qkv (S, B, H*3*D) head-interleaved -> scores (B*H, S, S) scaled by
    1/sqrt(D)."""
    def fn(qkv):
        S, B, HD3 = qkv.shape
        D = HD3 // (heads * 3)
        x = qkv.reshape(S, B, heads, 3, D)
        q, k = x[:, :, :, 0], x[:, :, :, 1]          # (S,B,H,D)
        q = q.transpose(1, 2, 0, 3).reshape(B * heads, S, D)
        k = k.transpose(1, 2, 0, 3).reshape(B * heads, S, D)
        return jnp.einsum("bqd,bkd->bqk", q, k) / jnp.sqrt(D).astype(qkv.dtype)
    return _apply(fn, queries_keys_values)


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads):
    """qkv (S,B,H*3*D) + att (B*H,S,S) -> context (S, B, H*D)."""
    def fn(qkv, att):
        S, B, HD3 = qkv.shape
        D = HD3 // (heads * 3)
        v = qkv.reshape(S, B, heads, 3, D)[:, :, :, 2]    # (S,B,H,D)
        v = v.transpose(1, 2, 0, 3).reshape(B * heads, S, D)
        ctx = jnp.einsum("bqk,bkd->bqd", att, v)          # (B*H,S,D)
        return ctx.reshape(B, heads, S, D).transpose(2, 0, 1, 3) \
            .reshape(S, B, heads * D)
    return _apply(fn, queries_keys_values, attention)


def interleaved_matmul_encdec_qk(queries, keys_values, heads):
    """q (Sq,B,H*D), kv (Sk,B,H*2*D) -> scores (B*H, Sq, Sk)."""
    def fn(q, kv):
        Sq, B, HD = q.shape
        D = HD // heads
        Sk = kv.shape[0]
        qq = q.reshape(Sq, B, heads, D).transpose(1, 2, 0, 3) \
            .reshape(B * heads, Sq, D)
        kk = kv.reshape(Sk, B, heads, 2, D)[:, :, :, 0] \
            .transpose(1, 2, 0, 3).reshape(B * heads, Sk, D)
        return jnp.einsum("bqd,bkd->bqk", qq, kk) / jnp.sqrt(D).astype(q.dtype)
    return _apply(fn, queries, keys_values)


def interleaved_matmul_encdec_valatt(keys_values, attention, heads):
    """kv (Sk,B,H*2*D) + att (B*H,Sq,Sk) -> context (Sq, B, H*D)."""
    def fn(kv, att):
        Sk, B, HD2 = kv.shape
        D = HD2 // (heads * 2)
        v = kv.reshape(Sk, B, heads, 2, D)[:, :, :, 1] \
            .transpose(1, 2, 0, 3).reshape(B * heads, Sk, D)
        ctx = jnp.einsum("bqk,bkd->bqd", att, v)
        Sq = att.shape[1]
        return ctx.reshape(B, heads, Sq, D).transpose(2, 0, 1, 3) \
            .reshape(Sq, B, heads * D)
    return _apply(fn, keys_values, attention)


def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched reference boxes as regression targets
    (ref contrib/bounding_box.cc BoxEncode). corner format in/out of the
    center-parameterized (dx,dy,dw,dh) encoding; samples>0 marks positives.
    Returns (targets (B,N,4), masks (B,N,4))."""
    def fn(smp, mat, anc, ref):
        ga = jnp.take_along_axis(
            ref, mat.astype(jnp.int32)[..., None].repeat(4, -1), axis=1)
        ax, ay = (anc[..., 0] + anc[..., 2]) / 2, (anc[..., 1] + anc[..., 3]) / 2
        aw, ah = anc[..., 2] - anc[..., 0], anc[..., 3] - anc[..., 1]
        gx, gy = (ga[..., 0] + ga[..., 2]) / 2, (ga[..., 1] + ga[..., 3]) / 2
        gw, gh = ga[..., 2] - ga[..., 0], ga[..., 3] - ga[..., 1]
        t = jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                       jnp.log(jnp.maximum(gw / aw, 1e-12)),
                       jnp.log(jnp.maximum(gh / ah, 1e-12))], axis=-1)
        t = (t - jnp.asarray(means)) / jnp.asarray(stds)
        mask = (smp > 0.5)[..., None].astype(t.dtype)
        return t * mask, mask
    res = _apply(lambda s, m, a, r: fn(s, m, a, r),
                 samples, matches, anchors, refs)
    return res


def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    """Inverse of box_encode (ref BoxDecode): regression deltas + anchors
    -> corner boxes (B,N,4)."""
    def fn(d, anc):
        if format == "corner":
            ax = (anc[..., 0] + anc[..., 2]) / 2
            ay = (anc[..., 1] + anc[..., 3]) / 2
            aw = anc[..., 2] - anc[..., 0]
            ah = anc[..., 3] - anc[..., 1]
        else:  # center
            ax, ay, aw, ah = (anc[..., 0], anc[..., 1], anc[..., 2],
                              anc[..., 3])
        dx, dy = d[..., 0] * std0, d[..., 1] * std1
        dw, dh = d[..., 2] * std2, d[..., 3] * std3
        if clip is not None and clip > 0:
            dw = jnp.minimum(dw, clip)
            dh = jnp.minimum(dh, clip)
        cx, cy = dx * aw + ax, dy * ah + ay
        w, h = jnp.exp(dw) * aw, jnp.exp(dh) * ah
        return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
    return _apply(fn, data, _to_nd(anchors))


def RROIAlign(data, rois, pooled_size, spatial_scale, sampling_ratio=2):
    """Rotated ROI align (ref contrib RROIAlign): rois
    (R, 6) = (batch_idx, cx, cy, w, h, angle_rad); bilinear sampling on the
    rotated grid via the shared gather helper."""
    from ..ops.detection import _bilinear_gather
    PH, PW = pooled_size
    s = sampling_ratio

    def fn(x, r):
        R = r.shape[0]
        cx, cy = r[:, 1] * spatial_scale, r[:, 2] * spatial_scale
        w, h = r[:, 3] * spatial_scale, r[:, 4] * spatial_scale
        ang = r[:, 5]
        iy = (jnp.arange(PH * s) + 0.5) / (PH * s) - 0.5   # [-.5,.5) grid
        ix = (jnp.arange(PW * s) + 0.5) / (PW * s) - 0.5
        gy, gx = jnp.meshgrid(iy, ix, indexing="ij")       # (PH*s, PW*s)
        # rotate local (gx*w, gy*h) by angle then translate to center
        ca, sa = jnp.cos(ang), jnp.sin(ang)
        lx = gx[None] * w[:, None, None]
        ly = gy[None] * h[:, None, None]
        xs = cx[:, None, None] + lx * ca[:, None, None] - ly * sa[:, None, None]
        ys = cy[:, None, None] + lx * sa[:, None, None] + ly * ca[:, None, None]
        batch_idx = r[:, 0].astype(jnp.int32)
        per_roi = x[batch_idx]                              # (R, C, H, W)
        sampled = _bilinear_gather(per_roi, ys, xs)         # (R, C, PH*s, PW*s)
        C = x.shape[1]
        return sampled.reshape(R, C, PH, s, PW, s).mean(axis=(3, 5))
    return _apply(fn, data, _to_nd(rois))


def quantize(data, min_range, max_range, out_type="int8"):
    """op alias of contrib.quantization.quantize (ref quantize.cc)."""
    from ..contrib import quantization as q
    return q.quantize(data, float(min_range.asscalar()),
                      float(max_range.asscalar()), out_type)


def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """ref quantize_v2.cc: ranges from calibration or from the data."""
    from ..contrib import quantization as q
    return q.quantize(data, min_calib_range, max_calib_range, out_type)


def dequantize(data, min_range, max_range, out_type="float32"):
    from ..contrib import quantization as q
    return q.dequantize(data, min_range, max_range, out_type)


def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    from ..contrib import quantization as q
    return q.requantize(data, min_range, max_range, min_calib_range,
                        max_calib_range)


def calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """ref quantization/calibrate.cc: KL-optimal threshold from a histogram.
    Delegates to the same entropy search quantize_net uses."""
    import numpy as onp
    from ..contrib.quantization import _entropy_threshold
    h = onp.asarray(hist._data if hasattr(hist, "_data") else hist)
    e = onp.asarray(hist_edges._data if hasattr(hist_edges, "_data")
                    else hist_edges)
    thr = _entropy_threshold(h, e, num_quantized_bins)
    from . import array as _array
    return (_array(onp.asarray([-thr], onp.float32)),
            _array(onp.asarray([thr], onp.float32)))


__all__ += [
    "MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection", "SyncBatchNorm",
    "SparseEmbedding", "index_array", "getnnz", "edge_id",
    "group_adagrad_update", "interleaved_matmul_selfatt_qk",
    "interleaved_matmul_selfatt_valatt", "interleaved_matmul_encdec_qk",
    "interleaved_matmul_encdec_valatt", "box_encode", "box_decode",
    "RROIAlign", "quantize", "quantize_v2", "dequantize", "requantize",
    "calibrate_entropy",
]


def hawkesll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """Marked Hawkes process log-likelihood (ref contrib/hawkes_ll.cc):
    intensity lam_k*(t) = mu_k + alpha_k beta_k sum_{t_i<t, y_i=k}
    exp(-beta_k (t - t_i)). Inputs: lda (N,K) background mu, alpha/beta
    (K,), state (N,K) decayed-counter memory s_k(0), ragged lags/marks
    (N,T) with valid_length (N,), observation horizon max_time (N,).
    Returns (loglik (N,), out_state (N,K) = s_k(max_time)). Lowered to one
    lax.scan over the sequence axis (the reference's per-sample loop)."""
    def fn(mu, a, b, st0, lg, mk, vl, mt):
        N, T = lg.shape
        K = mu.shape[1]
        mki = mk.astype(jnp.int32)

        def step(carry, inp):
            st, last, t, ll, j = carry
            lag_j, mark_j = inp
            t2 = t + lag_j
            oh = jax.nn.one_hot(mark_j, K, dtype=mu.dtype)        # (N,K)
            take = lambda m2: jnp.take_along_axis(
                m2, mark_j[:, None], 1)[:, 0]
            d = t2 - take(last)
            a_ci, b_ci = a[mark_j], b[mark_j]
            st_ci, mu_ci = take(st), take(mu)
            ed = jnp.exp(-b_ci * d)
            lam = mu_ci + a_ci * b_ci * st_ci * ed
            comp = mu_ci * d + a_ci * st_ci * (1 - ed)
            valid = j < vl                                        # (N,)
            ll2 = ll + jnp.where(valid, jnp.log(lam) - comp, 0.0)
            upd = (valid[:, None] * oh) > 0
            st2 = jnp.where(upd, (1 + st_ci * ed)[:, None], st)
            last2 = jnp.where(upd, t2[:, None], last)
            return (st2, last2, jnp.where(valid, t2, t), ll2, j + 1), None

        init = (st0, jnp.zeros_like(st0), jnp.zeros(N, mu.dtype),
                jnp.zeros(N, mu.dtype), jnp.zeros((), jnp.float32))
        (st, last, _, ll, _), _ = lax.scan(step, init, (lg.T, mki.T))
        d = mt[:, None] - last
        ed = jnp.exp(-b[None, :] * d)
        ll = ll - (mu * d + a[None, :] * st * (1 - ed)).sum(axis=1)
        return ll, st * ed

    res = _apply(lambda *xs: fn(*xs), _to_nd(lda), _to_nd(alpha), _to_nd(beta),
                 _to_nd(state), _to_nd(lags), _to_nd(marks),
                 _to_nd(valid_length), _to_nd(max_time))
    return res


__all__ += ["hawkesll"]


# ---- DGL graph-sampling ops (ref contrib/dgl_graph.cc) -------------------
def _csr_parts(g):
    import numpy as onp
    return (onp.asarray(g.data._data), onp.asarray(g.indices._data).astype(onp.int64),
            onp.asarray(g.indptr._data).astype(onp.int64), g.shape)


def _make_csr(vals, idx, ptr, shape):
    import numpy as onp
    from .sparse import CSRNDArray
    from . import array as _array
    return CSRNDArray(_array(onp.asarray(vals)),
                      _array(onp.asarray(idx, onp.int64).astype("int64")),
                      _array(onp.asarray(ptr, onp.int64).astype("int64")),
                      shape)


def _neighbor_sample(csr, seeds, num_hops, num_neighbor, max_num_vertices,
                     probability=None, seed=0):
    import numpy as onp
    vals, idx, ptr, shape = _csr_parts(csr)
    rng = onp.random.RandomState(seed)
    seeds = onp.asarray(seeds._data).astype(onp.int64)
    seeds = seeds[seeds >= 0]
    layer = {int(v): 0 for v in seeds}
    frontier = list(seeds)
    edges = {}                      # (u, v) -> value
    for hop in range(1, num_hops + 1):
        nxt = []
        for u in frontier:
            lo, hi = ptr[u], ptr[u + 1]
            nbrs = idx[lo:hi]
            evals = vals[lo:hi]
            if len(nbrs) == 0:
                continue
            k = min(num_neighbor, len(nbrs))
            if probability is not None:
                p = onp.asarray(probability._data)[nbrs]
                p = p / p.sum() if p.sum() > 0 else None
                sel = rng.choice(len(nbrs), size=k, replace=False, p=p)
            else:
                sel = rng.choice(len(nbrs), size=k, replace=False)
            for s in sel:
                v = int(nbrs[s])
                edges[(int(u), v)] = evals[s]
                if v not in layer:
                    layer[v] = hop
                    nxt.append(v)
        frontier = nxt
    verts = sorted(layer)[:max_num_vertices]
    vset = set(verts)
    out_v = onp.full(max_num_vertices + 1, -1, onp.int64)
    out_v[: len(verts)] = verts
    out_v[-1] = len(verts)
    out_layer = onp.full(max_num_vertices, -1, onp.int64)
    out_layer[: len(verts)] = [layer[v] for v in verts]
    # sub-CSR in ORIGINAL ids (reference keeps the input shape)
    rows = [[] for _ in range(shape[0])]
    for (u, v), e in sorted(edges.items()):
        if u in vset and v in vset:
            rows[u].append((v, e))
    new_ptr = [0]
    new_idx, new_vals = [], []
    for r in rows:
        for v, e in sorted(r):
            new_idx.append(v)
            new_vals.append(e)
        new_ptr.append(len(new_idx))
    from . import array as _array
    return (_array(out_v), _make_csr(new_vals, new_idx, new_ptr, shape),
            _array(out_layer))


def dgl_csr_neighbor_uniform_sample(csr_matrix, *seed_arrays, num_args=None,
                                    num_hops=1, num_neighbor=2,
                                    max_num_vertices=100, seed=0):
    """Uniform neighbor sampling for GNN mini-batches (ref dgl_graph.cc
    _contrib_dgl_csr_neighbor_uniform_sample). Per seed array returns
    (vertices padded to max_num_vertices+1 with count in the last slot,
    sampled sub-CSR in original ids, per-vertex sample layer). Eager host
    op — sampling is data-dependent."""
    outs = []
    for s in seed_arrays:
        outs.extend(_neighbor_sample(csr_matrix, s, num_hops, num_neighbor,
                                     max_num_vertices, None, seed))
    return outs


def dgl_csr_neighbor_non_uniform_sample(csr_matrix, probability, *seed_arrays,
                                        num_args=None, num_hops=1,
                                        num_neighbor=2, max_num_vertices=100,
                                        seed=0):
    """Probability-weighted variant (ref _contrib_dgl_csr_neighbor_non_
    uniform_sample); probability is per-vertex."""
    outs = []
    for s in seed_arrays:
        outs.extend(_neighbor_sample(csr_matrix, s, num_hops, num_neighbor,
                                     max_num_vertices, probability, seed))
    return outs


def dgl_subgraph(graph, *vids_arrays, return_mapping=False, num_args=None):
    """Induced subgraph(s) with relabeled vertices (ref dgl_graph.cc
    _contrib_dgl_subgraph); with return_mapping also emits a CSR whose
    values are the ORIGINAL edge positions."""
    import numpy as onp
    vals, idx, ptr, shape = _csr_parts(graph)
    outs = []
    maps = []
    for va in vids_arrays:
        vids = onp.asarray(va._data).astype(onp.int64)
        vids = vids[vids >= 0]
        relabel = {int(v): i for i, v in enumerate(vids)}
        n = len(vids)
        new_ptr, new_idx, new_vals, new_eid = [0], [], [], []
        for v in vids:
            lo, hi = ptr[v], ptr[v + 1]
            ents = [(relabel[int(c)], vals[e], e)
                    for e, c in zip(range(lo, hi), idx[lo:hi])
                    if int(c) in relabel]
            for cc, ee, eid in sorted(ents):
                new_idx.append(cc)
                new_vals.append(ee)
                new_eid.append(eid)
            new_ptr.append(len(new_idx))
        outs.append(_make_csr(new_vals, new_idx, new_ptr, (n, n)))
        maps.append(_make_csr(new_eid, new_idx, new_ptr, (n, n)))
    return outs + maps if return_mapping else outs


def dgl_graph_compact(*graphs, graph_sizes=None, return_mapping=False,
                      num_args=None):
    """Trim padded sampled graphs to their true size (ref dgl_graph.cc
    _contrib_dgl_graph_compact): graph i keeps its first graph_sizes[i]
    vertices/columns."""
    import numpy as onp
    sizes = [int(x) for x in onp.asarray(
        graph_sizes._data if hasattr(graph_sizes, "_data") else graph_sizes)]
    outs = []
    for g, n in zip(graphs, sizes):
        vals, idx, ptr, _ = _csr_parts(g)
        new_ptr, new_idx, new_vals = [0], [], []
        for r in range(n):
            lo, hi = ptr[r], ptr[r + 1]
            for e, c in zip(range(lo, hi), idx[lo:hi]):
                if c < n:
                    new_idx.append(int(c))
                    new_vals.append(vals[e])
            new_ptr.append(len(new_idx))
        outs.append(_make_csr(new_vals, new_idx, new_ptr, (n, n)))
    return outs if len(outs) > 1 else outs[0]


def dgl_adjacency(graph):
    """Adjacency with all-ones values (ref _contrib_dgl_adjacency)."""
    import numpy as onp
    vals, idx, ptr, shape = _csr_parts(graph)
    return _make_csr(onp.ones(len(vals), onp.float32), idx, ptr, shape)


__all__ += ["dgl_csr_neighbor_uniform_sample",
            "dgl_csr_neighbor_non_uniform_sample", "dgl_subgraph",
            "dgl_graph_compact", "dgl_adjacency"]


# ---- quantized int8 op family (ref src/operator/quantization/) -----------
# The matmul/conv ops compute NATIVELY in int8: int8 operands, int32 MXU
# accumulation (lax.dot_general / conv_general_dilated with
# preferred_element_type=int32), one fp32 rescale of the accumulator by
# scale_data*scale_weight — exactly the reference's int8 kernel contract
# (quantized_fully_connected.cc int32 accum / kInt8Range scaling). The v5e
# MXU runs int8 at 2x bf16 peak AND the int8 stream halves HBM bytes.
# MXTPU_INT8_SIM=1 forces the dequantize->fp32 compute->requantize fallback
# (the reference's own quantize_graph_pass.cc fallback for kernels without
# a native int8 impl). Elementwise/range-preserving ops stay on the scale
# arithmetic XLA fuses.
def _q_ranges(*pairs):
    out = []
    for mn, mx_ in pairs:
        out.append(float(mn.asnumpy()[0]) if hasattr(mn, "asnumpy") else mn)
        out.append(float(mx_.asnumpy()[0]) if hasattr(mx_, "asnumpy") else mx_)
    return out


def _requant_out(x_float):
    from ..contrib import quantization as q
    return q.quantize(x_float)


def _int8_native():
    from ..config import get_env
    return not get_env("MXTPU_INT8_SIM")


def _q_scale(lo, hi):
    lo = float(lo.asnumpy()[0]) if hasattr(lo, "asnumpy") else float(lo)
    hi = float(hi.asnumpy()[0]) if hasattr(hi, "asnumpy") else float(hi)
    return max(abs(lo), abs(hi)) / 127.0 or 1.0


def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden=None, no_bias=False,
                              flatten=True):
    """ref quantization/quantized_fully_connected.cc — int8 x int8 -> int32
    MXU matmul, fp32 rescale by scale_d*scale_w, bias added in fp32."""
    from ..contrib import quantization as q
    from .ndarray import FullyConnected
    if not _int8_native():
        d = q.dequantize(data, min_data, max_data)
        w = q.dequantize(weight, min_weight, max_weight)
        b = None if no_bias or bias is None else \
            q.dequantize(bias, min_bias, max_bias)
        out = FullyConnected(d, w, b, num_hidden=num_hidden,
                             no_bias=b is None, flatten=flatten)
        return _requant_out(out)
    s_out = _q_scale(min_data, max_data) * _q_scale(min_weight, max_weight)

    def fn(x, wt):
        x2 = x.reshape(x.shape[0], -1) if flatten and x.ndim > 2 else x
        acc = lax.dot_general(
            x2, wt, (((x2.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32) * s_out

    out = _apply(fn, data, weight)
    if not (no_bias or bias is None):
        out = out + q.dequantize(bias, min_bias, max_bias).reshape(1, -1)
    return _requant_out(out)


def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias=None, max_bias=None, kernel=None,
                   stride=(1, 1), pad=(0, 0), dilate=(1, 1), num_filter=None,
                   num_group=1, no_bias=False, layout="NCHW"):
    """ref quantization/quantized_conv.cc — int8 conv with int32
    accumulation on the MXU, fp32 rescale."""
    from ..contrib import quantization as q
    from .ndarray import Convolution
    if not _int8_native():
        d = q.dequantize(data, min_data, max_data)
        w = q.dequantize(weight, min_weight, max_weight)
        b = None if no_bias or bias is None else \
            q.dequantize(bias, min_bias, max_bias)
        out = Convolution(d, w, b, kernel=kernel, stride=stride, pad=pad,
                          dilate=dilate, num_filter=num_filter,
                          num_group=num_group, no_bias=b is None)
        return _requant_out(out)
    s_out = _q_scale(min_data, max_data) * _q_scale(min_weight, max_weight)
    n = len(kernel)
    stride_ = tuple(stride)[:n] + (1,) * (n - len(tuple(stride)[:n]))
    dil = tuple(dilate)[:n] + (1,) * (n - len(tuple(dilate)[:n]))
    pad_ = tuple(pad)[:n] + (0,) * (n - len(tuple(pad)[:n]))
    spatial = "".join("DHW"[3 - n:][i] for i in range(n))
    dn_str = ("NC" + spatial, "OI" + spatial, "NC" + spatial)

    def fn(x, w):
        dn = lax.conv_dimension_numbers(x.shape, w.shape, dn_str)
        acc = lax.conv_general_dilated(
            x, w, window_strides=stride_, padding=[(p, p) for p in pad_],
            rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=num_group,
            preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32) * s_out

    out = _apply(fn, data, weight)
    if not (no_bias or bias is None):
        b = q.dequantize(bias, min_bias, max_bias)
        out = out + b.reshape((1, -1) + (1,) * n)
    return _requant_out(out)


def quantized_pooling(data, min_data, max_data, kernel=(2, 2), pool_type="max",
                      stride=None, pad=(0, 0), global_pool=False, **kw):
    """ref quantized_pooling.cc — pure int8 (max/avg preserve the range)."""
    from .ndarray import Pooling
    out = _apply(lambda x: x.astype(jnp.float32), data)
    out = Pooling(out, kernel=kernel, pool_type=pool_type,
                  stride=stride or kernel, pad=pad, global_pool=global_pool)
    q = _apply(lambda x: jnp.round(x).astype(jnp.int8), out)
    return q, min_data, max_data


def quantized_act(data, min_data, max_data, act_type="relu"):
    """ref quantized_act.cc — relu on int8 keeps the calibrated range."""
    assert act_type == "relu", "int8 activation supports relu"
    return (_apply(lambda x: jnp.maximum(x, 0), data), min_data, max_data)


def quantized_flatten(data, min_data, max_data):
    """ref quantized_flatten.cc."""
    return (_apply(lambda x: x.reshape(x.shape[0], -1), data),
            min_data, max_data)


def quantized_concat(*args, dim=1, num_args=None):
    """ref quantized_concat.cc: inputs rescaled to the widest range then
    concatenated. args = d0..dn, min0..minn, max0..maxn (reference input
    order)."""
    n = num_args or len(args) // 3
    datas, mins, maxs = args[:n], args[n:2 * n], args[2 * n:3 * n]
    from ..contrib import quantization as q
    lo = min(float(m.asnumpy()[0]) if hasattr(m, "asnumpy") else m for m in mins)
    hi = max(float(m.asnumpy()[0]) if hasattr(m, "asnumpy") else m for m in maxs)
    parts = [q.dequantize(d, mn, mx_)
             for d, mn, mx_ in zip(datas, mins, maxs)]
    cat = _apply(lambda *xs: jnp.concatenate(xs, axis=dim), *parts)
    return q.quantize(cat, lo, hi)


def quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    """ref quantized_elemwise_add.cc."""
    from ..contrib import quantization as q
    a = q.dequantize(lhs, lhs_min, lhs_max)
    b = q.dequantize(rhs, rhs_min, rhs_max)
    return _requant_out(a + b)


def quantized_elemwise_mul(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    """ref quantized_elemwise_mul.cc."""
    from ..contrib import quantization as q
    a = q.dequantize(lhs, lhs_min, lhs_max)
    b = q.dequantize(rhs, rhs_min, rhs_max)
    return _requant_out(a * b)


def quantized_embedding(data, weight, min_weight, max_weight, input_dim=None,
                        output_dim=None):
    """ref quantized_embedding.cc: int8 table lookup, weight range kept."""
    out = _apply(lambda idx, w: jnp.take(w, idx.astype(jnp.int32), axis=0),
                 _to_nd(data), weight)
    return out, min_weight, max_weight


def quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                         min_data, max_data, eps=1e-3, min_calib_range=None,
                         max_calib_range=None, **kw):
    """ref quantized_batch_norm.cc: folded inference BN on the dequantized
    stream, requantized to the calibrated output range."""
    from ..contrib import quantization as q
    d = q.dequantize(data, min_data, max_data)

    def fn(x, g, b, mm, mv):
        shape = (1, -1) + (1,) * (x.ndim - 2)
        scale = g.reshape(shape) / jnp.sqrt(mv.reshape(shape) + eps)
        return x * scale + (b.reshape(shape) - mm.reshape(shape) * scale)
    out = _apply(fn, d, gamma, beta, moving_mean, moving_var)
    return q.quantize(out, min_calib_range, max_calib_range)


__all__ += ["quantized_fully_connected", "quantized_conv",
            "quantized_pooling", "quantized_act", "quantized_flatten",
            "quantized_concat", "quantized_elemwise_add",
            "quantized_elemwise_mul", "quantized_embedding",
            "quantized_batch_norm"]
