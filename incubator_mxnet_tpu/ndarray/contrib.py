"""Control-flow operators: foreach / while_loop / cond.

TPU-native analog of the reference's control-flow ops
(ref src/operator/control_flow.cc:1089 _foreach, :1150 _while_loop,
:1211 _cond; frontend python/mxnet/ndarray/contrib.py:139,235,403).

Design (tpu-first, not a translation):
- Eager mode runs real Python loops — exactly the reference's own eager
  semantics — so the autograd tape records through loop bodies and
  gradients flow to closed-over parameters naturally.
- Traced mode (inside hybridize / TrainStep / jit) lowers to XLA-native
  structured control flow: foreach -> lax.scan, cond -> lax.cond, and
  while_loop -> a MASKED lax.scan over max_iterations steps. The masked
  scan (rather than lax.while_loop) keeps the op reverse-mode
  differentiable — XLA cannot differentiate a dynamic while — at the cost
  of always executing max_iterations steps; rows past the dynamic stop
  are zero-filled (the return signature matches the reference:
  (outputs, final_loop_vars)).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax import tree_util as jtu

from .ndarray import NDArray, _apply, _to_nd
from .. import autograd

__all__ = ["foreach", "while_loop", "cond", "isinf", "isnan", "isfinite"]


def _is_nd(v):
    return isinstance(v, NDArray)


def _flatten(tree):
    return jtu.tree_flatten(tree, is_leaf=_is_nd)


def _traced(leaves):
    from ..gluon import _functional
    if _functional.in_functional_mode():
        return True
    return any(isinstance(x._data, jax.core.Tracer) for x in leaves)


def _stack_trees(trees):
    """Stack a list of identically-structured NDArray trees along axis 0,
    through _apply so the autograd tape sees it."""
    leaves0, treedef = _flatten(trees[0])
    cols = [[_flatten(t)[0][i] for t in trees] for i in range(len(leaves0))]
    stacked = [_apply(lambda *ds: jnp.stack(ds, 0), *c) for c in cols]
    return jtu.tree_unflatten(treedef, stacked)


def foreach(body, data, init_states):
    """Loop body over dim 0 of data (ref ndarray/contrib.py:139).

    body(data_i, states) -> (out, new_states). Returns (outs, final_states)
    with outs stacked along a new axis 0. Lowers to lax.scan when traced.
    """
    data_leaves, data_def = _flatten(data)
    state_leaves, state_def = _flatten(init_states)
    if not data_leaves:
        raise ValueError("foreach needs at least one input array")
    n = data_leaves[0].shape[0]

    if not _traced(data_leaves + state_leaves):
        if n == 0:
            raise ValueError("foreach over zero-length data: outputs are "
                             "undefined in eager mode (shape unknown)")
        states = init_states
        outs = []
        for i in range(n):
            sl = jtu.tree_unflatten(data_def, [d[i] for d in data_leaves])
            out, states = body(sl, states)
            outs.append(out)
        return _stack_trees(outs), states

    out_def_box = []

    def scan_body(carry, xs):
        states = jtu.tree_unflatten(state_def, [NDArray(c) for c in carry])
        sl = jtu.tree_unflatten(data_def, [NDArray(x) for x in xs])
        out, new_states = body(sl, states)
        o_leaves, o_def = _flatten(out)
        s_leaves, _ = _flatten(new_states)
        out_def_box.clear()
        out_def_box.append(o_def)
        return [s._data for s in s_leaves], [o._data for o in o_leaves]

    carry0 = [s._data for s in state_leaves]
    xs = [d._data for d in data_leaves]
    carry_t, ys = lax.scan(scan_body, carry0, xs)
    outs = jtu.tree_unflatten(out_def_box[0], [NDArray(y) for y in ys])
    states = jtu.tree_unflatten(state_def, [NDArray(c) for c in carry_t])
    return outs, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """While loop (ref ndarray/contrib.py:235).

    cond(*loop_vars) -> scalar; func(*loop_vars) -> (step_output,
    new_loop_vars). Returns (outputs, final_loop_vars); outputs stacked
    along axis 0. Eager: exact number of executed steps. Traced:
    max_iterations is REQUIRED, outputs have shape[0] == max_iterations
    with rows past the dynamic stop zero-filled (masked-scan lowering,
    reverse-differentiable).
    """
    loop_vars = list(loop_vars)
    var_leaves, var_def = _flatten(loop_vars)

    if not _traced(var_leaves):
        outs = []
        steps = 0
        while (max_iterations is None or steps < max_iterations) and \
                bool(_to_nd(cond(*loop_vars)).asscalar()):
            step_out, new_vars = func(*loop_vars)
            loop_vars = list(new_vars)
            outs.append(step_out)
            steps += 1
        if not outs:
            raise ValueError("while_loop executed zero steps — outputs "
                             "undefined (reference raises here too)")
        return _stack_trees(outs), loop_vars

    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations when traced "
                         "(static shapes; see module docstring)")

    # shape-infer the step output so the masked branch can emit zeros
    def _step_datas(datas):
        vs = jtu.tree_unflatten(var_def, [NDArray(d) for d in datas])
        out, new_vars = func(*vs)
        o_leaves, o_def = _flatten(out)
        v_leaves, _ = _flatten(list(new_vars))
        return [o._data for o in o_leaves], [v._data for v in v_leaves], o_def

    datas0 = [v._data for v in var_leaves]
    out_def_box = []

    def _probe(ds):
        vs = jtu.tree_unflatten(var_def, [NDArray(d) for d in ds])
        out, _ = func(*vs)
        leaves, o_def = _flatten(out)
        out_def_box.append(o_def)
        return [o._data for o in leaves]

    o_shapes = jax.eval_shape(_probe, datas0)
    out_def = out_def_box[0]

    def scan_body(carry, _):
        datas, active = carry
        vs = jtu.tree_unflatten(var_def, [NDArray(d) for d in datas])
        pred = _to_nd(cond(*vs))._data.reshape(()).astype(bool)
        run = jnp.logical_and(active, pred)

        def do(ds):
            o, v, _ = _step_datas(ds)
            return v, o

        def skip(ds):
            return list(ds), [jnp.zeros(s.shape, s.dtype) for s in o_shapes]

        new_datas, out_datas = lax.cond(run, do, skip, datas)
        return (new_datas, run), (out_datas, run)

    (final_datas, _), (ys, _valid) = lax.scan(
        scan_body, (datas0, jnp.bool_(True)), None, length=max_iterations)
    outs = jtu.tree_unflatten(out_def, [NDArray(y) for y in ys])
    final_vars = jtu.tree_unflatten(var_def, [NDArray(d) for d in final_datas])
    return outs, final_vars


def cond(pred, then_func, else_func):
    """If-then-else (ref ndarray/contrib.py:403). Branch outputs must have
    identical structure/shape/dtype. Lowers to lax.cond when traced."""
    pred = _to_nd(pred)
    if not _traced([pred]):
        return then_func() if bool(pred.asscalar()) else else_func()

    defs = []

    def _branch(f):
        def run(_):
            out = f()
            leaves, tdef = _flatten(out)
            defs.append(tdef)
            return [o._data for o in leaves]
        return run

    p = pred._data.reshape(()).astype(bool)
    ys = lax.cond(p, _branch(then_func), _branch(else_func), 0)
    if defs[0] != defs[-1]:
        raise ValueError("cond branches returned different structures")
    return jtu.tree_unflatten(defs[0], [NDArray(y) for y in ys])


# ---- misc contrib ops the reference exposes alongside control flow ------
def isinf(data):
    return _apply(lambda x: jnp.isinf(x).astype(jnp.float32), _to_nd(data))


def isnan(data):
    return _apply(lambda x: jnp.isnan(x).astype(jnp.float32), _to_nd(data))


def isfinite(data):
    return _apply(lambda x: jnp.isfinite(x).astype(jnp.float32), _to_nd(data))


# ---------------------------------------------------------------- detection
# (ref src/operator/contrib/: ROIAlign, MultiProposal, fft; tensor/
#  bounding_box.cc: box_nms/box_iou/bipartite_matching)
def ROIAlign(data, rois, pooled_size, spatial_scale, sample_ratio=-1,
             position_sensitive=False, aligned=True):
    """ref contrib/roi_align.cc. sample_ratio=-1 (the reference's adaptive
    per-bin count) is mapped to a fixed 2x2 grid — sample counts must be
    static under XLA."""
    if position_sensitive:
        raise NotImplementedError(
            "position_sensitive (PSRoIAlign) is not implemented")
    from ..ops.detection import roi_align
    return roi_align(data, rois, pooled_size, spatial_scale,
                     sample_ratio if sample_ratio > 0 else 2)


def MultiProposal(cls_prob, bbox_pred, im_info, **kw):
    from ..ops.detection import multi_proposal
    return multi_proposal(cls_prob, bbox_pred, im_info, **kw)


def box_iou(lhs, rhs, format="corner"):
    from ..ops import detection
    return detection.box_iou(lhs, rhs, format)


def box_nms(data, **kw):
    from ..ops import detection
    return detection.box_nms(data, **kw)


def bipartite_matching(data, is_ascend=False, threshold=None, topk=-1):
    """ref tensor/bounding_box.cc — NOTE the reference's positional order
    is (data, is_ascend, threshold, topk)."""
    if threshold is None:
        raise ValueError("bipartite_matching requires threshold")
    from ..ops import detection
    return detection.bipartite_matching(data, threshold, is_ascend, topk)


def fft(data, compute_size=None):
    from ..ops import detection
    return detection.fft(data, compute_size)


def ifft(data, compute_size=None):
    from ..ops import detection
    return detection.ifft(data, compute_size)


# ---------------------------------------------------------------- misc
# (ref src/operator/contrib/: adaptive_avg_pooling, boolean_mask,
#  index_copy, gradient multiplier, quadratic, allclose, arange_like)
def AdaptiveAvgPooling2D(data, output_size=1):
    """ref contrib/adaptive_avg_pooling.cc — NCHW adaptive average pool."""
    import jax.numpy as jnp
    from .ndarray import _apply
    osz = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def fn(x):
        B, C, H, W = x.shape
        oh, ow = osz
        # reference bin edges: start=floor(i*H/oh), end=ceil((i+1)*H/oh)
        # (bins OVERLAP when H % oh != 0)
        rows = [jnp.mean(x[:, :, (i * H) // oh: -(-((i + 1) * H) // oh), :],
                         axis=2, keepdims=True) for i in range(oh)]
        xr = jnp.concatenate(rows, axis=2)
        cols = [jnp.mean(xr[:, :, :, (j * W) // ow: -(-((j + 1) * W) // ow)],
                         axis=3, keepdims=True) for j in range(ow)]
        return jnp.concatenate(cols, axis=3)

    return _apply(fn, data)


def boolean_mask(data, index, axis=0):
    """ref contrib/boolean_mask.cc — dynamic-shape op, eager only. The
    mask is resolved on host (data-dependent shape), but the gather runs
    through _apply so the tape records it and backward scatters into the
    kept rows (the reference op's backward)."""
    import numpy as onp
    from .ndarray import NDArray
    mask = onp.asarray(index._data if isinstance(index, NDArray) else index
                       ).astype(bool)
    idx = jnp.asarray(onp.nonzero(mask)[0])
    return _apply(lambda d: jnp.take(d, idx, axis=axis), _to_nd(data))


def index_copy(old_tensor, index_vector, new_tensor):
    """ref contrib/index_copy.cc — rows of new_tensor written at index_vector."""
    from .ndarray import _apply

    def fn(old, idx, new):
        return old.at[idx.astype("int32")].set(new)

    return _apply(fn, old_tensor, index_vector, new_tensor)


def gradientmultiplier(data, scalar=1.0):
    """ref contrib/gradient_multiplier_op.cc — identity fwd, scaled grad."""
    import jax
    from .ndarray import _apply

    @jax.custom_vjp
    def gm(x):
        return x

    gm.defvjp(lambda x: (x, None), lambda _, g: (g * scalar,))
    return _apply(gm, data)


def quadratic(data, a=0.0, b=0.0, c=0.0):
    """ref contrib/quadratic_op.cc — a*x^2 + b*x + c (the tutorial op)."""
    from .ndarray import _apply
    return _apply(lambda x: a * x * x + b * x + c, data)


def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    """ref contrib/allclose_op.cc — scalar 1/0 output."""
    import jax.numpy as jnp
    from .ndarray import _apply
    return _apply(lambda x, y: jnp.allclose(x, y, rtol, atol, equal_nan)
                  .astype(jnp.float32), a, b)


def arange_like(data, start=0.0, step=1.0, axis=None):
    """ref contrib/arange_like — arange shaped like data (or its axis)."""
    import jax.numpy as jnp
    from .ndarray import _apply

    def fn(x):
        if axis is None:
            n = x.size
            return (start + step * jnp.arange(n)).reshape(x.shape)
        return start + step * jnp.arange(x.shape[axis])

    return _apply(fn, data)
