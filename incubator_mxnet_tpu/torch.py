"""PyTorch interop bridge (ref python/mxnet/torch.py — the legacy
lua-torch op bridge, modernized for PyTorch).

Three surfaces:
- ``to_torch`` / ``from_torch``: tensor conversion (DLPack zero-copy on
  CPU when possible, NumPy otherwise).
- ``torch_function``: run a differentiable torch function inside the
  autograd tape — backward is computed by torch.autograd and handed back
  to our tape, so a torch op composes with nd ops in one loss.
- ``TorchBlock``: wrap a ``torch.nn.Module`` as a Gluon block (host/CPU
  execution; the module's own parameters are trained by torch-side
  gradients through ``torch_function``).

Scope: the bridge executes on host CPU — it is an interop/migration aid
(the reference's was too), not a TPU compute path; keep hot paths in nd.
The tape records through tracked leaves, so at least one bridged input
must have ``attach_grad()`` for loss.backward() to reach the torch side
(standard autograd semantics). Not imported at package init: importing
``incubator_mxnet_tpu.torch`` is opt-in so the frameworks stay decoupled.
"""
from __future__ import annotations

import numpy as onp

from .ndarray import NDArray
from . import autograd

__all__ = ["to_torch", "from_torch", "torch_function", "TorchBlock"]


def _torch():
    try:
        import torch as _t
        return _t
    except ImportError as e:  # pragma: no cover
        raise ImportError("the torch bridge needs pytorch installed") from e


def to_torch(arr):
    """NDArray → torch.Tensor (host copy; DLPack when both sides allow)."""
    t = _torch()
    data = arr._data if isinstance(arr, NDArray) else arr
    try:
        import jax
        return t.from_dlpack(jax.device_get(data))  # zero/one-copy via CPU
    except Exception:
        return t.from_numpy(onp.asarray(data))


def from_torch(tensor, ctx=None):
    """torch.Tensor → NDArray."""
    return NDArray(onp.ascontiguousarray(tensor.detach().cpu().numpy()),
                   ctx=ctx)


def torch_function(fn, *inputs):
    """Run ``fn(*torch_tensors) -> torch_tensor`` under our autograd tape;
    the VJP is delegated to torch.autograd (ref torch bridge's
    forward/backward op pairs)."""
    t = _torch()

    class _Bridge(autograd.Function):
        def forward(self, *arrs):
            self._tins = [
                t.tensor(onp.asarray(a._data if isinstance(a, NDArray) else a),
                         requires_grad=True)
                for a in arrs]
            with t.enable_grad():
                out = fn(*self._tins)
            self._tout = out
            return NDArray(out.detach().cpu().numpy())

        def backward(self, dout):
            # full torch backward (not autograd.grad on inputs): gradients
            # also ACCUMULATE into any torch parameters inside fn, so a
            # TorchBlock's module is trainable with a torch optimizer off
            # our tape's loss.backward()
            t.autograd.backward(self._tout,
                                grad_tensors=t.tensor(onp.asarray(dout._data)))
            return tuple(
                NDArray(onp.zeros(tuple(i.shape),
                                  onp.asarray(i.detach()).dtype))
                if i.grad is None else NDArray(i.grad.cpu().numpy())
                for i in self._tins)

    return _Bridge()(*inputs)


class TorchBlock(object):
    """Wrap a torch.nn.Module for use in imperative flows
    (≙ the reference's TorchModule op wrappers).

    Forward runs on host CPU. Under autograd.record(), input gradients
    flow back to the tape via torch_function; the module's own parameters
    accumulate torch-side .grad, steppable with any torch optimizer —
    mirroring the split ownership the reference bridge had.
    """

    def __init__(self, module):
        self.module = module

    def __call__(self, *inputs):
        def run(*tins):
            return self.module(*tins)
        return torch_function(run, *inputs)

    def parameters(self):
        return self.module.parameters()
