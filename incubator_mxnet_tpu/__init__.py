"""incubator_mxnet_tpu — a TPU-native deep-learning framework.

A ground-up re-design of Apache MXNet's capabilities (reference:
seppo0010/incubator-mxnet) for TPU hardware: JAX/XLA/Pallas compute, SPMD
parallelism over jax.sharding meshes, functional autodiff under an
imperative (Gluon-style) and symbolic (Module-style) API.

Usage mirrors MXNet::

    import incubator_mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    with mx.autograd.record():
        y = (x * 2).sum()
    y.backward()
"""
__version__ = "0.1.0"

# Multi-process (DCN) workers: jax.distributed must come up BEFORE anything
# touches the XLA backend, and importing this package initialises it (device
# queries in context/ndarray). tools/launch.py sets this env per worker.
# (config only touches os — safe this early.)
from . import config as _config

if _config.get_env("MXTPU_NUM_PROC") > 1 and \
        _config.get_env("MXTPU_COORD_ADDR"):
    import jax as _jax
    from .base import distributed_is_initialized as _dist_up
    if not _dist_up():  # user may have done it already
        _jax.distributed.initialize(_config.get_env("MXTPU_COORD_ADDR"),
                                    _config.get_env("MXTPU_NUM_PROC"),
                                    _config.get_env("MXTPU_PROC_ID"))

if _config.get_env("MXTPU_MATMUL_PRECISION"):
    import jax as _jax
    _jax.config.update("jax_default_matmul_precision",
                       _config.get_env("MXTPU_MATMUL_PRECISION"))

# telemetry depends only on config/stdlib — import it before the
# subsystems that instrument against it, and honor the autoflush knob
from . import telemetry
telemetry._maybe_autostart()

from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus, num_tpus
from . import ndarray
from . import ndarray as nd
from . import autograd
from .ndarray import random as random
from . import initializer
from . import initializer as init
from . import optimizer
from .optimizer import Optimizer
from . import metric
from . import lr_scheduler
from . import callback
from . import kvstore
from . import kvstore as kv
from . import gluon
from . import jit
from . import parallel
from . import recordio
from . import io
from . import model
from .model import save_checkpoint, load_checkpoint, FeedForward
from . import symbol
from . import symbol as sym
from .executor import Executor
from . import module
from . import module as mod
from . import rnn
from . import models
from . import ops
from . import profiler
from . import monitor
from .monitor import Monitor
from . import operator
from . import subgraph
from . import config
from . import error
from . import registry
from . import engine
from . import runtime
from . import util
from .util import is_np_array, set_np, reset_np, np_shape, np_array
from . import image
from . import rtc
from . import library
from . import attribute, name
from .attribute import AttrScope
from .name import NameManager
from . import visualization
from . import visualization as viz
from . import test_utils
from . import numpy
from . import numpy as np
from . import numpy_extension
from . import numpy_extension as npx
from . import contrib
from . import serving

# ---- env-driven startup behaviors (config.ENV_VARS documents each) ----
if config.get_env("MXTPU_SEED") is not None:
    random.seed(config.get_env("MXTPU_SEED"))

if config.get_env("MXTPU_PROFILER_AUTOSTART"):
    # MXNET_PROFILER_AUTOSTART analog: record from import, dump at exit
    import atexit as _atexit

    profiler.set_config(filename=config.get_env("MXTPU_PROFILER_FILENAME"))
    profiler.set_state("run")
    _atexit.register(profiler.dump)
