"""Weight initializers (ref python/mxnet/initializer.py)."""
from __future__ import annotations

import math

import numpy as onp

from . import ndarray as nd
from .base import registry

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal", "Orthogonal",
           "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "Mixed", "register", "create"]

_REG = registry("initializer")
register = _REG.register


class Initializer:
    """Base initializer (ref initializer.py:95). Call with (name, arr) or use
    init_weight/init_bias style dispatch by name suffix."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr):
        self.init_weight_by_name(str(name), arr)

    def init_weight_by_name(self, name, arr):
        if name.endswith("bias"):
            self._init_zero(arr)
        elif name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith("beta"):
            self._init_zero(arr)
        elif "running_mean" in name or "moving_mean" in name:
            self._init_zero(arr)
        elif "running_var" in name or "moving_var" in name:
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    def _init_zero(self, arr):
        arr._data = nd.zeros(arr.shape, dtype=arr.dtype)._data

    def _init_one(self, arr):
        arr._data = nd.ones(arr.shape, dtype=arr.dtype)._data

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


_REG.register(Zero, "zeros")
_REG.register(One, "ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr._data = nd.full(arr.shape, self.value, dtype=arr.dtype)._data


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr._data = nd.random.uniform(-self.scale, self.scale, arr.shape).astype(arr.dtype)._data


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr._data = nd.random.normal(0, self.sigma, arr.shape).astype(arr.dtype)._data


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(onp.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = nd.random.uniform(-1.0, 1.0, (nout, nin)).asnumpy()
        else:
            tmp = nd.random.normal(0.0, 1.0, (nout, nin)).asnumpy()
        u, _, v = onp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr._data = nd.array(self.scale * q.reshape(arr.shape)).astype(arr.dtype)._data


@register
class Xavier(Initializer):
    """ref initializer.py Xavier (gaussian/uniform, avg/in/out)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier requires ndim >= 2, got %s for %s" % (shape, name))
        if len(shape) > 2:
            hw_scale = float(onp.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr._data = nd.random.uniform(-scale, scale, shape).astype(arr.dtype)._data
        else:
            arr._data = nd.random.normal(0, scale, shape).astype(arr.dtype)._data


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        weight = onp.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = shape[3] / 2.0
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        size = int(onp.prod(shape))
        for i in range(size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._data = nd.array(weight).astype(arr.dtype)._data


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (ref initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = onp.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr._data = nd.array(b).astype(arr.dtype)._data


class Mixed:
    """Patterned initializer dispatch (ref initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        import re
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise ValueError("no initializer pattern matches %r" % name)


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _REG.create(name, **kwargs)
