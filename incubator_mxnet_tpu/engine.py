"""Engine controls (ref python/mxnet/engine.py, src/engine/).

TPU-native: there is no software dependency engine — XLA/PJRT owns device
ordering; bulking is automatic whole-step compilation. These controls are
kept for API parity: bulk() is a no-op scope (everything is already bulked),
set_bulk_size returns the previous value.

RESOURCE MANAGER DECISION (ref include/mxnet/resource.h, src/resource.cc —
SURVEY §2.1 #10): the reference's per-context resource manager hands ops
temp workspaces, PRNG streams and cuDNN descriptors. None of those exist as
separate subsystems here BY DESIGN:
- temp workspace: XLA's memory planner allocates per-program scratch; ops
  never request buffers.
- PRNG: functional key threading (ndarray/random.py global key eagerly;
  gluon/_functional.py FunctionalScope splits a per-call key inside
  compiled steps) replaces stateful per-device generators.
- cuDNN descriptors: no library handles exist; XLA owns kernel selection
  (the operator-tuning subsystem, src/operator/operator_tune.cc, is
  likewise subsumed by XLA autotuning).

Eager dispatch measurements live in tools/bench_eager.py (~27us/op async
dispatch vs 0.3us/op inside the fused step on v5e) — the quantified answer
to SURVEY §7 hard part (a), "eager perf without the async engine".
"""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

from .config import get_env as _get_env

_BULK_SIZE = [_get_env("MXTPU_ENGINE_BULK_SIZE")]


def set_bulk_size(size):
    """ref engine.py set_bulk_size (MXNET_ENGINE_BULK_SIZE analog)."""
    prev = _BULK_SIZE[0]
    _BULK_SIZE[0] = size
    return prev


@contextlib.contextmanager
def bulk(size):
    """ref engine.py bulk scope — no-op: XLA fuses the whole step already."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
