"""Engine controls (ref python/mxnet/engine.py, src/engine/).

TPU-native: there is no software dependency engine — XLA/PJRT owns device
ordering; bulking is automatic whole-step compilation. These controls are
kept for API parity: bulk() is a no-op scope (everything is already bulked),
set_bulk_size returns the previous value.
"""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

_BULK_SIZE = [15]


def set_bulk_size(size):
    """ref engine.py set_bulk_size (MXNET_ENGINE_BULK_SIZE analog)."""
    prev = _BULK_SIZE[0]
    _BULK_SIZE[0] = size
    return prev


@contextlib.contextmanager
def bulk(size):
    """ref engine.py bulk scope — no-op: XLA fuses the whole step already."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
