"""mx.io namespace (ref python/mxnet/io/__init__.py)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,  # noqa
                 PrefetchingIter, ImageRecordIter, MNISTIter, CSVIter,
                 LibSVMIter, ImageDetRecordIter)
