"""Data iterators (ref python/mxnet/io/io.py: DataIter :179, NDArrayIter,
MXDataIter :799; src/io/iter_image_recordio_2.cc ImageRecordIter).

TPU-native: the C++ OMP decode pipeline of the reference maps to a
thread-pooled decode + double-buffered prefetch feeding async device puts;
an optional native (C++) RecordIO scanner accelerates the file layer.
"""
from __future__ import annotations

import threading
import time as _time
from collections import namedtuple
from queue import Queue

import numpy as onp

from .. import ndarray as nd
from .. import telemetry
from ..telemetry import flightrec, watchdog
from ..ndarray import NDArray

# Input-pipeline stall observability: seconds the CONSUMER (the training
# loop) spends blocked waiting for the next batch, by iterator class. A
# wait rate near the step rate means the input pipeline, not the
# accelerator, sets the epoch time (the MLPerf-pod tuning signal).
_IO_WAIT_SECONDS = telemetry.counter(
    "mxtpu_io_wait_seconds_total",
    "Seconds the consumer spent blocked in next() waiting for a batch.",
    ("iter",))
_IO_BATCHES = telemetry.counter(
    "mxtpu_io_batches_total", "Batches delivered to the consumer.",
    ("iter",))

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "ImageRecordIter", "MNISTIter", "CSVIter",
           "LibSVMIter", "ImageDetRecordIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype="float32", layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret


class DataBatch:
    """ref io.py DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 provide_data=None, provide_label=None, bucket_key=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label
        if bucket_key is not None:  # ref rnn/io.py bucketed batches
            self.bucket_key = bucket_key


class DataIter:
    """ref io.py:179 DataIter."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(), self.getpad(),
                             self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _init_data(data, allow_empty, default_name):
    """ref io/utils.py _init_data."""
    if data is None:
        assert allow_empty
        return []
    if isinstance(data, (onp.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    out = []
    for k, v in dict(data).items():
        if not isinstance(v, NDArray):
            v = nd.array(v)
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """ref io.py NDArrayIter — batching over in-memory arrays."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.cursor = -batch_size
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = onp.arange(self.num_data)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]), v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]), v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "roll_over":
            return self.cursor + self.batch_size <= self.num_data
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrs):
        lo = self.cursor
        hi = min(lo + self.batch_size, self.num_data)
        sel = self.idx[lo:hi]
        pad = self.batch_size - (hi - lo)
        if pad:
            sel = onp.concatenate([sel, self.idx[:pad]])
        return [NDArray(v._data[sel]) for _, v in arrs]

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        hi = self.cursor + self.batch_size
        return max(0, hi - self.num_data)


class ResizeIter(DataIter):
    """ref io.py ResizeIter — rescale an iterator to a fixed #batches/epoch."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur == self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label


class PrefetchingIter(DataIter):
    """Double-buffered prefetch thread (ref io.py PrefetchingIter,
    src/io/iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        assert len(iters) == 1, "single-iter prefetching (composite deferred)"
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._queue = Queue(maxsize=prefetch)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    def _mark_producer_chain(self, ident):
        """Tag the whole wrapped chain (ResizeIter.data_iter, CSVIter
        ._inner, ...) with the producer thread's ident: inner iterators'
        next() time on THAT thread is overlapped work, not consumer wait,
        and must not hit the IO-wait counters. Scoped to the thread ident
        (re-tagged each (re)start, compared at call time) so the same
        iterator object reused directly by a consumer later counts again."""
        inner, hops = self.iter, 0
        while inner is not None and hops < 16:
            inner._io_wait_suppressed_ident = ident
            inner = getattr(inner, "data_iter", None) \
                or getattr(inner, "_inner", None)
            hops += 1

    def _start(self):
        def run():
            # watchdog channel per producer thread: silence means the
            # thread is stuck decoding OR blocked on a full queue — the
            # latter indicts the CONSUMER (it stopped taking batches),
            # which is exactly what the stall report's stacks show
            channel = watchdog.register(
                "io_prefetch:%x" % threading.get_ident())
            try:
                self._mark_producer_chain(threading.get_ident())
                while not self._stop.is_set():
                    watchdog.heartbeat(channel)
                    try:
                        batch = self.iter.next()
                    except StopIteration:
                        self._queue.put(None)
                        return
                    self._queue.put(batch)
            finally:
                # an exhausted epoch is not a stall
                watchdog.unregister(channel)
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._stop.clear()
        self.iter.reset()
        self._start()

    def next(self):
        # the queue wait IS the pipeline stall: with the prefetch thread
        # keeping up this is ~0; when it isn't, the whole decode cost
        # lands here and the counter makes it visible
        t0 = _time.perf_counter()
        batch = self._queue.get()
        wait_s = _time.perf_counter() - t0
        _IO_WAIT_SECONDS.inc(wait_s, iter="PrefetchingIter")
        flightrec.record("io_wait", iter="PrefetchingIter",
                         dur_s=round(wait_s, 6))
        if batch is None:
            raise StopIteration
        _IO_BATCHES.inc(iter="PrefetchingIter")
        return batch

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label


def _payload_is_jpeg(path):
    """Probe the first record: IRHeader (flag u32, label f32, id u64x2 = 24
    bytes, + flag extra label floats) followed by JPEG SOI bytes?"""
    import struct
    from .. import recordio
    try:
        r = recordio.MXRecordIO(path, "r")
        raw = r.read()
        r.close()
        if raw is None or len(raw) < 26:
            return False
        flag = struct.unpack("<I", raw[:4])[0]
        off = 24 + 4 * flag
        return raw[off:off + 2] == b"\xff\xd8"
    except Exception:
        return False


class ImageRecordIter(DataIter):
    """RecordIO image pipeline (ref src/io/iter_image_recordio_2.cc:880).

    Reads an .rec(+.idx), decodes + augments with a thread pool, assembles
    NCHW float batches, and prefetches. ``num_parts/part_index`` shard the
    file for distributed data loading (ref src/io/image_iter_common.h).
    """

    def __init__(self, path_imgrec=None, data_shape=(3, 224, 224), batch_size=1,
                 label_width=1, shuffle=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, rand_crop=False, rand_mirror=False,
                 num_parts=1, part_index=0, preprocess_threads=None, round_batch=True,
                 seed=0, path_imgidx=None, prefetch_buffer=2, resize=0,
                 force_python=False, dtype="float32", **kwargs):
        super().__init__(batch_size)
        from .. import recordio
        from concurrent.futures import ThreadPoolExecutor
        if preprocess_threads is None:
            from ..config import get_env
            preprocess_threads = get_env("MXTPU_CPU_WORKER_NTHREADS")

        # Fast path tier 1: FULL native pipeline — JPEG decode + augment +
        # NCHW batch assembly in C++ worker threads, zero Python in the
        # decode loop (src/image.cc; ref iter_image_recordio_2.cc:51).
        # Requires 3-channel output and JPEG payloads (probed below).
        # Tier 2: native record READER (C++ readahead) + PIL decode threads.
        # Tier 3: pure Python.
        self._native_pipe = None
        self._native = None
        self._path = path_imgrec
        self._pipe_batch = 0
        try:
            from ..native import lib as _native_lib
            if not force_python and _native_lib.available() and \
                    data_shape[0] == 3 and _payload_is_jpeg(path_imgrec):
                self._native_pipe = _native_lib.NativeImagePipeline(
                    path_imgrec, batch_size, data_shape,
                    label_width=label_width, resize_short=resize,
                    rand_crop=rand_crop, rand_mirror=rand_mirror,
                    mean_rgb=(mean_r, mean_g, mean_b),
                    std_rgb=(std_r, std_g, std_b), shuffle=shuffle,
                    seed=seed, num_threads=preprocess_threads,
                    part_index=part_index, num_parts=num_parts)
        except Exception:
            self._native_pipe = None
        if self._native_pipe is None and not force_python:
            try:
                from ..native import lib as _native_lib
                if _native_lib.available():
                    self._native = _native_lib.NativeBatchReader(
                        path_imgrec, batch_size, shuffle=shuffle, seed=seed,
                        num_threads=max(1, preprocess_threads // 2),
                        part_index=part_index, num_parts=num_parts)
            except Exception:
                self._native = None

        if path_imgidx is None and path_imgrec is not None:
            guess = path_imgrec[: path_imgrec.rfind(".")] + ".idx"
            import os
            path_imgidx = guess if os.path.exists(guess) else None
        if path_imgidx:
            self._rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            keys = self._rec.keys
        else:
            self._rec = recordio.MXRecordIO(path_imgrec, "r")
            keys = None
            # sequential scan to index record offsets
            offsets = []
            while True:
                pos = self._rec.tell()
                if self._rec.read() is None:
                    break
                offsets.append(pos)
            self._offsets = offsets
        self._keys = keys
        n = len(keys) if keys is not None else len(self._offsets)
        shard = n // num_parts
        self._lo = part_index * shard
        self._hi = n if part_index == num_parts - 1 else self._lo + shard
        self._order = onp.arange(self._lo, self._hi)
        self._shuffle = shuffle
        self._rng = onp.random.RandomState(seed)
        self._data_shape = tuple(data_shape)
        self._label_width = label_width
        self._mean = onp.array([mean_r, mean_g, mean_b], dtype="float32").reshape(3, 1, 1)
        self._std = onp.array([std_r, std_g, std_b], dtype="float32").reshape(3, 1, 1)
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        # dtype="uint8": ship raw 0..255 bytes to the device (4x smaller
        # host->device transfer — the TPU input idiom) and normalize INSIDE
        # the compiled step; requires identity mean/std here
        self._out_dtype = dtype
        if dtype == "uint8":
            assert not self._mean.any() and (self._std == 1).all(), \
                "dtype='uint8' ships raw pixels; fold mean/std into the model"
        self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        self._rec_lock = threading.Lock()
        self._cursor = 0
        self._round = round_batch
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._label_width == 1 \
            else (self.batch_size, self._label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self._pipe_batch = 0
        if self._native_pipe is not None:
            self._native_pipe.reset(reshuffle=self._shuffle)
        if self._native is not None:
            self._native.reset(reshuffle=self._shuffle)
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._cursor = 0

    def _read_record(self, i):
        from .. import recordio
        # seek+read must be atomic: decode workers share ONE file handle
        with self._rec_lock:
            if self._keys is not None:
                raw = self._rec.read_idx(self._keys[i])
            else:
                self._rec.record.seek(self._offsets[i])
                raw = self._rec.read()
        header, img = recordio.unpack_img(raw, iscolor=1)
        return header, img

    def _process(self, i):
        header, img = self._read_record(i)
        return self._augment(header, img)

    def _decode_payload(self, raw):
        from .. import recordio
        header, img = recordio.unpack_img(raw, iscolor=1)
        return self._augment(header, img)

    def _augment(self, header, img):
        c, h, w = self._data_shape
        ih, iw = img.shape[:2]
        if self._rand_crop and ih > h and iw > w:
            y0 = self._rng.randint(0, ih - h + 1)
            x0 = self._rng.randint(0, iw - w + 1)
            img = img[y0:y0 + h, x0:x0 + w]
        elif ih != h or iw != w:
            from PIL import Image
            img = onp.asarray(Image.fromarray(img).resize((w, h)))
        if img.ndim == 2:
            img = onp.stack([img] * 3, axis=-1)
        if self._rand_mirror and self._rng.rand() < 0.5:
            img = img[:, ::-1]
        chw = img.transpose(2, 0, 1).astype("float32")
        chw = (chw - self._mean) / self._std
        label = header.label if onp.ndim(header.label) else float(header.label)
        return chw, label

    def next(self):
        # synchronous decode: the consumer waits for the whole assembly,
        # so all of next() is input-pipeline wait (wrap in PrefetchingIter
        # to overlap it with the step — the counter shows when to). When a
        # PrefetchingIter drives this from its producer thread, the decode
        # is overlapped work, not consumer wait, and must not be counted
        # (thread-scoped: direct reuse of this object elsewhere counts).
        if getattr(self, "_io_wait_suppressed_ident", None) \
                == threading.get_ident():
            return self._next_impl()
        t0 = _time.perf_counter()
        batch = self._next_impl()
        _IO_WAIT_SECONDS.inc(_time.perf_counter() - t0,
                             iter=type(self).__name__)
        _IO_BATCHES.inc(iter=type(self).__name__)
        return batch

    def _next_impl(self):
        if self._native_pipe is not None:
            res = self._native_pipe.next()
            if res is None:
                raise StopIteration
            data, labels, bad = res
            self._pipe_batch += 1  # before any raise: pipe consumed the batch
            if bad:
                raise IOError(
                    "%d undecodable record(s) in %s (corrupt JPEG data); the "
                    "native pipeline fails loudly to match the Python path"
                    % (bad, self._path))
            if self._label_width == 1:
                labels = labels[:, 0]
            # last batch wraps with duplicated head records on the C++ side;
            # report them as pad so consumers (metrics/eval) can exclude them
            pad = 0
            if self._pipe_batch == self._native_pipe.num_batches:
                rem = self._native_pipe.num_records % self.batch_size
                pad = (self.batch_size - rem) % self.batch_size
            # buffers are reused by the pipeline; nd.array copies to device
            return DataBatch([nd.array(self._cast_out(data))],
                             [nd.array(labels)], pad=pad)
        if self._native is not None:
            payloads = self._native.next()
            if payloads is None:
                raise StopIteration
            self._pipe_batch += 1
            pad = 0
            if self._pipe_batch == self._native.num_batches:
                rem = self._native.num_records % self.batch_size
                pad = (self.batch_size - rem) % self.batch_size
            results = list(self._pool.map(self._decode_payload, payloads))
            data = onp.stack([r[0] for r in results])
            labels = onp.asarray(
                [onp.ravel(r[1])[: self._label_width] if onp.ndim(r[1])
                 else r[1] for r in results], dtype="float32")
            return DataBatch([nd.array(self._cast_out(data))],
                             [nd.array(labels)], pad=pad)
        n = self._hi - self._lo
        if self._cursor >= n:
            raise StopIteration
        idxs = []
        for j in range(self.batch_size):
            k = self._cursor + j
            if k >= n:
                k = k % n if self._round else n - 1
            idxs.append(self._order[k % n])
        pad = max(0, self._cursor + self.batch_size - n)
        self._cursor += self.batch_size
        results = list(self._pool.map(self._process, idxs))
        data = onp.stack([r[0] for r in results])
        return DataBatch([nd.array(self._cast_out(data))],
                         [self._stack_labels(results)], pad=pad)

    def _cast_out(self, data):
        """Honor dtype='uint8' on EVERY decode path (native pipe, native
        reader + PIL, pure Python) — the 4x-smaller transfer is the whole
        point of the option."""
        if self._out_dtype == "uint8":
            return onp.clip(data, 0, 255).astype(onp.uint8)
        return data

    def _stack_labels(self, results):
        labels = onp.asarray([onp.ravel(r[1])[:self._label_width] if
                              onp.ndim(r[1]) else r[1] for r in results],
                             dtype="float32")
        return nd.array(labels)


class MNISTIter(NDArrayIter):
    """ref src/io/iter_mnist.cc — over the (synthetic-fallback) MNIST set."""

    def __init__(self, image=None, label=None, batch_size=128, shuffle=True,
                 flat=False, **kwargs):
        from ..gluon.data.vision import MNIST
        ds = MNIST(train=True)
        imgs = ds._data.asnumpy().astype("float32") / 255.0
        labels = onp.asarray(ds._label, dtype="float32")
        imgs = imgs.reshape(len(labels), -1) if flat else \
            imgs.transpose(0, 3, 1, 2)
        super().__init__(imgs, labels, batch_size, shuffle)


class CSVIter(DataIter):
    """ref src/io/iter_csv.cc — stream a CSV as fixed-shape batches."""

    def __init__(self, data_csv=None, data_shape=None, label_csv=None,
                 label_shape=(1,), batch_size=1, **kwargs):
        super().__init__(batch_size)
        data = onp.loadtxt(data_csv, delimiter=",", dtype="float32", ndmin=2)
        self._data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            lbl = onp.loadtxt(label_csv, delimiter=",", dtype="float32", ndmin=2)
            self._label = lbl.reshape((-1,) + tuple(label_shape))
        else:
            self._label = onp.zeros((len(self._data), 1), "float32")
        self._inner = NDArrayIter(self._data, self._label, batch_size,
                                  last_batch_handle="discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class LibSVMIter(DataIter):
    """ref src/io/iter_libsvm.cc — sparse libsvm text ("label idx:val ...")
    streamed as CSR batches.

    Batches carry CSRNDArray data (ndarray/sparse.py); models consume them
    via ``sparse.dot(csr, dense)`` or densify with ``tostype('default')``.
    Feature indices are 0-based like the reference (use ``indexing_mode``
    below for 1-based files).
    """

    def __init__(self, data_libsvm=None, data_shape=None, label_libsvm=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 indexing_mode=0, **kwargs):
        super().__init__(batch_size)
        self._round = round_batch
        if tuple(label_shape) != (1,):
            raise NotImplementedError(
                "LibSVMIter supports scalar labels (label_shape=(1,))")
        from ..ndarray import sparse as _sp
        self._sp = _sp
        n_feat = data_shape[0] if isinstance(data_shape, (tuple, list)) \
            else int(data_shape)
        self._n_feat = n_feat
        labels, rows = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = []
                for tok in parts[1:]:
                    k, v = tok.split(":")
                    col = int(k) - indexing_mode
                    if not 0 <= col < n_feat:
                        raise ValueError(
                            "libsvm feature index %s out of range [0, %d) — "
                            "1-based files need indexing_mode=1" % (k, n_feat))
                    row.append((col, float(v)))
                rows.append(row)
        if label_libsvm is not None:
            with open(label_libsvm) as lf:
                labels = [float(l.split()[0]) for l in lf if l.strip()]
            if len(labels) != len(rows):
                raise ValueError(
                    "label file has %d rows but data file has %d"
                    % (len(labels), len(rows)))
        self._rows = rows
        self._labels = onp.asarray(labels, "float32")
        self._cursor = 0
        self._n = len(rows)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._n_feat))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def next(self):
        if self._cursor >= self._n:
            raise StopIteration
        idxs = []
        for j in range(self.batch_size):
            k = self._cursor + j
            if k >= self._n:
                # round_batch wraps to the head; otherwise repeat the tail
                k = k % self._n if self._round else self._n - 1
            idxs.append(k)
        pad = max(0, self._cursor + self.batch_size - self._n)
        self._cursor += self.batch_size
        data, cols, indptr = [], [], [0]
        for i in idxs:
            for k, v in self._rows[i]:
                cols.append(k)
                data.append(v)
            indptr.append(len(cols))
        csr = self._sp.CSRNDArray(
            onp.asarray(data, "float32"), onp.asarray(cols, "int32"),
            onp.asarray(indptr, "int32"), (self.batch_size, self._n_feat))
        label = nd.array(self._labels[idxs])
        return DataBatch([csr], [label], pad=pad)


class ImageDetRecordIter(ImageRecordIter):
    """ref src/io/iter_image_det_recordio.cc — detection records: the extra
    label section holds [header_width, obj_width, (id, xmin, ymin, xmax,
    ymax) * n_obj] normalized boxes; labels are padded to
    (batch, label_pad, obj_width) and boxes FLIP WITH the image when
    rand_mirror fires.

    Python-tier only (force_python — the native pipeline's fixed label_width
    does not fit variable object counts); decode still rides the thread pool.
    """

    def __init__(self, label_pad_width=16, object_width=5, **kwargs):
        self._label_pad = label_pad_width
        self._obj_width = object_width
        kwargs.setdefault("label_width", label_pad_width * object_width)
        kwargs["force_python"] = True
        super().__init__(**kwargs)

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size, self._label_pad,
                                   self._obj_width))]

    def _augment(self, header, img):
        ow = self._obj_width
        lab = onp.asarray(header.label, "float32").ravel()
        if lab.size >= 2 and lab.size > ow:
            hw, obj_w = int(lab[0]), int(lab[1])
            objs = lab[hw:]
            objs = objs[: (objs.size // obj_w) * obj_w].reshape(-1, obj_w)
            objs = objs.copy()  # header label views can be read-only
        else:
            objs = onp.zeros((0, ow), "float32")
        mirrored = self._rand_mirror and self._rng.rand() < 0.5
        c, h, w = self._data_shape
        ih, iw = img.shape[:2]
        if ih != h or iw != w:
            from PIL import Image
            img = onp.asarray(Image.fromarray(img).resize((w, h)))
        if img.ndim == 2:
            img = onp.stack([img] * 3, axis=-1)
        if mirrored:
            img = img[:, ::-1]
            if len(objs):
                x1 = objs[:, 1].copy()
                objs[:, 1] = 1.0 - objs[:, 3]
                objs[:, 3] = 1.0 - x1
        chw = img.transpose(2, 0, 1).astype("float32")
        chw = (chw - self._mean) / self._std
        padded = -onp.ones((self._label_pad, ow), "float32")
        n = min(len(objs), self._label_pad)
        if n:
            padded[:n, : objs.shape[1]] = objs[:n, :ow]
        return chw, padded

    def _stack_labels(self, results):
        return nd.array(onp.stack([r[1] for r in results]))
