"""Multi-model registry: named, versioned servables behind one batcher each
(TF-Serving ServerCore/ModelManager analog).

A *servable* is anything with ``predict_batch(*stacked_inputs) -> tuple of
stacked outputs``:

- ``contrib.serving.ServedModel`` — a loaded ``.mxtpu`` artifact (its
  predict_batch re-chunks any bucket onto the one exported batch shape),
- ``BlockServable`` below — a live Gluon block through jit.EvalStep
  (each batcher bucket compiles once in EvalStep's shape-keyed cache),
- any user object with that method (e.g. a quantized net wrapper).

Hot reload: ``load()`` on an existing name installs a NEW version and
repoints dispatch at it; batches already in flight hold a reference to
the old servable and finish on it (connection draining).
``unload(..., drain=True)`` blocks until that version's in-flight count
hits zero before dropping it.

Zero-recompile hot reload (docs/AOT.md): by default (``MXTPU_AOT_PREWARM``)
a reload PRE-WARMS every configured batcher bucket of the incoming
version through the shared AOT executable cache BEFORE dispatch is
repointed — a background warm thread compiles smallest bucket first, so
traffic cuts over as soon as the most latency-sensitive shape is ready,
while the old version keeps serving. The warm batches are synthesized
from the batcher's observed per-item signature (or an explicit
``warm_spec``); each warmed bucket emits an ``aot:warm`` span and a
``mxtpu_aot_prewarms_total`` increment. The subsequent
``unload(old, drain=True)`` therefore never leaves a compile window
inside any request's span chain.
"""
from __future__ import annotations

import logging
import threading
import time

from .. import config
from ..telemetry import faultlab, flightrec, spans
from .batcher import DynamicBatcher, ServingClosedError, _accepts_replica
from .metrics import ServingMetrics

__all__ = ["ModelRegistry", "BlockServable", "ModelNotFoundError"]

_LOG = logging.getLogger(__name__)


class ModelNotFoundError(KeyError):
    """Unknown model name (or version) — HTTP maps this to 404."""


class BlockServable:
    """Serve a live, initialized Gluon block: forwards run through
    jit.EvalStep, so each padded bucket shape compiles exactly once and is
    reused (the CachedOp-style executable cache the batcher relies on)."""

    def __init__(self, net, model_id=None):
        from .. import jit
        self._step = jit.EvalStep(net, model_id=model_id)

    def predict_batch(self, *stacked_inputs):
        from ..ndarray import NDArray
        import jax.numpy as jnp
        out = self._step(*[NDArray(jnp.asarray(x)) for x in stacked_inputs])
        outs = out if isinstance(out, tuple) else (out,)
        return tuple(o.asnumpy() for o in outs)


def _as_servable(obj):
    if hasattr(obj, "predict_batch"):
        return obj
    from ..gluon.block import Block
    if isinstance(obj, Block):
        return BlockServable(obj)
    raise TypeError("not a servable: %r (need predict_batch() or a Gluon "
                    "block)" % (obj,))


class _ModelEntry:
    """One name: version->servable map + the batcher + in-flight accounting."""

    def __init__(self, name, **batcher_kw):
        self.name = name
        self.versions = {}
        self.current_version = None
        self.metrics = ServingMetrics(model=name)
        # seed the model's default SLOs (availability; latency too when
        # MXTPU_SLO_LATENCY_MS is set) so budgets/burn gauges exist from
        # first load; the batcher's close() detaches them again. Guarded:
        # a misconfigured objective must not make the model unloadable.
        try:
            from ..telemetry import slo
            slo.REGISTRY.ensure_model(name)
        except Exception:
            _LOG.debug("SLO seeding for model %r failed", name,
                       exc_info=True)
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._inflight = {}             # version -> dispatched-batch count
        self._replica_aware = {}        # version -> predict_batch(replica=)?
        self._warming = 0               # active prewarm threads (describe)
        self._warm_target = None        # only THIS version may repoint()
        self._degraded = None           # hlolint/hlodiff refusal (describe)
        # version -> the hlolint Programs its warm parsed, retained as
        # the DIFF BASE for the next deploy's hlodiff gate (a candidate
        # regresses relative to what is routed, so the routed version's
        # parsed programs must outlive its warm). A byte-identical
        # redeploy warms nothing fresh and inherits its base's programs.
        self._version_programs = {}
        # last-known-good rollback state (docs/RESILIENCE.md): versions a
        # degraded flip quarantined (they may never auto-return to
        # dispatch) + sticky provenance of the latest rollback
        self._quarantined = set()
        self.rollback_info = None
        self.batcher = DynamicBatcher(self._dispatch, name=name,
                                      metrics=self.metrics, **batcher_kw)

    def _dispatch(self, *stacked_inputs, replica=0):
        """Resolve the CURRENT version at dispatch time (batch granularity),
        pin it with an in-flight count so unload can drain. ``replica`` —
        the batcher worker's data-parallel replica index — is forwarded to
        servables whose predict_batch declares it (device placement)."""
        with self._lock:
            version = self.current_version
            if version is None:
                raise ModelNotFoundError(
                    "model %r has no loaded version" % self.name)
            servable = self.versions[version]
            aware = self._replica_aware.get(version, False)
            self._inflight[version] = self._inflight.get(version, 0) + 1
        try:
            if aware:
                return servable.predict_batch(*stacked_inputs,
                                              replica=replica)
            return servable.predict_batch(*stacked_inputs)
        finally:
            with self._drained:
                # an unload(drain=False) may have already forgotten this
                # version (popped its _inflight slot) — the batch's results
                # must still reach their waiters
                if version in self._inflight:
                    self._inflight[version] -= 1
                self._drained.notify_all()

    def _check_replica_topology(self, servable):
        """A servable that carves its own replica groups (MeshServable)
        must agree with the batcher's worker count: fewer workers than
        groups means some groups' weight copies sit resident but are
        NEVER dispatched or prewarmed (replica index -> group is modulo),
        silently losing the intended dp capacity. Loud warning, not an
        error — a deliberate partial rollout stays possible."""
        groups = getattr(servable, "replicas", None)
        if isinstance(groups, int) and groups != self.batcher.replicas:
            _LOG.warning(
                "model %r: servable has %d replica group(s) but the "
                "batcher runs %d replica worker(s) — dispatch covers "
                "groups modulo the worker count, so %s (load with "
                "replicas=%d to match)",
                self.name, groups, self.batcher.replicas,
                "some groups will never be dispatched or prewarmed"
                if groups > self.batcher.replicas
                else "several workers will share each group",
                groups)

    def install(self, servable, version):
        """Install (version=None: the next one) and repoint dispatch.
        Version choice and installation are one atomic step so concurrent
        hot-reloads cannot pick the same number."""
        self._check_replica_topology(servable)
        with self._lock:
            if version is None:
                version = (max(self.versions) + 1) if self.versions else 1
            self.versions[version] = servable
            self._replica_aware[version] = \
                _accepts_replica(servable.predict_batch)
            # a fresh servable under a reused number is a new deploy —
            # its predecessor's quarantine must not shadow it
            self._quarantined.discard(version)
            self.current_version = version
            # a direct install supersedes any in-flight warm: its stale
            # repoint()s must not drag dispatch back to an older version
            self._warm_target = version
            self._degraded = None
            return version

    def add_version(self, servable, version):
        """install() WITHOUT the repoint: the version becomes routable
        only via an explicit repoint() — the prewarm path registers the
        incoming version here, warms it, then cuts dispatch over. Marks
        the version as the warm target: overlapping hot-reloads each
        register here, and only the NEWEST registration's warm thread may
        repoint (a slower older warm finishing last must not pin dispatch
        to a stale model). On a FIRST load (nothing routable yet) the
        version is made current immediately — a model whose load() is
        still warming must answer with a lazy compile, not a 404."""
        self._check_replica_topology(servable)
        with self._lock:
            if version is None:
                version = (max(self.versions) + 1) if self.versions else 1
            self.versions[version] = servable
            self._replica_aware[version] = \
                _accepts_replica(servable.predict_batch)
            self._quarantined.discard(version)
            self._warm_target = version
            self._degraded = None
            if self.current_version is None:
                self.current_version = version
            return version

    def set_degraded(self, reason):
        """Flip this model's health/describe() to degraded with ``reason``
        — the numerics sentinel's shadow-breach callback lands here (the
        hlolint refusal shape).

        Last-known-good rollback (MXTPU_RESILIENCE_ROLLBACK, default on):
        when a PRIOR healthy version is still resident, dispatch is
        repointed to it instead of serving degraded — the bad version is
        quarantined (it can never auto-return via a late repoint()), the
        rollback lands on flightrec ``rolled_back_to`` and as sticky
        ``describe()`` provenance, and the degraded flag clears because
        traffic is on a healthy version again. With no prior version (or
        rollback off) the flag is sticky until the next
        install/add_version: a divergence breach is an operator decision,
        not a flap."""
        reason = str(reason)
        rolled = None
        with self._lock:
            self._degraded = reason
            bad = self.current_version
            if bad is not None and self._rollback_enabled():
                prior = [v for v in self.versions
                         if v < bad and v not in self._quarantined]
                if prior:
                    to = max(prior)
                    self._quarantined.add(bad)
                    self.current_version = to
                    self.rollback_info = {"from_version": bad,
                                          "to_version": to,
                                          "reason": reason}
                    # traffic is back on a known-good version: the model
                    # is serving healthy again (provenance stays sticky)
                    self._degraded = None
                    rolled = (bad, to)
        if rolled is not None:
            bad, to = rolled
            _LOG.warning(
                "model %r v%s flipped degraded (%s) — ROLLED BACK to "
                "last known good v%s (v%s quarantined)",
                self.name, bad, reason, to, bad)
            flightrec.record("rolled_back_to", model=self.name,
                             from_version=bad, to_version=to,
                             reason=reason)

    @staticmethod
    def _rollback_enabled():
        try:
            return bool(config.get_env("MXTPU_RESILIENCE_ROLLBACK"))
        except Exception:
            return True

    def repoint(self, version):
        """Cut dispatch over to ``version`` — only honored while it is
        still the newest warm target (idempotent; no-op once a newer
        load()/install() superseded it, the version was dropped, or a
        degraded flip quarantined it: a warm thread finishing after a
        rollback must not drag dispatch back to the bad version)."""
        with self._lock:
            if (version in self.versions and version == self._warm_target
                    and version not in self._quarantined):
                self.current_version = version

    def warm(self, servable, version, item_sig):
        """Pre-warm every configured (bucket x replica) pair of
        ``servable`` through the shared AOT executable cache, SMALLEST
        bucket first (all its replicas, then the next bucket); dispatch is
        repointed at ``version`` right after the first bucket's replicas
        compile, so traffic cuts over early while bigger buckets keep
        warming. For a replica-aware servable each warm call carries the
        replica index — a device-pinned executor compiles one executable
        per replica, and missing any pair would put that compile into the
        post-cutover window; replica-unaware servables share one
        executable, so each bucket warms once. Runs on the prewarm thread;
        after the early cutover the batcher workers can dispatch (and even
        compile-miss) the same model concurrently — safe because every
        trace window holds the net's trace lock exclusively, dispatches
        capture their argument snapshots under the same lock
        (jit._net_trace_lock), and cache misses are single-flight per key.
        Always leaves dispatch repointed — a warm failure degrades to the
        old lazy-compile behavior, never to an unroutable model."""
        import numpy as onp
        from .. import aot
        aware = _accepts_replica(servable.predict_batch)
        n_rep = self.batcher.replicas if aware else 1
        with self._lock:
            self._warming += 1
        warmed_programs = []
        # the hlodiff base: the version traffic is routed to as this warm
        # begins (its own warm retained its parsed programs). Captured
        # once up front — the first bucket's early cutover repoints
        # current_version at the INCOMING version mid-warm, and later
        # buckets must still diff against the outgoing one.
        with self._lock:
            _cur = self.current_version
            base_programs = (self._version_programs.get(_cur)
                             if _cur is not None and _cur != version
                             else None)
        try:
            for b in sorted(set(self.batcher.buckets)):
                fresh = []
                n0 = len(warmed_programs)
                try:
                    # faultlab site "registry.load" (warm stage): an
                    # injected exception exercises the partial-warm
                    # fallback below — still swaps, compiles lazily
                    if faultlab.armed:
                        faultlab.fire("registry.load", model=self.name,
                                      stage="warm", bucket=b)
                    synth = [onp.zeros((b,) + tuple(shape),
                                       dtype=onp.dtype(dt))
                             for shape, dt in item_sig]
                    with aot.collect_inserts() as fresh:
                        for r in range(n_rep):
                            with spans.span("aot:warm", model=self.name,
                                            version=version, bucket=b,
                                            replica=r):
                                if aware:
                                    servable.predict_batch(*synth,
                                                           replica=r)
                                else:
                                    servable.predict_batch(*synth)
                            try:
                                self.metrics.inc("prewarm_count")
                            except Exception:
                                _LOG.debug("prewarm_count update failed",
                                           exc_info=True)
                except Exception:
                    # the incoming model may not accept the observed
                    # signature at all (input shape changed): stop warming
                    # but still swap — first dispatch compiles lazily,
                    # exactly the pre-AOT behavior. Anything the partial
                    # warm DID insert (e.g. replica 0's compile before
                    # replica 1 raised) is still gated: the finally's
                    # repoint must not cut over an ungated error-severity
                    # artifact.
                    _LOG.warning(
                        "prewarm of model %r v%s bucket %d failed; "
                        "remaining buckets will compile on first dispatch",
                        self.name, version, b, exc_info=True)
                    if not self._hlolint_gate(version, fresh,
                                              warmed_programs):
                        return
                    if not self._hlodiff_gate(version, fresh,
                                              warmed_programs[n0:],
                                              base_programs):
                        return
                    break
                # hlolint load gate: the bucket's freshly compiled/loaded
                # artifacts are linted BEFORE dispatch is repointed at
                # them — an error-severity finding (fp64 leak, host
                # round-trip, predicted HBM overrun) refuses the cutover
                # and drops the version (the finally's repoint() then
                # no-ops: the version is gone). A refusal on a LATER
                # bucket rolls back a version already serving its earlier
                # buckets — _hlolint_gate logs which case happened.
                if not self._hlolint_gate(version, fresh, warmed_programs):
                    return
                # the differential gate runs strictly AFTER the absolute
                # one: a program must first be valid in isolation, then
                # no worse than the version it replaces (gate ordering,
                # docs/STATIC_ANALYSIS.md)
                if not self._hlodiff_gate(version, fresh,
                                          warmed_programs[n0:],
                                          base_programs):
                    return
                self.repoint(version)
            self._hlolint_cross(warmed_programs)
            self._hlodiff_ladder(warmed_programs, base_programs)
            with self._lock:
                if version in self.versions:
                    # retain this warm's programs as the next deploy's
                    # diff base; a byte-identical redeploy (all cache
                    # hits: nothing fresh parsed) inherits its own base
                    self._version_programs[version] = (
                        list(warmed_programs) or list(base_programs or []))
        finally:
            self.repoint(version)
            with self._lock:
                self._warming -= 1

    def _hlolint_gate(self, version, entries, collect=None):
        """Lint one warmed bucket's new AOT entries (tools/hlolint via
        their persisted artifacts). Returns False — after unrouting and
        dropping ``version`` with a loud degraded reason — when an
        error-severity finding means this compiled program must not take
        traffic; True (including on any gate-infrastructure failure:
        the gate must never break a load it cannot judge) otherwise.
        ``collect`` accumulates the parsed Programs so the cross-program
        pass after the full warm never re-deserializes the artifacts.

        Each bucket is gated before ITS repoint, but earlier buckets'
        repoints have already happened — a refusal on a later bucket is
        therefore a ROLLBACK (the version served traffic on its earlier
        buckets while this one warmed), and the log says which case
        occurred. The version drop uses the unload(drain=False)
        mechanics: in-flight dispatches on the dropped version still
        deliver their results (_dispatch tolerates a popped _inflight
        slot)."""
        if not entries:
            return True
        try:
            if not config.get_env("MXTPU_HLOLINT_GATE"):
                return True
            from tools.hlolint import gate as hlogate
        except ImportError:
            return True         # tools-less install: no gate to run
        try:
            errors, warns = hlogate.lint_entries(entries, collect=collect)
            hlogate.publish(errors + warns, model=self.name)
        except Exception:
            # fail open, but LOUDLY: a broken gate means error-severity
            # artifacts cut over unjudged from here on
            _LOG.warning("hlolint gate failed open for model %r — "
                         "artifacts are cutting over UNLINTED",
                         self.name, exc_info=True)
            return True
        if not errors:
            return True
        reason = "; ".join("%s %s: %s" % (f.rule, f.path, f.message)
                           for f in errors[:3])
        self._refuse_load(version, entries, "hlolint",
                          "load refused by hlolint: %s" % reason,
                          reason, len(errors))
        return False

    def _hlodiff_gate(self, version, entries, cand_programs,
                      base_programs):
        """The DIFFERENTIAL deploy gate (tools/hlodiff): the bucket's
        freshly warmed programs diff against the programs of the version
        traffic was routed to when the warm began — runs strictly after
        the absolute hlolint pass, so only programs already valid in
        isolation reach it. Error-severity D-findings (D001 FLOPs
        growth / D003 donation regression on the serve-/decode-kind
        path) refuse the cutover exactly like an hlolint refusal — the
        degraded reason is ``load refused by hlodiff:<rule>: ...`` and
        dispatch rides the same last-known-good rollback. Warn findings
        publish to flightrec + mxtpu_hlodiff_findings_total and never
        block. Skips when there is no base (first load, tools-less
        install, MXTPU_HLODIFF_GATE off) and fails OPEN loudly on any
        gate-infrastructure error — same contract as _hlolint_gate.

        Runs PAIR rules only: the cross-program set rules (D006 bucket
        ladder) need the complete candidate set, and mid-warm this
        bucket's programs are necessarily a partial ladder that would
        false-fire "lost bucket" against the base on every multi-bucket
        deploy — _hlodiff_ladder covers them once after the loop."""
        if not entries or not cand_programs or not base_programs:
            return True
        try:
            if not config.get_env("MXTPU_HLODIFF_GATE"):
                return True
            from tools.hlodiff import gate as dgate
            from tools.hlodiff.rules import RULES as _pair_rules
        except ImportError:
            return True         # tools-less install: no gate to run
        try:
            errors, warns = dgate.diff_programs(
                base_programs, cand_programs,
                only_rules=frozenset(_pair_rules))
            dgate.publish(errors + warns, model=self.name)
        except Exception:
            _LOG.warning("hlodiff gate failed open for model %r — the "
                         "deploy is cutting over UNDIFFED",
                         self.name, exc_info=True)
            return True
        if not errors:
            return True
        reason = "; ".join("%s %s: %s" % (f.rule, f.path, f.message)
                           for f in errors[:3])
        self._refuse_load(version, entries, "hlodiff",
                          "load refused by hlodiff:%s: %s"
                          % (errors[0].rule, reason),
                          reason, len(errors))
        return False

    def _hlodiff_ladder(self, warmed_programs, base_programs):
        """The cross-program D-rules (D006 bucket-ladder change) over
        the FULL warmed set, after every bucket gated and repointed —
        the per-bucket differential gate excludes them because a
        mid-warm candidate ladder is always partial. Warn severity by
        construction: publishes to flightrec + the findings counter,
        never refuses (the version is already serving its buckets)."""
        if not warmed_programs or not base_programs:
            return
        try:
            if not config.get_env("MXTPU_HLODIFF_GATE"):
                return
            from tools.hlodiff import gate as dgate
            from tools.hlodiff.rules import SET_RULES as _set_rules
            errors, warns = dgate.diff_programs(
                base_programs, warmed_programs,
                only_rules=frozenset(_set_rules))
            dgate.publish(errors + warns, model=self.name)
        except Exception:
            _LOG.debug("hlodiff ladder pass failed open",
                       exc_info=True)

    def _refuse_load(self, version, entries, tool, degraded_reason,
                     reason, n_errors):
        """Shared refusal mechanics for the load gates: evict the
        refused executables from the process-wide AOT cache (a retried
        load must recompile/re-load, which re-inserts and therefore
        re-gates — a warm cache HIT collects nothing and would cut the
        refused program over ungated), unroute and drop ``version`` with
        a loud sticky degraded reason, and when the version was already
        current repoint dispatch at the last known good with the same
        rollback provenance the degraded-flip path records (the degraded
        reason stays — the refused DEPLOY still needs the operator)."""
        from .. import aot
        for entry in entries:
            try:
                aot.CACHE.discard(entry.key)
            except Exception:
                _LOG.debug("refused-entry cache eviction failed",
                           exc_info=True)
        with self._lock:
            was_current = self.current_version == version
            self.versions.pop(version, None)
            self._replica_aware.pop(version, None)
            self._inflight.pop(version, None)
            self._quarantined.discard(version)
            self._version_programs.pop(version, None)
            self._degraded = degraded_reason
            if was_current:
                self.current_version = (max(self.versions)
                                        if self.versions else None)
                if self.current_version is not None:
                    self.rollback_info = {
                        "from_version": version,
                        "to_version": self.current_version,
                        "reason": degraded_reason}
        _LOG.error(
            "model %r v%s REFUSED by %s (%d error finding(s)) — %s: %s",
            self.name, version, tool, n_errors,
            "dispatch ROLLED BACK (the version was already current — a "
            "first load, or earlier buckets cut over — while warming "
            "continued)"
            if was_current else "dispatch was NOT cut over",
            reason)
        try:
            flightrec.record("%s_refused" % tool, model=self.name,
                             version=version, reason=reason,
                             rolled_back=was_current)
            if was_current and self.rollback_info is not None \
                    and self.rollback_info["from_version"] == version:
                flightrec.record("rolled_back_to", model=self.name,
                                 from_version=version,
                                 to_version=self.rollback_info["to_version"],
                                 reason="%s refusal" % tool)
        except Exception:
            _LOG.debug("%s_refused flightrec record dropped", tool,
                       exc_info=True)

    def _hlolint_cross(self, programs):
        """The cross-program pass (H005 needs the whole bucket ladder) —
        warn-only by construction, runs once after the full warm over the
        Programs the per-bucket gates already parsed (no second
        deserialize of the same artifacts)."""
        if not programs:
            return
        try:
            if not config.get_env("MXTPU_HLOLINT_GATE"):
                return
            from tools.hlolint import gate as hlogate
        except ImportError:
            return
        try:
            hlogate.publish(hlogate.lint_programs_set(programs),
                            model=self.name)
        except Exception:
            _LOG.warning("hlolint cross-program pass failed for model %r",
                         self.name, exc_info=True)

    def drop(self, version, drain, timeout, wait_queue_empty=False):
        """Remove one version. With a successor available, dispatch is
        repointed FIRST so the victim can drain; with drain of the LAST
        version the victim stays routable until queued + in-flight work
        settles (wait_queue_empty; the registry pauses intake around this)
        and is unrouted only at removal — a timed-out drain changes no
        routing at all. (A batch the worker has dequeued but not yet begun
        dispatching at the instant the predicate passes can still lose the
        race and fail loudly — microsecond window on the single worker.)"""
        with self._drained:
            remaining = [v for v in self.versions if v != version]
            if version == self.current_version and remaining:
                self.current_version = max(remaining)
            if drain:
                def settled():
                    return (self._inflight.get(version, 0) == 0
                            and (not wait_queue_empty
                                 or self.batcher.queue_depth() == 0))
                # poll as well as wait on notify: the batcher's deadline-
                # expiry path consumes queued requests WITHOUT a dispatch
                # (so nothing notifies this condition) — a pure wait_for
                # would sleep the whole timeout after such a drain finished
                end = time.monotonic() + timeout
                while not settled():
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            "model %r v%s still has in-flight batches"
                            % (self.name, version))
                    self._drained.wait(min(remaining, 0.05))
            self.versions.pop(version, None)
            self._inflight.pop(version, None)
            self._replica_aware.pop(version, None)
            self._version_programs.pop(version, None)
            # install()'s max()+1 can reuse a dropped number: a stale
            # quarantine entry must not poison the future deploy
            self._quarantined.discard(version)
            if version == self.current_version:
                self.current_version = (max(self.versions)
                                        if self.versions else None)

    def describe(self):
        try:
            from ..telemetry import slo
            slos = slo.REGISTRY.names_for_model(self.name)
        except Exception:
            slos = []
        with self._lock:
            return {"name": self.name,
                    "versions": sorted(self.versions),
                    "current_version": self.current_version,
                    "slos": slos,
                    "warming": self._warming > 0,
                    "degraded": self._degraded,
                    "rolled_back": self.rollback_info,
                    "queue_depth": self.batcher.queue_depth(),
                    "queue_size": self.batcher.queue_size,
                    "replicas": self.batcher.replicas,
                    "dead_replicas": self.batcher.dead_replicas(),
                    "replica_depths": self.batcher.replica_depths(),
                    "max_batch_size": self.batcher.max_batch_size,
                    "batch_timeout_ms": self.batcher.batch_timeout_ms}


class ModelRegistry:
    """Thread-safe name -> _ModelEntry map; the server front-end's substrate."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._generators = {}           # name -> GenerativeEngine
        self._closed = False
        # the continuous profiler's overload signal: it skips a capture
        # cycle while any of this registry's queues runs hot — profiling
        # must never widen the overload it exists to explain
        from ..telemetry import profstats
        self._probe_name = "serving-registry-%d" % id(self)
        profstats.add_load_probe(self._probe_name, self._queue_occupancy)

    def _queue_occupancy(self):
        """Max replica-queue occupancy across loaded models, in [0, 1]."""
        with self._lock:
            entries = list(self._entries.values())
        occ = 0.0
        for e in entries:
            cap = max(1, e.batcher.total_queue_size)
            occ = max(occ, e.batcher.queue_depth() / cap)
        return occ

    # ------------------------------------------------------------ lifecycle
    def load(self, name, servable, version=None, prewarm=None,
             warm_spec=None, warm_timeout=None, **batcher_kw):
        """Register (or hot-reload) ``name``. Returns the installed version.

        First load creates the entry + its batcher (batcher_kw:
        max_batch_size, batch_timeout_ms, queue_size, buckets,
        default_deadline_ms — defaults come from MXTPU_SERVE_*). A load on
        an existing name installs the next version and repoints dispatch;
        in-flight batches finish on the old servable.

        Prewarm (``prewarm`` default: MXTPU_AOT_PREWARM): when a per-item
        input signature is known — ``warm_spec`` (a list of
        ``(shape, dtype)`` per model input, no batch dim) or the batcher's
        observed signature from prior traffic — the incoming version is
        registered un-routed and every configured bucket is compiled
        through the shared AOT cache on a background thread, smallest
        bucket first; dispatch cuts over right after the first bucket and
        this call returns once all buckets are warm (bounded by
        ``warm_timeout`` / MXTPU_AOT_WARM_TIMEOUT_S — on timeout the warm
        keeps going in the background and dispatch still cuts over as soon
        as one bucket is ready). With no signature available (first load,
        no warm_spec) or prewarm=False, dispatch repoints immediately and
        buckets compile lazily on first dispatch.
        """
        # faultlab site "registry.load" (load stage): an injected
        # exception fails this load() loudly at the caller, before any
        # entry state changes
        if faultlab.armed:
            faultlab.fire("registry.load", model=name, stage="load")
        servable = _as_servable(servable)
        # install/add_version happens INSIDE the registry lock: paired
        # with unload()'s locked entry-removal check this makes
        # load-vs-unload-of-the-last-version atomic (never installs into
        # an entry whose batcher a concurrent unload is closing), and
        # concurrent hot-reloads serialize on the entry lock inside
        # install()/add_version()
        warm_thread = None
        with self._lock:
            if self._closed:
                raise RuntimeError("registry is shut down")
            entry = self._entries.get(name)
            if entry is None:
                entry = _ModelEntry(name, **batcher_kw)
                self._entries[name] = entry
            elif batcher_kw:
                raise ValueError("batcher options are fixed at first load "
                                 "of %r" % name)
            if prewarm is None:
                prewarm = config.get_env("MXTPU_AOT_PREWARM")
            item_sig = warm_spec if warm_spec is not None \
                else entry.batcher.last_item_sig
            if prewarm and item_sig:
                version = entry.add_version(servable, version)
                warm_thread = threading.Thread(
                    target=entry.warm, args=(servable, version, item_sig),
                    daemon=True, name="mxtpu-aot-warm-%s" % name)
                warm_thread.start()
            else:
                version = entry.install(servable, version)
        if warm_thread is not None:
            if warm_timeout is None:
                warm_timeout = config.get_env("MXTPU_AOT_WARM_TIMEOUT_S")
            warm_thread.join(warm_timeout)
            if warm_thread.is_alive():
                _LOG.warning(
                    "prewarm of model %r v%s still running after %.1fs — "
                    "returning; remaining buckets finish in the background",
                    name, version, warm_timeout)
        return version

    def unload(self, name, version=None, drain=True, timeout=30.0):
        """Drop one version (default: current). Dropping the last version
        shuts the entry's batcher down and forgets the name."""
        entry = self._entry(name)
        if version is None:
            version = entry.current_version
        if version not in entry.versions:
            raise ModelNotFoundError("model %r has no version %s"
                                     % (name, version))
        with entry._lock:
            last = set(entry.versions) == {version}
        if last and drain:
            # no successor to repoint at: pause intake so the queue can
            # only shrink, let the departing version serve every request
            # already accepted (never a spurious 404), and unroute at the
            # end; a timed-out drain reopens intake with routing untouched
            entry.batcher.pause_intake()
        try:
            entry.drop(version, drain, timeout, wait_queue_empty=last)
        except TimeoutError:
            if last and drain:
                entry.batcher.resume_intake()
            raise
        close_batcher = False
        with self._lock:
            # re-check under the registry lock: a concurrent load() (which
            # installs inside this lock) may have revived the entry
            if not entry.versions and self._entries.get(name) is entry:
                self._entries.pop(name)
                close_batcher = True
        if close_batcher:
            entry.batcher.close(drain=drain)
        elif last and drain:
            # a concurrent load() revived the entry mid-drain: the new
            # version must serve, so the pause cannot stick
            entry.batcher.resume_intake()

    def close(self, drain=True):
        """Graceful shutdown of every model's batcher (queue drained first)
        and every generator's decode loop (live sequences retire)."""
        from ..telemetry import profstats
        profstats.remove_load_probe(self._probe_name)
        with self._lock:
            self._closed = True
            entries = list(self._entries.values())
            generators = list(self._generators.values())
        for entry in entries:
            entry.batcher.close(drain=drain)
        for engine in generators:
            try:
                engine.close()
            except Exception:
                _LOG.debug("generator close failed", exc_info=True)

    # ----------------------------------------------------------- generators
    def load_generator(self, name, engine=None, **engine_kw):
        """Register a generative engine under ``name`` (POST /generate
        routes on it). Pass a constructed ``GenerativeEngine`` or let this
        build one (``engine_kw`` forwards to its constructor; prewarm
        happens inside construction, so by the time this returns the
        decode/prefill buckets are compiled and — under
        MXTPU_HLOLINT_GATE — their artifacts linted). One engine per
        name; re-registering an open name is an error (a generator holds
        a live KV pool — hot-swap means close + load, there is no
        version ladder to drain across)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("registry is shut down")
            old = self._generators.get(name)
            if old is not None and not old.closed:
                raise ValueError("generator %r is already loaded (close "
                                 "it before replacing)" % name)
        if engine is None:
            from .generate import GenerativeEngine
            engine = GenerativeEngine(name=name, **engine_kw)
        # seed the model-level availability SLO so /debug/slo carries the
        # generator from first load (the per-tenant inter_token
        # objectives appear on first submit); engine.close() detaches all
        # of them
        try:
            from ..telemetry import slo
            slo.REGISTRY.ensure_model(name)
        except Exception:
            _LOG.debug("SLO seeding for generator %r failed", name,
                       exc_info=True)
        with self._lock:
            if self._closed:
                engine.close()
                raise RuntimeError("registry is shut down")
            self._generators[name] = engine
        return engine

    def generator(self, name):
        """The live engine for ``name`` — ModelNotFoundError (-> 404)
        when absent or already closed; ServingClosedError (-> 503, NOT
        429) while the decode loop is DEAD and awaiting the supervisor:
        the model exists but cannot serve, and advertising queue-full
        retryability would be a lie."""
        with self._lock:
            engine = self._generators.get(name)
            names = sorted(n for n, e in self._generators.items()
                           if not e.closed) if engine is None else None
        if engine is None or engine.closed:
            raise ModelNotFoundError("no generator %r loaded (have: %s)"
                                     % (name, names or sorted(
                                         self._generators)))
        if not engine.alive:
            raise ServingClosedError(
                "generator %r decode loop is dead (awaiting supervisor "
                "revival)" % name)
        return engine

    def generators(self):
        """Describe every generator EXCEPT one whose decode loop died
        (not alive, not closed): GET /v1/models must not advertise a
        model that cannot serve — it relists the moment the supervisor
        resurrects the loop."""
        with self._lock:
            engines = list(self._generators.values())
        return [e.describe() for e in engines if e.alive or e.closed]

    # ------------------------------------------------------------ resilience
    def batchers(self):
        """{name -> DynamicBatcher} snapshot — the supervisor's replica
        scan surface (serving/resilience.py)."""
        with self._lock:
            return {n: e.batcher for n, e in self._entries.items()}

    def engines(self):
        """{name -> GenerativeEngine} snapshot — the supervisor's decode
        loop scan surface (serving/resilience.py)."""
        with self._lock:
            return dict(self._generators)

    # ------------------------------------------------------------ inference
    def _entry(self, name):
        with self._lock:
            entry = self._entries.get(name)
            names = sorted(self._entries) if entry is None else None
        if entry is None:
            raise ModelNotFoundError("no model %r loaded (have: %s)"
                                     % (name, names))
        return entry

    def submit(self, name, *inputs, deadline_ms=None, request_id=None,
               tenant=None):
        return self._entry(name).batcher.submit(
            *inputs, deadline_ms=deadline_ms, request_id=request_id,
            tenant=tenant)

    def predict(self, name, *inputs, deadline_ms=None, timeout=None,
                request_id=None, tenant=None):
        return self._entry(name).batcher.predict(
            *inputs, deadline_ms=deadline_ms, timeout=timeout,
            request_id=request_id, tenant=tenant)

    def metrics(self, name):
        return self._entry(name).metrics

    # ------------------------------------------------------------- numerics
    def register_shadow(self, name, reference, stride=None, threshold=None):
        """Attach ``reference`` (servable or Gluon block) as ``name``'s
        numerics shadow: a deterministic stride of dispatched batches is
        re-executed through it off the hot path and compared
        (telemetry/numwatch.py). A max-abs-diff breach beyond
        ``threshold`` (default MXTPU_SHADOW_THRESHOLD) flips this model's
        describe()/health() to degraded — the int8-vs-bf16 divergence
        gate ROADMAP's serving-quantization item needs."""
        entry = self._entry(name)
        reference = _as_servable(reference)
        from ..telemetry import numwatch
        numwatch.register_shadow(name, reference, stride=stride,
                                 threshold=threshold,
                                 on_breach=entry.set_degraded)

    def unregister_shadow(self, name):
        """Detach ``name``'s numerics shadow (the degraded flag, if
        already flipped, stays until the next load)."""
        from ..telemetry import numwatch
        return numwatch.unregister_shadow(name)

    # ------------------------------------------------------------ inspection
    def models(self):
        with self._lock:
            entries = list(self._entries.values())
        return [e.describe() for e in entries]

    def metrics_snapshot(self):
        with self._lock:
            entries = list(self._entries.items())
        return {name: e.metrics.snapshot() for name, e in entries}

    def health(self):
        """healthy | degraded (any queue >= 80% full) | unhealthy (shut down
        or a dead worker thread) — the load-balancer-facing contract."""
        with self._lock:
            closed = self._closed
            entries = list(self._entries.values())
            generators = list(self._generators.values())
        if closed:
            return {"status": "unhealthy", "reason": "shutting down"}
        for e in entries:
            if not e.batcher.alive and not e.batcher.closed:
                return {"status": "unhealthy",
                        "reason": "worker thread dead for model %r" % e.name}
        for g in generators:
            if not g.alive and not g.closed:
                return {"status": "unhealthy",
                        "reason": "decode loop dead for generator %r"
                                  % g.name}
        for e in entries:
            if e.batcher.queue_depth() >= 0.8 * e.batcher.total_queue_size:
                return {"status": "degraded",
                        "reason": "queue >= 80%% for model %r" % e.name,
                        "queue_depth": e.batcher.queue_depth()}
        for e in entries:
            if e._degraded:
                # a measurement-driven gate flipped this model's flag — a
                # load refused by hlolint, or a shadow-divergence breach
                # from the numerics sentinel: serving continues, but the
                # operator must see it
                return {"status": "degraded",
                        "reason": "model %r degraded: %s"
                                  % (e.name, e._degraded)}
        for e in entries:
            dead = e.batcher.dead_replicas()
            if dead:
                # survivors still serve (the router skips the dead set),
                # but capacity shrank — the load balancer should know
                return {"status": "degraded",
                        "reason": "model %r lost replica worker(s) %s"
                                  % (e.name, dead)}
        return {"status": "healthy", "models": len(entries)}
