"""Structured per-request access log: one bounded ring of terminal
predict outcomes, each a small JSON-able record

    {ts, request_id, tenant, model, code, shed_reason, latency_ms,
     queue_ms, batch_ms, device_ms, replica, bucket}

— the request-granular complement to the aggregate counters: *which*
request from *which tenant* was shed, how long it queued, which replica
dispatched it. The HTTP front-end (server.py) records every terminal
outcome, including 4xx/shed ones (``shed_reason`` is ``queue_full`` for
429 backpressure and ``deadline`` for 504 — the machine-readable split
clients used to string-match out of the error text); the batch-stage
legs (queue/batch/device, replica, bucket) come from the dispatch facts
the batcher worker attaches to each request and are null for requests
that never reached a dispatch.

Surfaces:

- the in-memory ring (``MXTPU_ACCESSLOG_SIZE`` records, oldest aged
  out; telemetry/ringbuf.py — appends never raise into the serving
  path), served at ``GET /debug/requests?n=`` as JSONL;
- optional sampled JSONL file export: ``MXTPU_ACCESSLOG_FILE`` appends
  every record the **deterministic stride sampler** selects
  (``MXTPU_ACCESSLOG_SAMPLE``: 1.0 = all, 0.5 = every second record, 0
  = none) — deterministic so two identical runs export identical files,
  and so tests pin exact sample membership without probability bounds.

Tenants: ``clamp_tenant`` normalizes the raw ``X-MXTPU-Tenant`` header
(default ``"default"`` when absent, length-clamped, control characters
stripped) BEFORE it becomes a metric label or log field — the telemetry
registry's cardinality clamp (MXTPU_TELEMETRY_MAX_SERIES -> ``_other_``)
is the backstop against hostile random tenants; this keeps single
values bounded too.
"""
from __future__ import annotations

import json
import logging
import threading

from ..telemetry.ringbuf import BoundedRing

__all__ = ["TENANT_HEADER", "DEFAULT_TENANT", "clamp_tenant", "record",
           "snapshot", "tail", "export_jsonl", "reset"]

_LOG = logging.getLogger(__name__)

#: request header naming the tenant an outcome is accounted to
TENANT_HEADER = "X-MXTPU-Tenant"
DEFAULT_TENANT = "default"
_TENANT_MAXLEN = 64

_ring = BoundedRing("MXTPU_ACCESSLOG_SIZE", min_size=16)
_export_lock = threading.Lock()     # file handle + stride counter
_export_count = 0
_export_file = None                 # cached (path, handle) — the export
                                    # sits on the response path, so it
                                    # must not pay open/close per record


def clamp_tenant(raw):
    """Normalize a raw tenant header value into a bounded label: None /
    empty -> ``"default"``; control characters dropped; length clamped.
    Cardinality stays the registry clamp's job — this only bounds one
    value's size."""
    if raw is None:
        return DEFAULT_TENANT
    cleaned = "".join(c for c in str(raw).strip() if c.isprintable())
    return cleaned[:_TENANT_MAXLEN] or DEFAULT_TENANT


def _now_s():
    from .. import profiler
    return profiler.now_us() / 1e6   # epoch-anchored monotonic (NTP-safe)


def record(request_id, tenant, model, code, latency_ms=None,
           shed_reason=None, queue_ms=None, batch_ms=None, device_ms=None,
           replica=None, bucket=None):
    """Append one terminal-outcome record (and maybe export it). Never
    raises into the serving path; a failed file export is debug-logged
    (a full disk must not fail the request it records)."""
    try:
        rec = {"ts": round(_now_s(), 6), "request_id": request_id,
               "tenant": tenant, "model": model, "code": int(code),
               "shed_reason": shed_reason,
               "latency_ms": (round(latency_ms, 3)
                              if latency_ms is not None else None),
               "queue_ms": (round(queue_ms, 3)
                            if queue_ms is not None else None),
               "batch_ms": (round(batch_ms, 3)
                            if batch_ms is not None else None),
               "device_ms": (round(device_ms, 3)
                             if device_ms is not None else None),
               "replica": replica, "bucket": bucket}
        _ring.append(rec)
        _maybe_export(rec)
    except Exception:
        _LOG.debug("access-log record failed", exc_info=True)


def _maybe_export(rec):
    from .. import config
    path = config.get_env("MXTPU_ACCESSLOG_FILE")
    if not path:
        return
    rate = min(1.0, max(0.0, config.get_env("MXTPU_ACCESSLOG_SAMPLE")))
    if rate <= 0.0:
        return
    global _export_count, _export_file
    with _export_lock:
        _export_count += 1
        # stride sampler: record n is written when floor(n*rate) advances
        # over floor((n-1)*rate) — exactly ceil(N*rate) of N records, at
        # evenly-spaced deterministic positions
        take = int(_export_count * rate) > int((_export_count - 1) * rate)
        if not take:
            return
        try:
            if _export_file is None or _export_file[0] != path:
                if _export_file is not None:
                    _export_file[1].close()
                _export_file = (path, open(path, "a"))
            f = _export_file[1]
            f.write(json.dumps(rec) + "\n")
            f.flush()
        except Exception:
            _export_file = None
            _LOG.debug("access-log export to %r failed", path,
                       exc_info=True)


def snapshot():
    """Every buffered record, oldest first (readers never block writers)."""
    return _ring.snapshot()


def tail(n=200):
    """The newest ``n`` records, oldest first."""
    return snapshot()[-max(0, int(n)):]


def export_jsonl(n=200):
    """The tail as JSONL text — what ``GET /debug/requests?n=`` serves."""
    return "".join(json.dumps(rec) + "\n" for rec in tail(n))


def reset():
    """Drop the ring, re-read MXTPU_ACCESSLOG_SIZE, rewind the export
    stride, and close the cached export handle (test isolation)."""
    global _export_count, _export_file
    _ring.reset()
    with _export_lock:
        _export_count = 0
        if _export_file is not None:
            try:
                _export_file[1].close()
            except Exception:
                pass
            _export_file = None
