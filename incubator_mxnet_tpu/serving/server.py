"""HTTP serving front-end on the stdlib ThreadingHTTPServer (no new deps).

One thread per connection; each request thread blocks on its batcher
future while the single worker thread per model does the actual compiled
dispatch — so the server scales to many concurrent clients without ever
running JAX outside the worker. JSON tensor encoding keeps the whole
stack exercisable end-to-end in tier-1 CPU tests (tests/test_serving.py
drives 64+ concurrent requests through a real socket).

Routes (TF-Serving REST-shaped):

- ``POST /v1/models/<name>:predict`` — body ``{"inputs": [<nested list>,
  ...], "deadline_ms": <optional>, "dtype": <optional, default float32>}``;
  response ``{"outputs": [<nested list>, ...]}``. Each input is ONE item,
  WITHOUT the batch dim — cross-request batching is the server's job.
- ``POST /generate`` — generative inference against a registered
  ``GenerativeEngine`` (registry.load_generator; docs/GENERATE.md). Body
  ``{"model": <name — optional when exactly one generator is loaded>,
  "prompt": [<token ids>], "max_new_tokens", "temperature", "top_k",
  "seed", "deadline_ms"}``. The response streams as
  ``Transfer-Encoding: chunked`` JSONL — one ``{"token": id, "index":
  n}`` line per generated token the moment the decode loop emits it
  (the first line is the prefill's token, so TTFT is measurable at the
  client), terminated by one ``{"done": true, "reason": "eos" |
  "max_tokens" | ..., "tokens": n}`` line. A client that hangs up
  mid-stream cancels the sequence: the decode loop retires the row and
  frees its KV blocks at the next step. Pre-stream failures use the
  predict error contract (400 bad request / invalid prompt, 429 prefill
  queue full + ``Retry-After``, 404 unknown generator, 503 shutting
  down, 504 prefill deadline).
- ``GET /v1/models``            — registered models + queue/batch config
  (incl. per-model ``replicas`` / ``replica_depths`` / ``dead_replicas``
  — the data-parallel serving topology, docs/SERVING.md) and loaded
  generators (KV-pool occupancy, bucket ladder, in-flight sequences).
- ``GET /v1/models/<name>``     — one model + its metrics snapshot
  (``replica_dispatch`` shows the router's per-replica balance).
- ``GET /metrics``              — Prometheus text exposition of the
  process-wide telemetry registry (serving counters, batch-size
  histogram, latency histogram, plus training/compile/kvstore/io
  metrics recorded in this process — docs/OBSERVABILITY.md).
- ``GET /metrics.json``         — the legacy per-model JSON snapshot
  (counters, batch-size histogram, p50/p95/p99 latency), byte-compatible
  with what ``GET /metrics`` returned before the Prometheus move.
- ``GET /healthz``              — healthy | degraded | unhealthy (503).
- ``GET /debug/stacks``         — all-thread stacks + heartbeat ages +
  the newest watchdog stall report (text/plain; the live "why is it
  stuck" view).
- ``GET /debug/flightrec``      — the flight-recorder ring as JSONL
  (newest last).
- ``GET /debug/spans``          — the finished-span ring as JSONL.
- ``GET /debug/aot``            — the process-wide AOT executable cache:
  one JSON record per compiled entry (model id, kind, input signature,
  build vs artifact provenance, program cost/memory stats, idle time) —
  the live "what is compiled right now" view behind the zero-recompile
  serving contract (docs/AOT.md).
- ``GET /debug/profile?seconds=N`` — on-demand ``jax.profiler`` capture
  into a bounded directory (telemetry/devstats.py): blocks for N
  seconds (clamped to MXTPU_PROFILE_MAX_S) and returns the capture dir
  plus a ``capture_id`` (stable across the dir prune — re-fetch via
  ``GET /debug/hotspots?capture=<id>``) and a ``summary`` (top-K ops +
  device-idle ratio, telemetry/profstats.py); single-flight — a
  concurrent capture gets 409 instead of corrupting the in-flight
  trace (docs/OBSERVABILITY.md "Device truth").
- ``GET /debug/hotspots?n=K`` — the ranked per-op hotspot table the
  profstats layer accumulates over every folded capture (continuous
  daemon + operator captures): top-K ops with XLA category, self time,
  count and share, the per-category split, and the device-idle ratio.
  ``?capture=<id>`` returns one remembered capture's full summary
  instead (bounded store, MXTPU_PROFSTATS_SUMMARIES;
  docs/OBSERVABILITY.md "Op-level attribution").
- ``GET /debug/requests?n=`` — the structured access log: the newest
  ``n`` terminal predict outcomes as JSONL ``{ts, request_id, tenant,
  model, code, shed_reason, latency_ms, queue_ms, batch_ms, device_ms,
  replica, bucket}`` (serving/accesslog.py).
- ``GET /debug/slo``        — per-SLO error-budget remaining, window
  burn rates, and alert-pair states (telemetry/slo.py;
  docs/OBSERVABILITY.md "SLOs and tenants").
- ``GET /debug/numerics``   — the numerics sentinel: per-site tap stats
  (finite fraction / abs-max / rms, storm episodes) and per-model
  shadow divergence (telemetry/numwatch.py; docs/OBSERVABILITY.md
  "Numerical health").
- ``GET /debug/faults``     — the fault-injection registry's arming
  state (telemetry/faultlab.py; docs/RESILIENCE.md). ``POST
  /debug/faults`` with ``{"spec": "<site:kind:key=val;...>"}`` arms it
  at runtime (chaos drills mid-soak, no restart); an empty/absent spec
  disarms. Malformed specs are 400 and leave the prior arming intact.
- ``GET /debug/``           — machine-readable index of every debug
  route (path + one-line description, the DEBUG_ROUTES table) — the
  first page a runbook loads mid-incident.
- ``GET /debug/history?series=&since=&step=`` — the metric-history
  store's raw + coarse rings and recording-rule series
  (telemetry/history.py; docs/OBSERVABILITY.md "Metric history &
  incident timelines").
- ``GET /debug/incident?around=<ts>`` — flightrec events, SLO alert
  transitions, and metric excursions around a timestamp merged into
  one causally-ordered timeline (``?before_s=`` / ``?after_s=`` bound
  the window).

Tracing: every predict request gets a request ID (client-supplied
``X-Request-Id`` wins, else one is generated), echoed on the response
header and propagated through the batcher queue onto the profiler's
``record_batch`` chrome-trace events.

Tenancy: an ``X-MXTPU-Tenant`` header (clamped; ``default`` when
absent) labels every terminal outcome — per-tenant
``mxtpu_requests_total{model,tenant,code}`` counters and latency
histograms, the access-log record, and the per-model SLO ledger feed
(2xx good; 429/504/5xx bad; latency objective judged from the
request's end-to-end handler window).

Error contract (the robustness story made visible):

- queue full        -> 429 + ``Retry-After`` + ``shed_reason:
  "queue_full"`` (explicit backpressure; shed load upstream)
- deadline exceeded -> 504 + ``shed_reason: "deadline"``
- unknown model     -> 404
- all replicas dead -> 503 + ``shed_reason: "no_replicas"`` and NO
  ``Retry-After`` — an outage is not backpressure; no pacing hint is
  honest until the supervisor restores a worker (docs/RESILIENCE.md)
- decode loop dead  -> 503 on ``POST /generate`` (and the generator is
  delisted from ``GET /v1/models`` until resurrected)
- shutting down     -> 503
- malformed body    -> 400
- servable raised   -> 500
"""
from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import config
from .. import telemetry
from . import accesslog
from .batcher import (DeadlineExceededError, NoReplicasError,
                      QueueFullError, ServingClosedError)
from .metrics import (http_request_finished, http_request_started,
                      request_accounted)
from .registry import ModelNotFoundError, ModelRegistry

_LOG = logging.getLogger(__name__)

__all__ = ["ServingServer", "serve", "DEBUG_ROUTES"]

_PREDICT_SUFFIX = ":predict"
_MODELS_PREFIX = "/v1/models"

#: Every debug endpoint this server exposes, served machine-readably at
#: ``GET /debug/``. Adding a ``/debug/*`` route WITHOUT listing it here
#: fails tests/test_history.py::test_debug_index_lists_every_route — an
#: undiscoverable diagnostic endpoint is a diagnostic endpoint nobody
#: reaches during the incident it was built for.
DEBUG_ROUTES = (
    ("/debug/", "index of every debug route (this listing)"),
    ("/debug/stacks", "all-thread stacks + heartbeat ages + newest "
     "watchdog stall report (text)"),
    ("/debug/flightrec", "flight-recorder event ring as JSONL"),
    ("/debug/spans", "finished-span ring as JSONL"),
    ("/debug/aot", "process-wide AOT executable cache entries"),
    ("/debug/requests", "structured access log, newest n terminal "
     "outcomes as JSONL (?n=)"),
    ("/debug/slo", "per-SLO budgets, burn rates, and alert states"),
    ("/debug/numerics", "numerics sentinel: tap stats, storm episodes, "
     "shadow divergence"),
    ("/debug/faults", "faultlab arming state (GET) / arm-disarm (POST)"),
    ("/debug/profile", "on-demand device-profiler capture (?seconds=)"),
    ("/debug/hotspots", "ranked per-op hotspot table (?n=, ?capture=)"),
    ("/debug/history", "metric-history rings: raw + coarse time series "
     "and recording rules (?series=&since=&step=)"),
    ("/debug/incident", "incident timeline: flightrec events, SLO alert "
     "transitions, and metric excursions around a timestamp "
     "(?around=&before_s=&after_s=)"),
)


class _Handler(BaseHTTPRequestHandler):
    """Bound to a registry via the per-server subclass ServingServer makes."""

    registry = None
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass  # serving metrics replace per-request stderr lines

    # ------------------------------------------------------------------
    def _send(self, code, payload, request_id=None, headers=None):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if request_id is not None:
            self.send_header(telemetry.REQUEST_ID_HEADER, request_id)
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code, text, content_type):
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _model_name(self):
        rest = self.path[len(_MODELS_PREFIX):].lstrip("/")
        if rest.endswith(_PREDICT_SUFFIX):
            rest = rest[:-len(_PREDICT_SUFFIX)]
        return rest

    # ------------------------------------------------------------------
    def do_GET(self):
        if self.path in ("/healthz", "/health"):
            h = self.registry.health()
            self._send(503 if h["status"] == "unhealthy" else 200, h)
        elif self.path == "/metrics":
            # Prometheus text exposition of the process-wide registry
            self._send_text(200, telemetry.export_text(),
                            "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/metrics.json":
            # legacy JSON snapshot (byte-compatible with the pre-Prometheus
            # GET /metrics payload)
            self._send(200, self.registry.metrics_snapshot())
        elif self.path == "/debug/stacks":
            # the on-demand "why is it stuck": all-thread stacks now, the
            # heartbeat ages, and the watchdog's newest stall report
            from ..telemetry import watchdog
            beats = "".join("%-32s %.3fs ago\n" % (n, s) for n, s in
                            sorted(watchdog.channels().items()))
            text = ("--- heartbeats ---\n" + (beats or "(none)\n")
                    + "\n" + watchdog.format_stacks())
            last = watchdog.last_report()
            if last:
                text += "\n--- last stall report ---\n" + last
            self._send_text(200, text, "text/plain; charset=utf-8")
        elif self.path == "/debug/flightrec":
            from ..telemetry import flightrec
            self._send_text(200, flightrec.format_tail(10_000),
                            "application/jsonl; charset=utf-8")
        elif self.path == "/debug/spans":
            from ..telemetry import spans
            self._send_text(200, spans.export_jsonl(),
                            "application/jsonl; charset=utf-8")
        elif self.path == "/debug/aot":
            from .. import aot
            self._send(200, {"entries": aot.CACHE.snapshot()})
        elif self.path.split("?", 1)[0] == "/debug/requests":
            # the structured access log: newest n terminal outcomes as
            # JSONL (tenant, code, shed_reason, queue/batch/device legs)
            from urllib.parse import parse_qs, urlparse
            q = parse_qs(urlparse(self.path).query)
            try:
                n = int(q.get("n", ["200"])[0])
            except ValueError:
                self._send(400, {"error": "n must be an integer"})
                return
            self._send_text(200, accesslog.export_jsonl(n),
                            "application/jsonl; charset=utf-8")
        elif self.path == "/debug/slo":
            # budgets, burn rates, and alert states per SLO (evaluating
            # the alert state machines now — a scrape can resolve an
            # alert whose error burst has ended)
            from ..telemetry import slo
            self._send(200, slo.REGISTRY.describe())
        elif self.path == "/debug/numerics":
            # the numerics sentinel: per-site tap stats / storm episodes
            # and per-model shadow divergence (telemetry/numwatch.py)
            from ..telemetry import numwatch
            self._send(200, numwatch.describe())
        elif self.path == "/debug/faults":
            # the faultlab arming state: armed flag + per-fault
            # stride/p/budget/fired counters (telemetry/faultlab.py)
            from ..telemetry import faultlab
            self._send(200, faultlab.describe())
        elif self.path.rstrip("/") == "/debug":
            # machine-readable index of every debug route — the first
            # page an operator (or a runbook script) loads mid-incident
            self._send(200, {"routes": [{"path": p, "description": d}
                                        for p, d in DEBUG_ROUTES]})
        elif self.path.split("?", 1)[0] == "/debug/history":
            self._do_history()
        elif self.path.split("?", 1)[0] == "/debug/incident":
            self._do_incident()
        elif self.path.split("?", 1)[0] == "/debug/profile":
            self._do_profile()
        elif self.path.split("?", 1)[0] == "/debug/hotspots":
            self._do_hotspots()
        elif self.path.rstrip("/") == _MODELS_PREFIX:
            self._send(200, {"models": self.registry.models(),
                             "generators": self.registry.generators()})
        elif self.path.startswith(_MODELS_PREFIX + "/"):
            name = self._model_name()
            try:
                entry = self.registry._entry(name)
            except ModelNotFoundError as e:
                self._send(404, {"error": str(e)})
                return
            desc = entry.describe()
            desc["metrics"] = entry.metrics.snapshot()
            self._send(200, desc)
        else:
            self._send(404, {"error": "no route %r" % self.path})

    def _do_history(self):
        """GET /debug/history?series=&since=&step= — the metric-history
        store (telemetry/history.py): raw + coarse rings per series,
        optionally filtered (series substring / bare metric name),
        truncated (since=epoch seconds) and re-bucketed (step=seconds
        of min/max/mean folding)."""
        from urllib.parse import parse_qs, urlparse
        from ..telemetry import history
        q = parse_qs(urlparse(self.path).query)
        series = q.get("series", [None])[0]
        try:
            since = float(q["since"][0]) if "since" in q else None
            step = float(q["step"][0]) if "step" in q else None
        except ValueError:
            self._send(400, {"error": "since/step must be numbers"})
            return
        if step is not None and step <= 0:
            self._send(400, {"error": "step must be > 0"})
            return
        self._send(200, history.query(series=series, since=since,
                                      step=step))

    def _do_incident(self):
        """GET /debug/incident?around=&before_s=&after_s= — the incident
        timeline builder: flightrec events, SLO alert transitions, and
        metric excursions in the window, merged and causally ordered on
        the shared perf_counter anchor (telemetry/history.py)."""
        from urllib.parse import parse_qs, urlparse
        from ..telemetry import history
        q = parse_qs(urlparse(self.path).query)
        try:
            around = float(q["around"][0]) if "around" in q else None
            before_s = float(q.get("before_s", ["90"])[0])
            after_s = float(q.get("after_s", ["30"])[0])
        except ValueError:
            self._send(400, {"error": "around/before_s/after_s must be "
                                      "numbers"})
            return
        self._send(200, history.incident(around=around, before_s=before_s,
                                         after_s=after_s))

    def _do_profile(self):
        """GET /debug/profile?seconds=N — the on-demand device-profiler
        capture (single-flight; 409 while one is in flight). The handler
        thread blocks for the capture window; the ThreadingHTTPServer
        keeps answering /metrics and predicts meanwhile. The response
        carries the parsed ``summary`` (top-K ops + idle ratio) and a
        ``capture_id`` that stays fetchable via GET /debug/hotspots
        ?capture=<id> after the dir itself is pruned."""
        from urllib.parse import parse_qs, urlparse
        from ..telemetry import devstats
        from ..telemetry.profstats import brief, capture_and_summarize
        q = parse_qs(urlparse(self.path).query)
        try:
            seconds = float(q.get("seconds", ["2"])[0])
        except ValueError:
            self._send(400, {"error": "seconds must be a number"})
            return
        try:
            out, summary = capture_and_summarize(seconds)
        except devstats.ProfileCaptureBusy as e:
            self._send(409, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — capture failure, not crash
            self._send(500, {"error": "%s: %s" % (type(e).__name__, e)})
        else:
            out["summary"] = brief(summary)
            self._send(200, out)

    def _do_hotspots(self):
        """GET /debug/hotspots?n=K — the rolling ranked hotspot table;
        ``?capture=<id>`` returns one remembered capture summary."""
        from urllib.parse import parse_qs, urlparse
        from ..telemetry import profstats
        q = parse_qs(urlparse(self.path).query)
        try:
            n = int(q.get("n", ["20"])[0])
        except ValueError:
            self._send(400, {"error": "n must be an integer"})
            return
        cid = q.get("capture", [None])[0]
        if cid:
            summary = profstats.get_summary(cid)
            if summary is None:
                self._send(404, {"error": "no remembered capture %r" % cid,
                                 "known": profstats.summaries()})
            else:
                self._send(200, summary)
            return
        self._send(200, profstats.hotspots(n))

    def do_POST(self):
        if self.path == "/debug/faults":
            self._do_faults()
            return
        if self.path == "/generate":
            req_id = self.headers.get(telemetry.REQUEST_ID_HEADER) \
                or telemetry.new_request_id()
            tenant = accesslog.clamp_tenant(
                self.headers.get(accesslog.TENANT_HEADER))
            http_request_started()
            try:
                self._do_generate(req_id, tenant)
            finally:
                http_request_finished()
            return
        if not (self.path.startswith(_MODELS_PREFIX + "/")
                and self.path.endswith(_PREDICT_SUFFIX)):
            self._send(404, {"error": "no route %r (POST "
                             "/v1/models/<name>:predict or "
                             "POST /generate)" % self.path})
            return
        name = self._model_name()
        # request-scoped trace id: a client-supplied X-Request-Id wins (the
        # caller's trace context survives), else assign one here — this is
        # the id the batcher carries queue -> dispatch -> profiler event
        req_id = self.headers.get(telemetry.REQUEST_ID_HEADER) \
            or telemetry.new_request_id()
        # tenant accounting label (X-MXTPU-Tenant, clamped; "default"
        # when absent) — rides the batcher alongside the request id and
        # keys the per-tenant counters, the SLO ledger feed, and the
        # access-log record
        tenant = accesslog.clamp_tenant(
            self.headers.get(accesslog.TENANT_HEADER))
        # inflight gauge covers body read through response written — the
        # front-end concurrency signal the load harness reads per stage
        http_request_started()
        try:
            self._do_predict(name, req_id, tenant)
        finally:
            http_request_finished()

    def _do_predict(self, name, req_id, tenant):
        import numpy as onp
        t_start = time.perf_counter()
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(length) or b"{}")
            raw = req["inputs"]
            if not isinstance(raw, list) or not raw:
                raise ValueError("'inputs' must be a non-empty list (one "
                                 "entry per model input, no batch dim)")
            dtypes = req.get("dtype", "float32")
            if isinstance(dtypes, str):
                dtypes = [dtypes] * len(raw)
            elif len(dtypes) != len(raw):
                raise ValueError("'dtype' list length %d != %d inputs"
                                 % (len(dtypes), len(raw)))
            inputs = [onp.asarray(x, dtype=onp.dtype(d))
                      for x, d in zip(raw, dtypes)]
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)  # non-numeric -> 400
        except Exception as e:  # noqa: BLE001 — anything malformed is a 400
            self._finish(name, tenant, req_id, 400, t_start,
                         {"error": "bad request: %s" % e})
            return
        breq = None
        try:
            # root span of the request's trace chain: submit() captures
            # this span's context into the queued request, so the worker's
            # serve:queue / serve:batch spans parent onto it across the
            # queue boundary (HTTP -> queue -> bucket -> device in one
            # dump). submit + result (rather than predict) keeps the
            # request object, whose worker-attached dispatch facts feed
            # the access-log record.
            with telemetry.request_scope(req_id), \
                    telemetry.span("http:predict", model=name,
                                   tenant=tenant):
                batcher = self.registry._entry(name).batcher
                breq = batcher.submit(*inputs, deadline_ms=deadline_ms,
                                      request_id=req_id, tenant=tenant)
                outs = breq.result(batcher.result_timeout(breq))
        except QueueFullError as e:
            # explicit backpressure: a machine-readable shed_reason (no
            # more string-matching the error text) + a Retry-After hint
            # sized to the coalescing window — the queue drains at batch
            # granularity, so "one window from now" is the earliest a
            # retry can meet a freed slot
            self._finish(name, tenant, req_id, 429, t_start,
                         {"error": str(e), "shed_reason": "queue_full"},
                         shed_reason="queue_full", breq=breq,
                         headers={"Retry-After": self._retry_after(name)})
        except DeadlineExceededError as e:
            self._finish(name, tenant, req_id, 504, t_start,
                         {"error": str(e), "shed_reason": "deadline"},
                         shed_reason="deadline", breq=breq)
        except ModelNotFoundError as e:
            self._finish(name, tenant, req_id, 404, t_start,
                         {"error": str(e)}, breq=breq)
        except NoReplicasError as e:
            # every replica worker is dead: this is an OUTAGE, not
            # backpressure — 503 (not 429) and deliberately NO
            # Retry-After, because no client-side pacing hint is honest
            # until the supervisor (or an operator) restores a worker
            self._finish(name, tenant, req_id, 503, t_start,
                         {"error": str(e), "shed_reason": "no_replicas"},
                         shed_reason="no_replicas", breq=breq)
        except ServingClosedError as e:
            self._finish(name, tenant, req_id, 503, t_start,
                         {"error": str(e)}, breq=breq)
        except Exception as e:  # noqa: BLE001 — servable failure
            self._finish(name, tenant, req_id, 500, t_start,
                         {"error": "%s: %s" % (type(e).__name__, e)},
                         breq=breq)
        except BaseException as e:
            if (isinstance(e, (KeyboardInterrupt, SystemExit))
                    and not getattr(e, "_mxtpu_died_in_servable", False)):
                # a genuine interpreter-exit signal, not a delivered
                # request error — let it propagate
                raise
            # a worker-killing defect delivered raw to the poison
            # request's future (query of death — docs/RESILIENCE.md)
            # must not kill the handler thread: at the HTTP boundary it
            # is a servable outage, 503 — even when the servable's
            # chosen defect is spelled SystemExit
            self._finish(name, tenant, req_id, 503, t_start,
                         {"error": "%s: %s" % (type(e).__name__, e)},
                         breq=breq)
        else:
            self._finish(name, tenant, req_id, 200, t_start,
                         {"outputs": [onp.asarray(o).tolist()
                                      for o in outs]}, breq=breq)

    def _do_faults(self):
        """POST /debug/faults — arm (body ``{"spec": "<site:kind:...>"}``)
        or disarm (empty/absent spec) the process-wide fault-injection
        registry at runtime. Chaos drills flip faults mid-soak through
        this without a restart; a malformed spec is a 400 and leaves the
        previous arming untouched (faultlab.arm validates before it
        swaps). The response echoes ``faultlab.describe()``."""
        from ..telemetry import faultlab
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(length) or b"{}")
            spec = req.get("spec") or ""
            if not isinstance(spec, str):
                raise ValueError("'spec' must be a string")
        except Exception as e:  # noqa: BLE001 — anything malformed is a 400
            self._send(400, {"error": "bad request: %s" % e})
            return
        try:
            faultlab.arm(spec)
        except ValueError as e:
            self._send(400, {"error": "bad fault spec: %s" % e})
            return
        self._send(200, faultlab.describe())

    def _retry_after(self, name):
        """Whole-second Retry-After hint for a 429: at least one batch
        window from now (rounded up) — sooner retries meet the same full
        queue that shed them."""
        try:
            window_ms = self.registry._entry(name).batcher.batch_timeout_ms
        except Exception:
            window_ms = 0.0
        return str(max(1, int(-(-window_ms // 1000))))

    # ------------------------------------------------------------ generate
    def _do_generate(self, req_id, tenant):
        """POST /generate: validate + prefill synchronously (every failure
        there still has the buffered-JSON error contract), then stream
        the decode loop's tokens as chunked JSONL."""
        from .generate import BadGenRequest
        t_start = time.perf_counter()
        name = None
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(length) or b"{}")
            name = req.get("model")
            if name is None:
                gens = [g["name"] for g in self.registry.generators()
                        if not g["closed"]]
                if len(gens) != 1:
                    raise ValueError(
                        "'model' is required when %d generators are "
                        "loaded" % len(gens))
                name = gens[0]
            prompt = req.get("prompt")
            kw = {"max_new_tokens": req.get("max_new_tokens"),
                  "temperature": float(req.get("temperature", 0.0)),
                  "top_k": int(req.get("top_k", 0)),
                  "seed": int(req.get("seed", 0))}
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
        except Exception as e:  # noqa: BLE001 — anything malformed is a 400
            self._finish(name or "-", tenant, req_id, 400, t_start,
                         {"error": "bad request: %s" % e})
            return
        try:
            # the root span covers validate + the batched prefill (the
            # engine's gen:prefill span parents onto it); the decode
            # stream outlives it by design — decode steps are engine-
            # scoped gen:decode_step spans, not per-request children
            with telemetry.request_scope(req_id), \
                    telemetry.span("http:generate", model=name,
                                   tenant=tenant):
                engine = self.registry.generator(name)
                stream = engine.submit(prompt, tenant=tenant,
                                       request_id=req_id,
                                       deadline_ms=deadline_ms, **kw)
        except BadGenRequest as e:
            self._finish(name, tenant, req_id, 400, t_start,
                         {"error": "bad request: %s" % e})
        except QueueFullError as e:
            self._finish(name, tenant, req_id, 429, t_start,
                         {"error": str(e), "shed_reason": "queue_full"},
                         shed_reason="queue_full",
                         headers={"Retry-After": "1"})
        except DeadlineExceededError as e:
            self._finish(name, tenant, req_id, 504, t_start,
                         {"error": str(e), "shed_reason": "deadline"},
                         shed_reason="deadline")
        except ModelNotFoundError as e:
            self._finish(name, tenant, req_id, 404, t_start,
                         {"error": str(e)})
        except ServingClosedError as e:
            # covers a registry whose decode loop is DEAD (awaiting
            # supervisor revival) as well as graceful shutdown: a 503
            # outage signal, never a 429 pacing hint
            self._finish(name, tenant, req_id, 503, t_start,
                         {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — engine failure
            self._finish(name, tenant, req_id, 500, t_start,
                         {"error": "%s: %s" % (type(e).__name__, e)})
        else:
            self._stream_generate(name, stream, tenant, req_id, t_start)

    def _chunk(self, obj):
        """One HTTP/1.1 chunk holding one JSON line."""
        data = (json.dumps(obj) + "\n").encode("utf-8")
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def _stream_generate(self, name, stream, tenant, req_id, t_start):
        """Drain one GenStream into a chunked response. Terminal
        accounting happens when the engine's ``("end", reason)`` event
        arrives — BEFORE the final done-chunk is written, keeping the
        instrument-before-deliver discipline for the record the access
        log and SLO ledger see (the per-token counters/histograms were
        already recorded by the engine at emit time). A write that hits
        a dead client cancels the sequence; the decode loop frees its
        KV blocks at the next step."""
        import queue as _pyqueue
        ntok, code, shed, reason = 0, 200, None, None
        try:
            self.send_response(200)
            self.send_header("Content-Type",
                             "application/jsonl; charset=utf-8")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header(telemetry.REQUEST_ID_HEADER, req_id)
            self.send_header(accesslog.TENANT_HEADER, tenant)
            self.end_headers()
            while True:
                kind, val = stream.get(timeout=600.0)
                if kind == "end":
                    reason = val
                    break
                self._chunk({"token": val, "index": ntok})
                ntok += 1
        except (BrokenPipeError, ConnectionResetError):
            stream.cancel()
            code, shed = 499, "client_disconnect"
        except _pyqueue.Empty:
            # the decode loop stopped feeding this stream (stalled or
            # died) — give up the connection; the watchdog's stall report
            # is the diagnosis surface
            stream.cancel()
            code, shed = 504, "stream_stalled"
        except Exception:  # noqa: BLE001 — never kill the handler thread
            stream.cancel()
            code = 500
        if reason in ("kv_oom", "error"):
            # headers already said 200; the access log still records the
            # degraded finish so capacity trouble is attributable
            shed = reason
        self._account(name, tenant, req_id, code, t_start, shed_reason=shed)
        if code == 200:
            try:
                self._chunk({"done": True, "reason": reason,
                             "tokens": ntok})
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                stream.cancel()

    def _account(self, name, tenant, req_id, code, t_start,
                 shed_reason=None, dispatch=None):
        """The shared terminal-outcome accounting (per-tenant counters,
        SLO ledger, access log) — guarded: telemetry failure never turns
        a served response into a 500."""
        latency_ms = (time.perf_counter() - t_start) * 1e3
        d = dispatch or {}
        try:
            request_accounted(name, tenant, code, latency_ms)
            from ..telemetry import slo
            if code != 404:
                # a 404 names a model that does not exist — feeding it to
                # the SLO registry would let hostile model-name probes
                # seed unbounded SLO objects (404 is not SLO-eligible
                # anyway; the per-tenant counter above, which IS
                # cardinality-clamped, still records the probe)
                slo.REGISTRY.observe(name, code, latency_ms=latency_ms)
            accesslog.record(
                request_id=req_id, tenant=tenant, model=name, code=code,
                latency_ms=latency_ms, shed_reason=shed_reason,
                queue_ms=d.get("queue_ms"), batch_ms=d.get("batch_ms"),
                device_ms=d.get("device_ms"), replica=d.get("replica"),
                bucket=d.get("bucket"))
        except Exception:
            _LOG.debug("request accounting failed", exc_info=True)

    def _finish(self, name, tenant, req_id, code, t_start, payload,
                shed_reason=None, breq=None, headers=None):
        """Account one terminal outcome, then send the response.
        Accounting (per-tenant counters + latency histogram, the SLO
        ledger, the access-log record) happens BEFORE the send, mirroring
        the batcher's instrument-before-deliver discipline: a scrape
        fired the moment the client unblocks must already see this
        request."""
        self._account(name, tenant, req_id, code, t_start,
                      shed_reason=shed_reason,
                      dispatch=breq.dispatch if breq is not None else None)
        self._send(code, payload, request_id=req_id, headers=headers)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # the socketserver default backlog of 5 refuses connections under a
    # concurrent burst — size it to a queue's worth of clients instead
    request_queue_size = 128


class ServingServer:
    """The single-host serving endpoint: a ModelRegistry behind HTTP.

    ``port=0`` binds an ephemeral port (read it back from ``.port``) —
    what the tier-1 tests use. ``start()`` returns immediately (the accept
    loop runs in a daemon thread); ``stop(drain=True)`` stops accepting,
    drains every model's queue, and joins — the graceful-shutdown path.
    Usable as a context manager.
    """

    def __init__(self, registry=None, host="127.0.0.1", port=None):
        self.registry = registry if registry is not None else ModelRegistry()
        if port is None:
            port = config.get_env("MXTPU_SERVE_PORT")
        handler = type("_BoundHandler", (_Handler,),
                       {"registry": self.registry})
        self._httpd = _Server((host, int(port)), handler)
        self._thread = None

    @property
    def host(self):
        return self._httpd.server_address[0]

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="mxtpu-serve-http")
        self._thread.start()
        return self

    def stop(self, drain=True):
        """Graceful shutdown: close the listener, then drain (or fail)
        queued requests via the registry, then join the accept loop.
        Safe to call even if start() never ran (shutdown() would block
        forever waiting on serve_forever's loop-exit event)."""
        if self._thread is not None:
            self._httpd.shutdown()
        self.registry.close(drain=drain)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def serve(models, host="127.0.0.1", port=None, **batcher_kw):
    """Convenience bring-up: ``models`` maps name -> servable OR a path to
    a ``.mxtpu`` artifact. Returns the STARTED ServingServer (caller owns
    ``stop()``)."""
    registry = ModelRegistry()
    for name, obj in models.items():
        if isinstance(obj, str):
            from ..contrib import serving as _artifact
            obj = _artifact.load(obj)
        registry.load(name, obj, **batcher_kw)
    return ServingServer(registry, host=host, port=port).start()
