"""Dynamic request batcher (TF-Serving BatchingSession analog,
arXiv:1605.08695 §4.3: cross-request batching in front of a compiled
executable is how many small requests saturate an accelerator).

``replicas`` worker threads (default 1, ``MXTPU_SERVE_REPLICAS``) each own
a BOUNDED dispatch queue and pull single-item requests off it, dispatching
a stacked batch when either ``max_batch_size`` requests are waiting or
``batch_timeout_ms`` has passed since the first one — classic
size-or-deadline coalescing, times N data-parallel executors. A
least-depth router in ``submit()`` picks the replica with the fewest
requests queued-plus-in-dispatch (ties rotate), so aggregate goodput
scales with replicas while no replica piles up behind a slow batch
(docs/SERVING.md "Sharded serving"). Batches are padded up to a small set
of bucket sizes (powers of two by default) so the servable underneath
sees only a handful of shapes: a live Gluon block compiles once per
bucket through jit.EvalStep's shape-keyed executable cache, and an
exported .mxtpu artifact re-chunks every bucket onto its one compiled
batch shape (contrib/serving.ServedModel.predict_batch).

Replica-aware servables: when the dispatch callable accepts a ``replica``
keyword (the registry's dispatch closure does, forwarding to servables
whose ``predict_batch`` takes it — e.g. a ServedModel pinning each
replica's executable to its own mesh device), the worker passes its
replica index so each replica runs on its own chip. Plain servables are
called positionally, exactly as before.

Robustness contract:
- full queues -> ``QueueFullError`` raised at submit time after every
  live replica was tried (explicit backpressure; HTTP maps it to 429 —
  never unbounded latency),
- per-request deadline -> ``DeadlineExceededError`` for requests still
  queued when it passes (they are dropped BEFORE padding/dispatch),
- a DYING replica worker drains its queue back through the router:
  queued requests are re-routed to live replicas (or failed loudly when
  none remain), its depth gauge is detached, and the model keeps serving
  on the survivors — a dead replica must never strand requests until
  their deadline,
- ``close(drain=True)`` -> stops intake, finishes everything queued,
  then joins every worker.

Only worker threads touch the servable (and therefore JAX), so arbitrary
many client threads can submit concurrently.
"""
from __future__ import annotations

import inspect
import logging
import threading
import time
import queue as _queue

import numpy as onp

from .. import config
from ..telemetry import (devstats, faultlab, flightrec, numwatch, spans,
                         watchdog)
from ..telemetry.registry import counter as _counter
from .metrics import ServingMetrics

__all__ = ["DynamicBatcher", "QueueFullError", "DeadlineExceededError",
           "ServingClosedError", "NoReplicasError", "default_buckets"]

_LOG = logging.getLogger(__name__)

#: Idempotent predict requests re-routed after their replica worker died
#: (serving/resilience.py retry contract; docs/RESILIENCE.md).
_RETRIES = _counter(
    "mxtpu_retries_total",
    "Predict requests retried once after a replica worker death, by model.",
    ("model",))


class QueueFullError(RuntimeError):
    """Overload rejection: every live replica's bounded queue is at
    capacity."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before it could be dispatched."""


class ServingClosedError(RuntimeError):
    """Submit after close(): the batcher is shutting down."""


class NoReplicasError(ServingClosedError):
    """Every replica worker is dead (or parked by the crash-loop
    breaker): nobody will ever service a submit. HTTP maps this to 503
    with shed_reason ``no_replicas`` and NO Retry-After — unlike 429
    queue_full there is no queue that drains; capacity returns only when
    the supervisor revives a worker (docs/RESILIENCE.md)."""


def default_buckets(max_batch_size):
    """Powers of two up to (and always including) max_batch_size."""
    buckets, b = [], 1
    while b < max_batch_size:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch_size)
    return buckets


def _accepts_replica(fn):
    """True when ``fn`` declares an explicit ``replica`` parameter (a bare
    **kwargs does NOT count — passing replica= to a servable that merely
    swallows it would silently drop the placement contract)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    p = sig.parameters.get("replica")
    return p is not None and p.kind in (p.POSITIONAL_OR_KEYWORD,
                                        p.KEYWORD_ONLY)


class _Request:
    """One queued inference item + the completion event its client waits on."""

    __slots__ = ("inputs", "deadline", "enqueued_at", "request_id",
                 "span_ctx", "tenant", "dispatch", "retried", "_event",
                 "_result", "_error")

    def __init__(self, inputs, deadline, request_id=None, span_ctx=None,
                 tenant=None):
        self.inputs = inputs            # tuple of per-input arrays, NO batch dim
        self.deadline = deadline        # absolute time.monotonic() or None
        self.request_id = request_id    # trace id riding queue -> dispatch
        self.tenant = tenant            # accounting label riding alongside it
        # captured SpanContext of the submitter's open span (the HTTP
        # handler's http:predict): the explicit queue-boundary propagation
        # the worker parents its serve:queue/serve:batch spans onto
        self.span_ctx = span_ctx
        # dispatch facts the worker attaches before completing the request
        # ({replica, bucket, queue_ms, batch_ms, device_ms}) — what the
        # access-log record's batch-stage legs are assembled from; None
        # for requests that never reached a dispatch (shed, expired)
        self.dispatch = None
        # True once the request has been re-routed after a replica-death
        # failure: the retry contract is ONE bounded attempt, so a second
        # death fails it for good (serving/resilience.py)
        self.retried = False
        self.enqueued_at = time.monotonic()
        self._event = threading.Event()
        self._result = None
        self._error = None

    def succeed(self, result):
        self._result = result
        self._event.set()

    def fail(self, error):
        self._error = error
        self._event.set()

    def result(self, timeout=None):
        """Block until the batch containing this request ran (or failed)."""
        if not self._event.wait(timeout):
            if (self.deadline is not None
                    and time.monotonic() >= self.deadline):
                raise DeadlineExceededError(
                    "deadline exceeded: no result after %.3fs (request "
                    "still queued or in flight)" % timeout)
            # deadline not (yet) passed: a plain caller-side wait timeout,
            # not a client-requested 504
            raise TimeoutError("request not completed after %.3fs" % timeout)
        if self._error is not None:
            raise self._error
        return self._result


class DynamicBatcher:
    """Coalesce concurrent single-item requests into bucketed batches over
    ``replicas`` data-parallel dispatch queues.

    ``servable`` is either an object with ``predict_batch(*stacked) ->
    tuple of stacked outputs`` or a bare callable with that signature
    (the registry passes its version-resolving dispatch closure here, so
    hot-reload swaps take effect at batch granularity). A dispatch
    callable declaring a ``replica`` keyword receives the dispatching
    worker's replica index (device placement hook).
    """

    def __init__(self, servable, max_batch_size=None, batch_timeout_ms=None,
                 queue_size=None, buckets=None, default_deadline_ms=None,
                 metrics=None, name="model", replicas=None):
        self._dispatch_fn = (servable.predict_batch
                             if hasattr(servable, "predict_batch")
                             else servable)
        self._replica_aware = _accepts_replica(self._dispatch_fn)
        self.name = name
        self.max_batch_size = int(max_batch_size
                                  if max_batch_size is not None
                                  else config.get_env("MXTPU_SERVE_MAX_BATCH"))
        self.batch_timeout_ms = float(
            batch_timeout_ms if batch_timeout_ms is not None
            else config.get_env("MXTPU_SERVE_TIMEOUT_MS"))
        qsize = int(queue_size if queue_size is not None
                    else config.get_env("MXTPU_SERVE_QUEUE_SIZE"))
        if qsize < 1:
            # Queue(maxsize=0) would mean UNBOUNDED — silently deleting
            # the backpressure contract (and /healthz's >=80% threshold)
            raise ValueError(
                "queue_size must be >= 1 (got %d): the bounded queue IS "
                "the backpressure contract (MXTPU_SERVE_QUEUE_SIZE)" % qsize)
        self.queue_size = qsize         # per-replica bound
        n_rep = int(replicas if replicas is not None
                    else config.get_env("MXTPU_SERVE_REPLICAS"))
        if n_rep < 1:
            raise ValueError("replicas must be >= 1 (got %d) "
                             "(MXTPU_SERVE_REPLICAS)" % n_rep)
        self.replicas = n_rep
        self.default_deadline_ms = (
            default_deadline_ms if default_deadline_ms is not None
            else config.get_env("MXTPU_SERVE_DEADLINE_MS"))
        self.buckets = sorted(buckets) if buckets \
            else default_buckets(self.max_batch_size)
        if self.buckets[-1] < self.max_batch_size:
            self.buckets.append(self.max_batch_size)
        self.metrics = metrics if metrics is not None \
            else ServingMetrics(model=name)
        self._queues = [_queue.Queue(maxsize=qsize) for _ in range(n_rep)]
        self.metrics.queue_depth_fn = \
            lambda: sum(q.qsize() for q in self._queues)
        # the saturation line the history pressure predictor needs: the
        # trend toward "queue full" is only predictable if capacity is a
        # metric too
        self.metrics.set_queue_capacity(qsize * n_rep)
        # router state: per-replica in-dispatch counts, dispatch totals,
        # the dead set, and the tie-break rotation — one leaf lock, never
        # held while acquiring anything else
        self._route_lock = threading.Lock()
        self._inflight = [0] * n_rep        # handed to worker, not done
        self._dispatched = [0] * n_rep      # requests dispatched, ever
        self._dead = set()
        self._rr = 0
        self._replica_depth_fns = []
        for r in range(n_rep):
            fn = self._replica_depth_reader(r)
            self._replica_depth_fns.append(fn)
            self.metrics.bind_replica_depth(r, fn)
        # per-bucket dispatch-stage depth: requests gathered into a bucket
        # and not yet completed (padding + servable + slicing). Written by
        # workers, sampled by scrape threads at exposition time — its
        # own leaf lock, never held while acquiring anything else
        self._depth_lock = threading.Lock()
        self._bucket_depth = dict.fromkeys(self.buckets, 0)
        for b in self.buckets:
            self.metrics.bind_bucket_depth(b, self._bucket_depth_reader(b))
        self._closed = False
        self._paused = False
        # one bounded retry for requests orphaned by a dying worker
        # (docs/RESILIENCE.md "Retry idempotency contract")
        self._retry_on_death = bool(config.get_env("MXTPU_RESILIENCE_RETRY"))
        # per-item (shape, dtype) signature of the most recently dispatched
        # request — what a hot-reload prewarm synthesizes warm batches
        # from (registry.load); written by workers, read by warm/load
        # threads, hence its own lock
        self._sig_lock = threading.Lock()
        self._last_item_sig = None
        # stall-watchdog channels: each worker beats once per gather cycle
        # (<= 0.25s apart when idle), so silence means a stuck dispatch,
        # not an empty queue
        self._hb_channels = [
            watchdog.register("batcher:%s" % name if n_rep == 1
                              else "batcher:%s:r%d" % (name, r))
            for r in range(n_rep)]
        self._workers = [
            threading.Thread(target=self._run, args=(r,), daemon=True,
                             name="mxtpu-batcher-%s-r%d" % (name, r))
            for r in range(n_rep)]
        for w in self._workers:
            w.start()

    # ------------------------------------------------------------ client side
    def _route(self):
        """Live replica indices, least-depth first (depth = queued +
        in-dispatch), ties rotated so equal-depth replicas share evenly."""
        with self._route_lock:
            live = [r for r in range(self.replicas) if r not in self._dead]
            inflight = {r: self._inflight[r] for r in live}
            rr = self._rr
            self._rr += 1
        live.sort(key=lambda r: (self._queues[r].qsize() + inflight[r],
                                 (r - rr) % self.replicas))
        return live

    def submit(self, *inputs, deadline_ms=None, request_id=None,
               tenant=None):
        """Enqueue one item (arrays WITHOUT the batch dim); returns a future-
        like _Request. Raises QueueFullError/ServingClosedError immediately
        instead of blocking — backpressure is the caller's signal to shed
        load upstream. ``request_id`` (assigned by the HTTP front-end or
        any caller) rides the queue and is emitted on the dispatch's
        profiler trace event, tying one request to its batch; ``tenant``
        (the clamped X-MXTPU-Tenant value) rides alongside it for the
        per-tenant accounting and the access-log record."""
        if self._closed or self._paused:
            raise ServingClosedError("batcher %r is shut down" % self.name)
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        # NB `is not None`: deadline_ms=0 means expired-unless-dispatched-
        # immediately, not "no deadline"
        deadline = (time.monotonic() + max(0.0, deadline_ms) / 1000.0
                    if deadline_ms is not None else None)
        # materialize on the client thread: the worker groups requests by
        # shape/dtype signature, which needs real arrays
        req = _Request(tuple(onp.asarray(x) for x in inputs), deadline,
                       request_id=request_id,
                       span_ctx=spans.current_context(), tenant=tenant)
        order = self._route()
        if not order:
            # every replica worker died: nobody will ever service this —
            # a 503 (no_replicas), NOT a 429: there is no queue that
            # drains, so advertising retryability would be a lie
            raise NoReplicasError(
                "batcher %r has no live replica workers" % self.name)
        routed = None
        for r in order:
            try:
                self._queues[r].put_nowait(req)
                routed = r
                break
            except _queue.Full:
                continue
        if routed is None:
            try:
                self.metrics.inc("rejected_count")
            except Exception:
                pass
            raise QueueFullError(
                "model %r: all %d live replica queue(s) full "
                "(%d-deep each, %d replica(s) configured): rejecting — "
                "raise MXTPU_SERVE_QUEUE_SIZE, add replicas "
                "(MXTPU_SERVE_REPLICAS), or add capacity"
                % (self.name, len(order), self.queue_size,
                   self.replicas)) from None
        # the routed replica can die between _route() and the put — its
        # worker's drain may already have swept the queue, so sweep again
        # ourselves (idempotent; re-routes to survivors or fails loudly)
        with self._route_lock:
            landed_dead = routed in self._dead
        if landed_dead:
            self._reroute_queue(routed)
        # close() can win the race between the _closed check above and the
        # enqueue; if the workers are already gone nobody will ever service
        # this request — fail it instead of letting the client hang
        if self._closed and not self.alive:
            err = ServingClosedError("batcher %r is shut down" % self.name)
            req.fail(err)
            raise err
        # guarded like the worker-side updates: the request is already
        # enqueued — a telemetry failure here would error a client whose
        # work the worker still dispatches (result delivered to nobody)
        try:
            self.metrics.inc("request_count")
        except Exception:
            pass
        return req

    def predict(self, *inputs, deadline_ms=None, timeout=None,
                request_id=None, tenant=None):
        """Blocking convenience: submit + wait for the result tuple.

        A request with a deadline never waits (much) past it: the wait is
        capped at deadline + one batch window, so a client behind a stuck
        batch gets DeadlineExceededError at its deadline instead of
        hanging — the worker-side check then drops the stale entry when it
        finally dequeues it."""
        req = self.submit(*inputs, deadline_ms=deadline_ms,
                          request_id=request_id, tenant=tenant)
        if timeout is None:
            timeout = self.result_timeout(req)
        return req.result(timeout)

    def result_timeout(self, req):
        """The bounded wait predict() applies to one submitted request:
        600 s absolute cap, or — for a request with a deadline — the
        deadline plus one batch window, so a client behind a stuck batch
        errors at its deadline instead of hanging. Exposed so callers
        that need the _Request itself (the HTTP front-end assembling
        access-log records) can reproduce predict()'s wait exactly."""
        timeout = 600.0
        if req.deadline is not None:
            timeout = min(timeout,
                          max(0.0, req.deadline - time.monotonic())
                          + self.batch_timeout_ms / 1000.0 + 0.05)
        return timeout

    def queue_depth(self):
        """Requests waiting across every replica queue (not yet gathered)."""
        return sum(q.qsize() for q in self._queues)

    @property
    def total_queue_size(self):
        """Aggregate queue capacity (per-replica bound x replicas) — the
        denominator /healthz's >=80% occupancy check uses."""
        return self.queue_size * self.replicas

    def _bucket_depth_reader(self, bucket):
        """Sampler closure for one bucket's dispatch-stage depth gauge."""
        def read():
            with self._depth_lock:
                return self._bucket_depth.get(bucket, 0)
        return read

    def _replica_depth_reader(self, replica):
        """Sampler closure for one replica's depth gauge: queued + handed
        to its worker and not yet completed — the router's signal, so the
        scrape shows exactly what routing decisions are made on."""
        def read():
            with self._route_lock:
                inflight = self._inflight[replica]
            return self._queues[replica].qsize() + inflight
        return read

    def bucket_depths(self):
        """{bucket -> in-dispatch request count} snapshot (test hook; the
        scrape surface is the mxtpu_serving_bucket_queue_depth gauge)."""
        with self._depth_lock:
            return dict(self._bucket_depth)

    def replica_depths(self):
        """[queued + in-dispatch per replica] snapshot (test hook; the
        scrape surface is mxtpu_serving_replica_queue_depth)."""
        return [fn() for fn in self._replica_depth_fns]

    def replica_dispatch_counts(self):
        """[requests dispatched per replica, cumulative] — the balance
        proof (mirrored on mxtpu_serving_replica_dispatch_total)."""
        with self._route_lock:
            return list(self._dispatched)

    def dead_replicas(self):
        with self._route_lock:
            return sorted(self._dead)

    def respawn_replica(self, replica):
        """Bring one dead replica worker back: a fresh thread on the
        SAME queue, a fresh watchdog channel, the depth gauge re-bound,
        and the replica removed from the router's dead set — the
        supervisor's repair verb (serving/resilience.py). Returns False
        (no-op) when the batcher is closed or the replica is not dead."""
        if self._closed:
            return False
        with self._route_lock:
            if replica not in self._dead:
                return False
            self._dead.discard(replica)
        # fresh heartbeat channel: the dying worker unregistered its old
        # one, and the watchdog must see the reborn worker's beats under
        # the same name
        self._hb_channels[replica] = watchdog.register(
            "batcher:%s" % self.name if self.replicas == 1
            else "batcher:%s:r%d" % (self.name, replica))
        try:
            self.metrics.bind_replica_depth(
                replica, self._replica_depth_fns[replica])
        except Exception:
            _LOG.debug("replica depth gauge rebind failed", exc_info=True)
        w = threading.Thread(target=self._run, args=(replica,), daemon=True,
                             name="mxtpu-batcher-%s-r%d"
                             % (self.name, replica))
        self._workers[replica] = w
        w.start()
        flightrec.record("replica_respawned", model=self.name,
                         replica=replica)
        return True

    @property
    def last_item_sig(self):
        """Per-item ((shape, dtype), ...) of the newest dispatched request,
        or None before any dispatch — the observed signature hot-reload
        prewarm builds synthetic warm batches from."""
        with self._sig_lock:
            return self._last_item_sig

    def pause_intake(self):
        """Reject new submits (ServingClosedError) while the workers keep
        draining what's queued — the unload-last-version drain uses this.
        Unlike close(), fully reversible via resume_intake()."""
        self._paused = True

    def resume_intake(self):
        self._paused = False

    @property
    def alive(self):
        """True while at least one replica worker can still dispatch."""
        return any(w.is_alive() for w in self._workers)

    @property
    def closed(self):
        return self._closed

    def close(self, drain=True, timeout=30.0):
        """Graceful shutdown: refuse new requests, optionally finish the
        queued ones, join every worker. With drain=False queued requests
        fail with ServingClosedError."""
        self._closed = True
        if not drain:
            self._fail_queued(ServingClosedError("server shutting down"))
        deadline = time.monotonic() + timeout
        for w in self._workers:
            w.join(max(0.0, deadline - time.monotonic()))
        # a submit racing this close can slip a request in after a
        # worker's final empty-queue check; fail any such leftovers so no
        # client waits on a queue nobody services
        self._fail_queued(ServingClosedError("server shutting down"))
        # unbind the queue-depth gauge callbacks from the shared telemetry
        # registry (they would otherwise pin this batcher's queues forever
        # and export stale series for an unloaded model)
        try:
            self.metrics.detach_telemetry()
        except Exception:
            pass
        # same discipline for the device-truth gauges this model's
        # dispatches drove: a dead model must not export its last MFU
        try:
            devstats.detach_model(self.name)
        except Exception:
            pass
        # ...and the numerics sentinel's tap series, storm episodes and
        # shadow registration — an unloaded model must not export a
        # frozen abs-max or keep a reference servable pinned
        try:
            numwatch.detach_model(self.name)
        except Exception:
            pass
        # ...and for the SLO engine's burn/budget/alert gauges: an
        # unloaded model must not keep exporting a frozen burn rate
        try:
            from ..telemetry import slo
            slo.REGISTRY.detach_model(self.name)
        except Exception:
            pass
        # ...and the metric-history rings + trend-episode state: an
        # unloaded model must not resurface in the next incident report
        # or pin its per-series rings for process lifetime
        try:
            from ..telemetry import history
            history.detach_model(self.name)
        except Exception:
            pass

    def _fail_queued(self, err):
        for q in self._queues:
            while True:
                try:
                    req = q.get_nowait()
                except _queue.Empty:
                    break
                req.fail(err)

    # ------------------------------------------------------------ worker side
    def _gather(self, replica):
        """Collect the next batch off this replica's queue: block for the
        first request, then keep taking until max_batch_size or the batch
        window elapses."""
        q = self._queues[replica]
        try:
            # the poll period only bounds close() latency — keep it coarse
            # so idle models cost ~4 wakeups/s per replica, not 20
            first = q.get(timeout=0.25)
        except _queue.Empty:
            return None
        batch = [first]
        window_end = time.monotonic() + self.batch_timeout_ms / 1000.0
        while len(batch) < self.max_batch_size:
            remaining = window_end - time.monotonic()
            if remaining <= 0:
                break
            try:
                batch.append(q.get(timeout=remaining))
            except _queue.Empty:
                break
        return batch

    def _bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _run(self, replica):
        died = True
        try:
            self._run_loop(replica)
            died = False
        except BaseException:
            # the loop body already contains the request-failing guards;
            # anything escaping it is a worker-killing defect — log it,
            # then hand this replica's queue back to the router below
            _LOG.error("batcher %r replica %d worker died",
                       self.name, replica, exc_info=True)
        finally:
            # a cleanly-exiting (or dying) worker must not read as a
            # stall: silence from a gone thread is unregistered, silence
            # from a live-but-stuck one is the watchdog's signal
            watchdog.unregister(self._hb_channels[replica])
            if died and not self._closed:
                self._drain_dead_replica(replica)

    def _fail_or_retry_on_death(self, req, replica, err):
        """Completion path for a request held by a dying worker: one
        bounded retry for idempotent predicts (MXTPU_RESILIENCE_RETRY),
        else fail now.

        The retry re-enters the DYING replica's own queue: the death
        path's _reroute_queue sweep (the existing drain-back machinery)
        then carries it to a survivor — or fails it loudly as
        NoReplicasError when none remain. The request keeps its original
        deadline, so a retry can never outlive the client's budget, and
        the ``retried`` flag bounds it to one attempt (a second death
        fails it for good).

        Deaths that originated INSIDE the servable call are never
        retried: the request's own content is the prime suspect (a query
        of death), and re-dispatching it serially kills the survivors —
        the drains-back contract (test_serving_sharded) is that one
        poison request costs one replica while every innocent request
        completes. Only exogenous deaths (the worker killed around the
        dispatch: injection, runtime faults in the batcher's own
        machinery) are safe to re-route."""
        if (self._retry_on_death and not req.retried and not self._closed
                and not getattr(err, "_mxtpu_died_in_servable", False)
                and not (req.deadline is not None
                         and time.monotonic() >= req.deadline)):
            req.retried = True
            try:
                self._queues[replica].put_nowait(req)
            except _queue.Full:
                # a dying replica with a FULL queue: nothing to absorb
                # the retry into without displacing someone — fail below
                _LOG.debug("retry of request %r dropped: queue full",
                           req.request_id)
            else:
                try:
                    _RETRIES.inc(model=self.name)
                except Exception:
                    _LOG.debug("retry counter update failed", exc_info=True)
                flightrec.record("request_retried", model=self.name,
                                 replica=replica,
                                 request_id=req.request_id)
                return
        if getattr(err, "_mxtpu_died_in_servable", False):
            # query of death: the sender gets the servable's own defect,
            # raw — the pre-resilience drains-back contract (the HTTP
            # front-end maps a raw worker-killing BaseException to 503)
            req.fail(err)
            return
        # an exogenous BaseException (injected WorkerKilled, MemoryError
        # in the batcher's own machinery) must not ride a _Request into
        # an arbitrary client thread / the HTTP handler's `except
        # Exception` ladder — surface worker death as the
        # servable-unavailable error it is
        req.fail(err if isinstance(err, Exception) else ServingClosedError(
            "model %r replica %d worker died mid-dispatch (%s)"
            % (self.name, replica, err)))

    def _drain_dead_replica(self, replica):
        """Death path: mark the replica dead so the router skips it,
        detach its depth gauge (a dead replica must not export a frozen
        depth), and re-route everything sitting in its queue — mirror of
        the detach-on-close contract, at replica granularity."""
        with self._route_lock:
            self._dead.add(replica)
        flightrec.record("replica_died", model=self.name, replica=replica)
        try:
            self.metrics.detach_replica_depth(
                self._replica_depth_fns[replica])
        except Exception:
            _LOG.debug("replica depth gauge detach failed", exc_info=True)
        self._reroute_queue(replica)

    def _reroute_queue(self, replica):
        """Drain one (dead) replica's queue back through the router."""
        q = self._queues[replica]
        while True:
            try:
                req = q.get_nowait()
            except _queue.Empty:
                break
            rerouted = False
            for r in self._route():
                try:
                    self._queues[r].put_nowait(req)
                    rerouted = True
                    break
                except _queue.Full:
                    continue
            if not rerouted:
                # no live replica (or all full): fail loudly NOW — a
                # request must never sit in a dead replica's queue until
                # its deadline expires it
                req.fail(NoReplicasError(
                    "model %r replica %d worker died and no live replica "
                    "could absorb its queue" % (self.name, replica)))

    def _run_loop(self, replica):
        while True:
            watchdog.heartbeat(self._hb_channels[replica])
            batch = self._gather(replica)
            if batch is None:
                if self._closed and self._queues[replica].empty():
                    return
                continue
            with self._route_lock:
                self._inflight[replica] += len(batch)
            try:
                self._process_batch(batch, replica)
            except BaseException as e:
                # a worker-killing defect (BaseException escaping the
                # per-batch Exception guards) must still answer the batch
                # it was holding — clients of a dying replica get the
                # error now (or one bounded retry), not a timeout at
                # their deadline
                for req in batch:
                    if not req._event.is_set():
                        self._fail_or_retry_on_death(req, replica, e)
                raise
            finally:
                with self._route_lock:
                    self._inflight[replica] -= len(batch)

    def _process_batch(self, batch, replica):
        now = time.monotonic()
        live = []
        for req in batch:
            if req.deadline is not None and now >= req.deadline:
                try:
                    self.metrics.inc("expired_count")
                except Exception:
                    # telemetry failure must not fail the request path,
                    # but the dropped increment is debug-visible (R005)
                    _LOG.debug("expired_count update failed",
                               exc_info=True)
                req.fail(DeadlineExceededError(
                    "deadline passed while queued (model %r)" % self.name))
            else:
                live.append(req)
        if not live:
            return
        # group by per-input shape/dtype signature: one client's
        # malformed request must not fail well-formed requests that
        # happened to share its gather window (cross-client isolation);
        # homogeneous traffic stays one group = one dispatch
        groups = {}
        for req in live:
            sig = tuple((x.shape, x.dtype.str) for x in req.inputs)
            groups.setdefault(sig, []).append(req)
        for group in groups.values():
            self._dispatch_replica(group, replica)

    def _dispatch_replica(self, live, replica):
        """Pad one shape-homogeneous group to its bucket, dispatch it on
        this replica, and deliver results (or one shared error) to every
        waiter — the per-replica dispatch hot path (mxtpulint
        HOT_PATH_PATTERNS covers it)."""
        if faultlab.armed:
            # faultlab site "batcher.dispatch": an injected FaultInjected
            # fails just this group (real-servable-raise semantics, the
            # worker survives); WorkerKilled and anything else propagate
            # into _run_loop's worker-death path
            try:
                faultlab.fire("batcher.dispatch", model=self.name,
                              replica=replica)
            except faultlab.FaultInjected as e:
                try:
                    self.metrics.inc("error_count", len(live))
                except Exception:
                    _LOG.debug("error_count update failed", exc_info=True)
                for req in live:
                    req.fail(e)
                return
        n = len(live)
        bucket = self._bucket_for(n)
        t0 = time.monotonic()
        request_ids = [r.request_id for r in live
                       if r.request_id is not None]
        with self._depth_lock:
            self._bucket_depth[bucket] = self._bucket_depth.get(bucket, 0) + n
        with self._route_lock:
            self._dispatched[replica] += n
        try:
            self.metrics.inc_replica_dispatch(replica, n)
        except Exception:
            pass
        try:
            self._dispatch_bucketed(live, n, bucket, t0, replica,
                                    request_ids)
        finally:
            with self._depth_lock:
                self._bucket_depth[bucket] -= n

    def _dispatch_bucketed(self, live, n, bucket, t0, replica, request_ids):
        with self._sig_lock:
            self._last_item_sig = tuple((x.shape, x.dtype.str)
                                        for x in live[0].inputs)
        self._trace_queue_waits(live, t0)
        flightrec.record("batch_dispatch", model=self.name, n=n,
                         bucket=bucket, replica=replica)
        # live span on the worker thread: the servable (and, for a
        # BlockServable, EvalStep's eval:step span) nests inside it. A
        # batch has many logical parents — the span parents onto the
        # OLDEST request's captured context; the rest stay findable via
        # args.request_ids.
        with spans.span("serve:batch", parent=live[0].span_ctx,
                        model=self.name, bucket=bucket, batch_size=n,
                        replica=replica, request_ids=request_ids):
            self._dispatch_batch_traced(live, n, bucket, t0, replica,
                                        request_ids)

    def _trace_queue_waits(self, live, t0):
        """Retroactive serve:queue child spans, one per request: queue
        wait is only measurable at dispatch, after the submitting thread
        has long moved on — the record_span queue-boundary form (no
        thread-local stack is touched)."""
        try:
            from .. import profiler
            now_us = profiler.now_us()
            for req in live:
                wait_s = max(0.0, t0 - req.enqueued_at)
                spans.record_span("serve:queue", now_us - wait_s * 1e6,
                                  wait_s * 1e6, parent=req.span_ctx,
                                  request_id=req.request_id,
                                  model=self.name)
        except Exception:
            # tracing must never take down serving, but a queue-wait
            # trace that silently stops emitting is undiagnosable (R005
            # discipline): keep the drop debug-visible
            _LOG.debug("serve:queue span emission failed", exc_info=True)

    def _call_servable(self, stacked, replica, request_ids):
        """The one servable call site: per-replica ``serve:dispatch`` span
        (the loadgen span-join attributes device time per replica off its
        ``replica`` arg; ``request_ids`` make it joinable per request),
        replica kwarg forwarded when the servable declares it. The
        devstats dispatch context labels the MFU observation — which
        fires levels deeper, where the compiled entry's FLOPs are known —
        with THIS model name and replica index."""
        with spans.span("serve:dispatch", model=self.name, replica=replica,
                        batch=int(stacked[0].shape[0]) if stacked else 0,
                        request_ids=request_ids), \
                devstats.dispatch_context(self.name, replica):
            if self._replica_aware:
                return self._dispatch_fn(*stacked, replica=replica)
            return self._dispatch_fn(*stacked)

    def _note_dispatch(self, live, bucket, replica, t0, call_s):
        """Attach the per-request dispatch facts the access-log record is
        assembled from (server.py): queue wait (enqueue -> gather), batch
        time (gather -> now: pad + servable + slice), and the servable
        call's own duration (the device leg). Set BEFORE succeed()/fail()
        so the completion event's happens-before makes them visible to
        the client thread."""
        now = time.monotonic()
        for req in live:
            req.dispatch = {
                "replica": replica, "bucket": bucket,
                "queue_ms": max(0.0, t0 - req.enqueued_at) * 1e3,
                "batch_ms": (now - t0) * 1e3,
                "device_ms": call_s * 1e3 if call_s is not None else None}

    def _dispatch_batch_traced(self, live, n, bucket, t0, replica,
                               request_ids):
        call_s = None
        try:
            # pad by repeating the last row: always shape/dtype-consistent,
            # never introduces out-of-range values. A raising servable must
            # fail THIS batch, not kill the worker thread.
            stacked = tuple(
                onp.stack([r.inputs[i] for r in live]
                          + [live[-1].inputs[i]] * (bucket - n))
                for i in range(len(live[0].inputs)))
            # timer brackets the servable call ONLY: host-side pad/stack
            # time belongs to the batch leg, not the device_ms fact
            tc0 = time.monotonic()
            try:
                outs = self._call_servable(stacked, replica, request_ids)
            except BaseException as e:
                if not isinstance(e, Exception):
                    # a worker-killing BaseException escaping the servable
                    # ITSELF is request-correlated until proven otherwise
                    # (a query of death): mark it so the death path fails
                    # this batch instead of retrying the killer onto a
                    # survivor — one poison request must cost one replica,
                    # not the fleet
                    e._mxtpu_died_in_servable = True
                raise
            call_s = time.monotonic() - tc0
        except Exception as e:  # noqa: BLE001 — forwarded to every waiter
            try:
                self.metrics.inc("error_count", n)
            except Exception:
                pass
            self._note_dispatch(live, bucket, replica, t0, call_s)
            for req in live:
                req.fail(e)
            return
        dur = time.monotonic() - t0
        # numerics sentinel: stride-sampled stats tap over the DEVICE
        # outputs, before the host materialization below — one packed
        # scalar-bundle transfer when sampled, a dict increment when not
        # (tap() never raises; R005)
        numwatch.tap(self.name, "serve:outputs",
                     outs if isinstance(outs, (list, tuple)) else (outs,))
        try:
            # normalize + slice BEFORE delivering anything: malformed
            # servable output (scalar, short dim 0, ragged) must fail the
            # batch loudly, not kill the worker or deliver to only some
            # waiters
            if not isinstance(outs, (list, tuple)):
                outs = (outs,)
            # reviewed sync point: results MUST land on host here — they
            # are sliced per request and handed to arbitrary client
            # threads/HTTP JSON; this is the one place the whole batch
            # pays a single device->host transfer instead of each client
            # paying its own
            outs = [onp.asarray(o) for o in outs]  # mxtpulint: disable=R001
            results = [tuple(o[j] for o in outs) for j in range(n)]
        except Exception as e:  # noqa: BLE001 — forwarded to every waiter
            try:
                self.metrics.inc("error_count", n)
            except Exception:
                pass
            self._note_dispatch(live, bucket, replica, t0, call_s)
            for req in live:
                req.fail(e)
            return
        done = time.monotonic()
        # instrument BEFORE delivering: a client unblocks the moment
        # succeed() fires, and a scrape right after a response must see
        # this batch's counters and trace event already recorded. Guarded:
        # a telemetry failure (misconfigured registry bound, -W error)
        # must neither kill the worker nor leave the waiters hanging.
        try:
            for req in live:
                self.metrics.observe_latency_ms(
                    (done - req.enqueued_at) * 1000.0)
            self.metrics.inc("ok_count", n)
            self.metrics.observe_batch(n, bucket)
        except Exception:
            pass
        self._profile_batch(n, bucket, dur, request_ids)
        self._note_dispatch(live, bucket, replica, t0, call_s)
        for j, req in enumerate(live):
            req.succeed(results[j])
        # shadow sampling: offer this batch (padded inputs + host outputs)
        # to the numerics sentinel's background comparator AFTER delivery —
        # a full shadow queue drops the sample, never delays the response
        numwatch.shadow_offer(self.name, stacked, outs)

    def _profile_batch(self, n, bucket, dur, request_ids=None):
        """Per-batch hook into the framework profiler (no-op unless
        profiler.set_state('run')). ``request_ids`` — the trace ids of the
        live requests in the batch — land as an event arg, so one HTTP
        request is findable queue -> bucket -> device in the trace dump."""
        try:
            from .. import profiler
            # epoch-anchored monotonic us (profiler.now_us — NTP-step safe)
            profiler.record_batch(self.name, n, bucket,
                                  start_us=profiler.now_us() - dur * 1e6,
                                  dur_us=dur * 1e6,
                                  request_ids=request_ids)
        except Exception:  # profiling must never take down serving
            pass
