"""Serving observability (TF-Serving BatchingSession metrics analog).

One ``ServingMetrics`` per registered model: monotonic counters, the
dispatched batch-size histogram (the coalescing proof), and request
latency percentiles from a bounded ring buffer — cheap enough to stay on
for every request, rich enough to tune ``MXTPU_SERVE_*`` capacity knobs
from (see docs/SERVING.md). Exposed programmatically via ``snapshot()``
and over HTTP at ``GET /metrics`` (serving/server.py).
"""
from __future__ import annotations

import threading
from collections import deque

__all__ = ["ServingMetrics", "percentile"]


def percentile(sorted_values, q):
    """Nearest-rank percentile of an ascending-sorted sequence (q in 0..100)."""
    if not sorted_values:
        return None
    rank = max(1, -(-len(sorted_values) * q // 100))  # ceil without floats
    return sorted_values[min(int(rank), len(sorted_values)) - 1]


class ServingMetrics:
    """Thread-safe per-model serving counters + batch histogram + latency ring.

    Latency is end-to-end request time (enqueue -> result ready), the number
    a client observes; the ring buffer bounds memory so a long-lived server
    reports a moving window, not its whole history.
    """

    def __init__(self, latency_window=4096):
        self._lock = threading.Lock()
        self.request_count = 0        # accepted into the queue
        self.ok_count = 0
        self.error_count = 0          # dispatch raised
        self.rejected_count = 0       # queue full (backpressure)
        self.expired_count = 0        # deadline passed while queued
        self.batch_count = 0          # dispatches
        self.batched_items = 0        # real (non-padding) items dispatched
        self.padded_items = 0         # padding rows added to reach a bucket
        self.batch_size_hist = {}     # real batch size -> count
        self._latencies_ms = deque(maxlen=latency_window)
        self.queue_depth_fn = None    # injected by the batcher

    # ------------------------------------------------------------------
    def inc(self, counter, n=1):
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)

    def observe_batch(self, size, bucket):
        with self._lock:
            self.batch_count += 1
            self.batched_items += size
            self.padded_items += bucket - size
            self.batch_size_hist[size] = self.batch_size_hist.get(size, 0) + 1

    def observe_latency_ms(self, ms):
        with self._lock:
            self._latencies_ms.append(ms)

    # ------------------------------------------------------------------
    @property
    def mean_batch_size(self):
        """Mean REAL items per dispatch — > 1 means coalescing is happening."""
        with self._lock:
            if not self.batch_count:
                return 0.0
            return self.batched_items / self.batch_count

    def latency_percentiles_ms(self, qs=(50, 95, 99)):
        with self._lock:
            ordered = sorted(self._latencies_ms)
        return {"p%d" % q: percentile(ordered, q) for q in qs}

    def snapshot(self):
        """One JSON-able dict with every counter, the histogram, and p50/95/99."""
        with self._lock:
            out = {
                "request_count": self.request_count,
                "ok_count": self.ok_count,
                "error_count": self.error_count,
                "rejected_count": self.rejected_count,
                "expired_count": self.expired_count,
                "batch_count": self.batch_count,
                "batched_items": self.batched_items,
                "padded_items": self.padded_items,
                "batch_size_hist": dict(self.batch_size_hist),
                "mean_batch_size": (self.batched_items / self.batch_count
                                    if self.batch_count else 0.0),
                "latency_window": len(self._latencies_ms),
            }
        out["latency_ms"] = self.latency_percentiles_ms()
        if self.queue_depth_fn is not None:
            out["queue_depth"] = self.queue_depth_fn()
        return out
