"""Serving observability (TF-Serving BatchingSession metrics analog).

One ``ServingMetrics`` per registered model: monotonic counters, the
dispatched batch-size histogram (the coalescing proof), and request
latency percentiles from a bounded ring buffer — cheap enough to stay on
for every request, rich enough to tune ``MXTPU_SERVE_*`` capacity knobs
from (see docs/SERVING.md).

Every update is double-written: the per-instance fields feed the
JSON ``snapshot()`` (served at ``GET /metrics.json`` for back-compat) and
the process-wide telemetry registry feeds the Prometheus exposition at
``GET /metrics`` (telemetry/registry.py) — one coherent surface shared
with training, kvstore, and IO metrics (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import math
import threading
from collections import deque

from .. import telemetry

__all__ = ["ServingMetrics", "percentile", "request_accounted"]


def percentile(sorted_values, q):
    """Nearest-rank percentile of an ascending-sorted sequence (q in 0..100).

    rank = ceil(n * q / 100), clamped to [1, n]. The epsilon guards float
    representation error at exact-integer products (e.g. n=70, q=30 gives
    21.000000000000004, which a bare ceil would round UP to rank 22); it
    also keeps small windows exact: q=50 of 1 element is that element,
    q=99 of 2 elements is the max.
    """
    if not sorted_values:
        return None
    n = len(sorted_values)
    q = min(max(float(q), 0.0), 100.0)
    rank = int(math.ceil(n * q / 100.0 - 1e-9))
    return sorted_values[min(max(rank, 1), n) - 1]


# ---------------------------------------------------------------------------
# Shared-registry metrics (one series per model label). Batch-size buckets
# cover the power-of-two bucketing the batcher pads to; latency buckets
# span sub-ms CPU echoes to multi-second compiled first calls.
_REQS = telemetry.counter(
    "mxtpu_serving_requests_total",
    "Requests accepted into a model's serving queue.", ("model",))
_OK = telemetry.counter(
    "mxtpu_serving_ok_total",
    "Requests completed successfully.", ("model",))
_ERRORS = telemetry.counter(
    "mxtpu_serving_errors_total",
    "Requests failed by a raising servable.", ("model",))
_REJECTED = telemetry.counter(
    "mxtpu_serving_rejected_total",
    "Requests rejected at submit time (queue full backpressure).",
    ("model",))
_EXPIRED = telemetry.counter(
    "mxtpu_serving_expired_total",
    "Requests whose deadline passed while queued.", ("model",))
_PREWARMS = telemetry.counter(
    "mxtpu_aot_prewarms_total",
    "Batcher buckets warmed ahead of traffic (hot-reload / warm_spec "
    "prewarm through the shared AOT executable cache).", ("model",))
_BATCHES = telemetry.counter(
    "mxtpu_serving_batches_total", "Dispatched batches.", ("model",))
_BATCHED_ITEMS = telemetry.counter(
    "mxtpu_serving_batched_items_total",
    "Real (non-padding) items dispatched.", ("model",))
_PADDED_ITEMS = telemetry.counter(
    "mxtpu_serving_padded_items_total",
    "Padding rows added to reach a bucket shape.", ("model",))
_BATCH_SIZE = telemetry.histogram(
    "mxtpu_serving_batch_size",
    "Real items per dispatched batch (the coalescing proof).",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256), labelnames=("model",))
_LATENCY_MS = telemetry.histogram(
    "mxtpu_serving_request_latency_ms",
    "End-to-end request latency (enqueue -> result ready) in ms.",
    buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
    labelnames=("model",))
_QUEUE_DEPTH = telemetry.gauge(
    "mxtpu_serving_queue_depth",
    "Requests currently waiting in the model's bounded queue.", ("model",))
_QUEUE_CAPACITY = telemetry.gauge(
    "mxtpu_serving_queue_capacity",
    "Aggregate queue capacity of the model (per-replica bound x "
    "replicas) — the saturation line the metric-history pressure_rising "
    "predictor extrapolates mxtpu_serving_queue_depth toward "
    "(telemetry/history.py; docs/OBSERVABILITY.md).", ("model",))
_BUCKET_DEPTH = telemetry.gauge(
    "mxtpu_serving_bucket_queue_depth",
    "Requests gathered into this batch bucket and not yet completed "
    "(padding + servable dispatch + result slicing). Together with "
    "mxtpu_serving_queue_depth this splits waiting time into queue vs "
    "dispatch — the per-bucket saturation signal the load harness joins "
    "against client latency (docs/LOADGEN.md).", ("model", "bucket"))
_REPLICA_DEPTH = telemetry.gauge(
    "mxtpu_serving_replica_queue_depth",
    "Requests routed to this data-parallel replica and not yet completed "
    "(queued on its dispatch queue + handed to its worker). This is the "
    "exact signal the batcher's least-depth router balances on, so a "
    "persistently deeper replica means a slower executor (bad device, "
    "noisy neighbor) — the per-replica saturation view the load harness "
    "joins against serve:dispatch spans (docs/SERVING.md, docs/LOADGEN.md).",
    ("model", "replica"))
_REPLICA_DISPATCH = telemetry.counter(
    "mxtpu_serving_replica_dispatch_total",
    "Requests dispatched by this data-parallel replica (cumulative) — "
    "compare across replicas to verify the router is balancing "
    "(docs/SERVING.md).", ("model", "replica"))
_TENANT_REQS = telemetry.counter(
    "mxtpu_requests_total",
    "Terminal predict outcomes by model, tenant (X-MXTPU-Tenant header, "
    "clamped via serving/accesslog.clamp_tenant; 'default' when absent) "
    "and HTTP status code — the per-tenant request accounting the SLO "
    "engine and fair scheduling build on (docs/OBSERVABILITY.md 'SLOs "
    "and tenants'). Hostile random tenant values collapse onto the "
    "'_other_' series past MXTPU_TELEMETRY_MAX_SERIES.",
    ("model", "tenant", "code"))
_TENANT_LATENCY_MS = telemetry.histogram(
    "mxtpu_request_latency_ms",
    "End-to-end HTTP predict latency per tenant (body read -> response "
    "computed, the http:predict span window) in ms — the per-tenant "
    "complement of mxtpu_serving_request_latency_ms.",
    buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000,
             10000),
    labelnames=("model", "tenant"))


def request_accounted(model, tenant, code, latency_ms):
    """One terminal HTTP predict outcome (server.py): per-tenant request
    counter + latency histogram on the shared registry. ``code`` is the
    final HTTP status; every outcome counts, including 4xx."""
    code_s = str(int(code))
    _TENANT_REQS.inc(model=model, tenant=tenant, code=code_s)
    _TENANT_LATENCY_MS.observe(latency_ms, model=model, tenant=tenant)


_HTTP_INFLIGHT = telemetry.gauge(
    "mxtpu_http_inflight_requests",
    "Predict requests currently held by the HTTP front-end (body read "
    "through response written). Tracks client-side concurrency pressure: "
    "rising inflight with flat queue depth means time is spent outside "
    "the batcher (docs/LOADGEN.md).")

def http_request_started():
    """One predict request entered the HTTP front-end (server.py)."""
    _HTTP_INFLIGHT.inc()


def http_request_finished():
    _HTTP_INFLIGHT.dec()


_COUNTER_MAP = {
    "request_count": _REQS,
    "ok_count": _OK,
    "error_count": _ERRORS,
    "rejected_count": _REJECTED,
    "expired_count": _EXPIRED,
    "prewarm_count": _PREWARMS,
}


class ServingMetrics:
    """Thread-safe per-model serving counters + batch histogram + latency ring.

    Latency is end-to-end request time (enqueue -> result ready), the number
    a client observes; the ring buffer bounds memory so a long-lived server
    reports a moving window, not its whole history. ``model`` names the
    telemetry-registry label this instance's updates are mirrored onto.
    """

    def __init__(self, latency_window=4096, model="model"):
        self._lock = threading.Lock()
        self.model = model
        self.request_count = 0        # accepted into the queue
        self.ok_count = 0
        self.error_count = 0          # dispatch raised
        self.rejected_count = 0       # queue full (backpressure)
        self.expired_count = 0        # deadline passed while queued
        self.prewarm_count = 0        # buckets warmed ahead of traffic
        self.batch_count = 0          # dispatches
        self.batched_items = 0        # real (non-padding) items dispatched
        self.padded_items = 0         # padding rows added to reach a bucket
        self.batch_size_hist = {}     # real batch size -> count
        self.replica_dispatch = {}    # replica -> requests dispatched
        self._latencies_ms = deque(maxlen=latency_window)
        self._queue_depth_fn = None   # injected by the batcher
        self._bucket_depth_fns = []   # per-bucket samplers, ditto
        self._replica_depth_fns = []  # per-replica samplers, ditto
        self._capacity_fn = None      # constant sampler, ditto

    # ------------------------------------------------------------------
    @property
    def queue_depth_fn(self):
        return self._queue_depth_fn

    @queue_depth_fn.setter
    def queue_depth_fn(self, fn):
        self._queue_depth_fn = fn
        if fn is not None:
            # sampled at scrape time — depth is a point-in-time gauge
            _QUEUE_DEPTH.set_function(fn, model=self.model)

    def set_queue_capacity(self, capacity):
        """Publish the model's aggregate queue capacity (batcher init).
        Bound as a constant CALLBACK, not a set() value, so teardown can
        remove it by identity like every other per-instance series —
        immune to the hot-reload remove-by-label race detach_telemetry
        documents."""
        cap = float(capacity)
        self._capacity_fn = lambda: cap
        _QUEUE_CAPACITY.set_function(self._capacity_fn, model=self.model)

    def bind_bucket_depth(self, bucket, fn):
        """Register ``fn() -> depth`` as the sampler for one batch bucket
        (batcher init — buckets are known up front, so cardinality is
        bounded by the bucket list, not by traffic)."""
        self._bucket_depth_fns.append(fn)
        _BUCKET_DEPTH.set_function(fn, model=self.model, bucket=bucket)

    def bind_replica_depth(self, replica, fn):
        """Register ``fn() -> depth`` as the sampler for one data-parallel
        replica (batcher init — replica count is fixed up front, so
        cardinality is bounded by configuration, not traffic)."""
        with self._lock:
            self._replica_depth_fns.append(fn)
        _REPLICA_DEPTH.set_function(fn, model=self.model, replica=replica)

    def detach_replica_depth(self, fn):
        """Drop ONE replica's depth series (dead-replica path, called from
        the dying worker thread): removal is by callback identity,
        mirroring detach_telemetry, so the other replicas' series keep
        exporting."""
        _REPLICA_DEPTH.remove_function(fn)
        with self._lock:
            try:
                self._replica_depth_fns.remove(fn)
            except ValueError:
                pass

    def inc_replica_dispatch(self, replica, n=1):
        """Count ``n`` requests dispatched by one replica (worker side)."""
        with self._lock:
            self.replica_dispatch[replica] = \
                self.replica_dispatch.get(replica, 0) + n
        _REPLICA_DISPATCH.inc(n, model=self.model, replica=replica)

    def detach_telemetry(self):
        """Drop this instance's gauge-callback series from the shared
        registry (batcher close/unload): a dead model must not keep
        exporting a stale depth, nor keep its queue object alive through
        the callback closure. Removal is by callback IDENTITY, so a
        hot-reload that already re-registered the same model name keeps
        its series, and a series the cardinality clamp re-keyed is still
        found. Counters/histograms stay — they are process-lifetime
        cumulative by Prometheus convention."""
        _QUEUE_DEPTH.remove_function(self._queue_depth_fn)
        _QUEUE_CAPACITY.remove_function(self._capacity_fn)
        for fn in self._bucket_depth_fns:
            _BUCKET_DEPTH.remove_function(fn)
        with self._lock:
            replica_fns = list(self._replica_depth_fns)
            self._replica_depth_fns = []
        for fn in replica_fns:
            _REPLICA_DEPTH.remove_function(fn)

    # ------------------------------------------------------------------
    def inc(self, counter, n=1):
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)
        prom = _COUNTER_MAP.get(counter)
        if prom is not None:
            prom.inc(n, model=self.model)

    def observe_batch(self, size, bucket):
        with self._lock:
            self.batch_count += 1
            self.batched_items += size
            self.padded_items += bucket - size
            self.batch_size_hist[size] = self.batch_size_hist.get(size, 0) + 1
        _BATCHES.inc(model=self.model)
        _BATCHED_ITEMS.inc(size, model=self.model)
        _PADDED_ITEMS.inc(bucket - size, model=self.model)
        _BATCH_SIZE.observe(size, model=self.model)

    def observe_latency_ms(self, ms):
        with self._lock:
            self._latencies_ms.append(ms)
        _LATENCY_MS.observe(ms, model=self.model)

    # ------------------------------------------------------------------
    @property
    def mean_batch_size(self):
        """Mean REAL items per dispatch — > 1 means coalescing is happening."""
        with self._lock:
            if not self.batch_count:
                return 0.0
            return self.batched_items / self.batch_count

    def latency_percentiles_ms(self, qs=(50, 95, 99)):
        with self._lock:
            ordered = sorted(self._latencies_ms)
        return {"p%d" % q: percentile(ordered, q) for q in qs}

    def snapshot(self):
        """One JSON-able dict with every counter, the histogram, and p50/95/99."""
        with self._lock:
            out = {
                "request_count": self.request_count,
                "ok_count": self.ok_count,
                "error_count": self.error_count,
                "rejected_count": self.rejected_count,
                "expired_count": self.expired_count,
                "prewarm_count": self.prewarm_count,
                "batch_count": self.batch_count,
                "batched_items": self.batched_items,
                "padded_items": self.padded_items,
                "batch_size_hist": dict(self.batch_size_hist),
                "replica_dispatch": {str(r): c for r, c in
                                     sorted(self.replica_dispatch.items())},
                "mean_batch_size": (self.batched_items / self.batch_count
                                    if self.batch_count else 0.0),
                "latency_window": len(self._latencies_ms),
            }
        out["latency_ms"] = self.latency_percentiles_ms()
        if self.queue_depth_fn is not None:
            out["queue_depth"] = self.queue_depth_fn()
        return out
