"""Mesh-sharded servables: tensor-parallel predict behind the batcher.

``serving/`` executes requests through servables; this module provides the
one that runs a live Gluon block ACROSS a device mesh instead of on one
chip (ROADMAP item 1, GSPMD sharding per arXiv 2004.13336 / MLPerf
TPU-pod serving per arXiv 1909.09756):

- **Tensor parallelism** — parameters annotated by
  ``parallel.tensor_parallel`` (ColParallelDense / RowParallelDense /
  shard_params) carry a ``PartitionSpec`` over the ``tp`` mesh axis;
  :class:`MeshServable` lays each parameter out with the matching
  ``jax.sharding.NamedSharding`` and compiles ONE partitioned program —
  XLA inserts the all-reduce/all-gather on ICI. Un-annotated parameters
  replicate. This is how a model too big for one chip serves.
- **Data-parallel replica groups** — ``replicas=N`` carves the device
  list into N disjoint tp-sized groups, one mesh each, and
  ``predict_batch(..., replica=r)`` dispatches on group ``r`` — the
  batcher's per-replica workers each drive their own chips, so dp x tp
  compose on one host (8 devices = 4 replicas x tp=2).

Executables go through the process-wide ``aot.CACHE`` keyed with the
mesh signature (plus the replica group), so prewarm covers every
(bucket x replica) pair and hot-reloads of an identical model never
recompile; with ``MXTPU_AOT_CACHE_DIR`` set the partitioned StableHLO is
persisted per key (sharded-artifact residue of ROADMAP item 3) and a
fresh process with the same device topology loads instead of re-tracing.

Inputs arrive replicated (every chip sees the whole batch; the tp
collectives operate on weights/activations), outputs are replicated back
and returned as device arrays — the batcher's one reviewed sync point
materializes them host-side.
"""
from __future__ import annotations

import logging
import time as _time

from .. import aot
from .. import config
from ..telemetry import devstats, spans

__all__ = ["MeshServable", "serving_mesh"]

_LOG = logging.getLogger(__name__)


def serving_mesh(tp=None, devices=None, tp_axis="tp"):
    """A 1-axis tp mesh over the first ``tp`` devices (the
    :class:`MeshServable` default when no mesh is passed;
    tp default: MXTPU_SERVE_TP)."""
    import jax
    if tp is None:
        tp = int(config.get_env("MXTPU_SERVE_TP"))
    if devices is None:
        devices = jax.devices()
    if tp < 1 or tp > len(devices):
        raise ValueError("tp=%d needs 1..%d devices" % (tp, len(devices)))
    import numpy as onp
    from jax.sharding import Mesh
    return Mesh(onp.array(devices[:tp]), axis_names=(tp_axis,))


class MeshServable:
    """Serve a live, initialized Gluon block tensor-parallel over a mesh
    (optionally in data-parallel replica groups).

    ``predict_batch(*stacked[, replica=r])`` is the batcher entry point;
    declaring ``replica`` makes the batcher (and the registry dispatch
    closure) pass each worker's replica index through, and the prewarm
    path warm every (bucket x replica) pair.
    """

    def __init__(self, net, mesh=None, tp=None, tp_axis="tp", replicas=1,
                 model_id=None):
        import jax
        from ..gluon import _functional
        self.tp_axis = tp_axis
        replicas = int(replicas)
        if replicas < 1:
            raise ValueError("replicas must be >= 1 (got %d)" % replicas)
        if mesh is not None:
            meshes = [mesh]
            if replicas > 1:
                raise ValueError(
                    "pass either an explicit mesh (one group) or "
                    "replicas=N (N auto-carved tp groups), not both")
        else:
            if tp is None:
                tp = int(config.get_env("MXTPU_SERVE_TP"))
            devices = jax.devices()
            if replicas * tp > len(devices):
                raise ValueError(
                    "replicas=%d x tp=%d needs %d devices, have %d"
                    % (replicas, tp, replicas * tp, len(devices)))
            meshes = [serving_mesh(tp, devices[g * tp:(g + 1) * tp],
                                   tp_axis)
                      for g in range(replicas)]
        self.meshes = meshes
        self.mesh = meshes[0]
        from .. import jit as _jit
        params, param_arrs, pure_fn, _aux = _functional.make_pure_fn(
            net, train_mode=False)
        self._pure_fn = pure_fn
        self._params = params
        # traces of pure_fn swap the live net's param NDArray._data; two
        # replica-group workers compile-missing concurrently (distinct
        # cache keys, so single-flight does not serialize them) must not
        # interleave their trace windows — same contract as EvalStep
        self._trace_lock = _jit._net_trace_lock(net)
        # one replicated-or-tp-sharded copy of the weights per group —
        # each replica group owns its chips outright (true data
        # parallelism: no cross-group communication ever)
        self._group_params = [
            [jax.device_put(a._data, self._param_sharding(p, m))
             for p, a in zip(params, param_arrs)]
            for m in meshes]
        if model_id is None:
            model_id = aot.model_id_for(net, extra=("mesh-serve",))
        self._model_id = model_id

    def _param_sharding(self, p, mesh):
        """p.sharding (a PartitionSpec from tensor_parallel annotations)
        on this group's mesh; un-annotated params replicate — the same
        rule DataParallelTrainStep applies (parallel/data_parallel.py)."""
        from jax.sharding import NamedSharding, PartitionSpec
        spec = getattr(p, "sharding", None)
        if spec is None:
            return NamedSharding(mesh, PartitionSpec())
        if isinstance(spec, NamedSharding):
            return NamedSharding(mesh, spec.spec)
        return NamedSharding(mesh, spec)

    @property
    def replicas(self):
        return len(self.meshes)

    # ------------------------------------------------------------------
    def _compiled(self, datas, group):
        """The partitioned executable for this input signature on replica
        group ``group``, through the shared AOT cache (mesh signature +
        group index in the key: two groups hold the same program compiled
        against DIFFERENT devices, so they must not share an entry)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = self.meshes[group]
        gparams = self._group_params[group]
        key = aot.cache_key(self._model_id, aot.input_signature(datas),
                            kind="serve", mesh=mesh,
                            extra=("rep", group))
        pure_fn = self._pure_fn
        repl = NamedSharding(mesh, PartitionSpec())

        def fwd(param_datas, *xs):
            import jax as _jax
            outs, _aux = pure_fn(param_datas, list(xs),
                                 _jax.random.PRNGKey(0))
            return tuple(outs)

        # ONE spec construction closed over by build() AND handed to the
        # artifact loader: the fresh-build and artifact-load compile
        # signatures can never diverge
        param_specs = [jax.ShapeDtypeStruct(d.shape, d.dtype,
                                            sharding=d.sharding)
                       for d in gparams]
        in_specs = [jax.ShapeDtypeStruct(d.shape, d.dtype, sharding=repl)
                    for d in datas]

        def build():
            param_shardings = [d.sharding for d in gparams]
            jitted = jax.jit(fwd,
                             in_shardings=(param_shardings,)
                             + (repl,) * len(datas),
                             out_shardings=repl)
            exported = None
            with spans.span("eval:build", model_id=self._model_id,
                            mesh=str(aot.mesh_sig(mesh)), replica=group), \
                    self._trace_lock:
                try:
                    import jax.export as jax_export
                    exported = jax_export.export(jitted)(param_specs,
                                                         *in_specs)
                    fn = jax.jit(exported.call).lower(
                        param_specs, *in_specs).compile()
                except Exception:
                    # non-exportable partitioned program: direct AOT
                    # compile, in-memory only (no persisted artifact)
                    _LOG.debug("mesh-serve export failed; direct AOT",
                               exc_info=True)
                    exported = None
                    fn = jitted.lower(param_specs, *in_specs).compile()
            return fn, None, exported

        return aot.compile_cached(key, build, exportable=True,
                                  arg_specs=(param_specs,) + tuple(in_specs))

    def predict_batch(self, *stacked_inputs, replica=0):
        """Batcher entry point: run one stacked batch tensor-parallel on
        replica group ``replica % self.replicas``. Returns device arrays
        (replicated on the group's mesh) — the batcher materializes them
        host-side at its one reviewed sync point."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        group = int(replica) % len(self.meshes)
        mesh = self.meshes[group]
        repl = NamedSharding(mesh, PartitionSpec())
        import numpy as onp
        # reviewed host->device point: the batcher hands this path host
        # numpy already (its padding stacks on host); asarray only
        # materializes list/scalar payloads from direct callers — never a
        # device->host transfer of a live device array
        datas = [jax.device_put(
                     x if hasattr(x, "shape") and hasattr(x, "dtype")
                     else onp.asarray(x), repl)  # mxtpulint: disable=R001
                 for x in stacked_inputs]
        entry = self._compiled(datas, group)
        t0 = _time.perf_counter()
        out = entry.fn(self._group_params[group], *datas)
        # device-truth MFU for the tp group: under the batcher (ambient
        # dispatch context) its reviewed sync point would pay this wait
        # on the same thread moments later anyway, so always observe
        # there; a direct caller keeps async dispatch unless
        # MXTPU_DEVSTATS_EVAL_SYNC opts in (same contract as EvalStep).
        # The program's FLOPs spread over the whole tp group, so the
        # observation divides by the group's chip count.
        if entry.stats is not None and (
                devstats.in_dispatch_context()
                or config.get_env("MXTPU_DEVSTATS_EVAL_SYNC")):
            try:
                jax.block_until_ready(out)
            except Exception:
                pass
            devstats.observe_dispatch("serve", entry.stats,
                                      _time.perf_counter() - t0,
                                      model=self._model_id, replica=group,
                                      devices=len(mesh.devices.flat))
        if isinstance(out, (list, tuple)) and len(out) == 1:
            return (out[0],)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)
