"""Self-healing serving: the reflexes that act on failure signals
(docs/RESILIENCE.md).

The observability stack reports failure — a dead replica worker lands in
``dead_replicas()`` and flips ``/healthz`` degraded, a crashed decode
loop reads unhealthy — but nothing in PRs 10-17 *acts* on any of it. The
:class:`Supervisor` here closes that loop, treating component death as a
normal event to recover from rather than an error to report (the
TensorFlow system-design stance on worker failure, arXiv:1605.08695):

- **Replica respawn.** A daemon thread polls every registered batcher's
  dead set (``MXTPU_RESILIENCE_POLL_S``) and respawns dead replica
  workers via :meth:`DynamicBatcher.respawn_replica` after an
  exponential backoff with seeded jitter (``base * 2^(deaths-1)``,
  capped; ``MXTPU_RESILIENCE_BACKOFF_BASE_S`` / ``_CAP_S``). The jitter
  keeps a fleet of supervisors from respawning in lockstep.
- **Crash-loop circuit breaker.** ``MXTPU_RESILIENCE_CRASH_N`` deaths of
  one replica within ``MXTPU_RESILIENCE_CRASH_WINDOW_S`` seconds parks
  it: no further respawns (flightrec ``replica_parked``), and because a
  parked replica stays in the router's dead set, ``/healthz`` keeps
  reporting degraded until an operator calls :meth:`unpark` — respawning
  a deterministic crasher forever would just burn the error budget.
- **Decode-loop resurrection.** Engines are marked supervised
  (``set_supervised(True)``), so a dying decode loop PRESERVES its
  sequences; the supervisor then drives
  :meth:`GenerativeEngine.resurrect` under the same backoff/park policy.
  Survivors continue bit-exactly from their KV state; rows lost with a
  mid-donation pool retire as ``finish_reason="engine_restart"``.

The other two reflexes live where the state lives: the bounded
single-retry of replica-death predict failures in serving/batcher.py
(``mxtpu_retries_total``), and last-known-good version rollback in
serving/registry.py (flightrec ``rolled_back_to``).
"""
from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque

from .. import config
from ..telemetry import flightrec

__all__ = ["Supervisor"]

_LOG = logging.getLogger(__name__)


class Supervisor:
    """Respawn dead batcher replicas and resurrect dead decode loops for
    every model in ``registry``, with exponential backoff + jitter and a
    crash-loop circuit breaker. One supervisor per registry; start() /
    stop() bracket the serving lifetime (ServingServer does not start
    one implicitly — chaos tests need supervised and unsupervised
    fleets)."""

    def __init__(self, registry, poll_s=None, backoff_base_s=None,
                 backoff_cap_s=None, crash_n=None, crash_window_s=None,
                 seed=0):
        self.registry = registry
        self.poll_s = float(poll_s if poll_s is not None
                            else config.get_env("MXTPU_RESILIENCE_POLL_S"))
        self.backoff_base_s = float(
            backoff_base_s if backoff_base_s is not None
            else config.get_env("MXTPU_RESILIENCE_BACKOFF_BASE_S"))
        self.backoff_cap_s = float(
            backoff_cap_s if backoff_cap_s is not None
            else config.get_env("MXTPU_RESILIENCE_BACKOFF_CAP_S"))
        self.crash_n = int(crash_n if crash_n is not None
                           else config.get_env("MXTPU_RESILIENCE_CRASH_N"))
        self.crash_window_s = float(
            crash_window_s if crash_window_s is not None
            else config.get_env("MXTPU_RESILIENCE_CRASH_WINDOW_S"))
        # seeded jitter: deterministic in tests, still decorrelates a
        # fleet whose supervisors seed differently
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._deaths = {}       # (kind, model, replica) -> deque[monotonic]
        self._due = {}          # (kind, model, replica) -> respawn-at
        self._parked = set()    # (kind, model, replica)
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------- lifecycle
    def start(self):
        """Start the poll thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mxtpu-supervisor")
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        """Stop supervising. Dead-but-supervised engines are resurrected
        one last time (their preserved sequences must not strand), then
        every engine reverts to the unsupervised death path."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        for name, engine in self._engines().items():
            try:
                if not engine.closed and not engine.alive:
                    engine.resurrect()
            except Exception:
                _LOG.error("final resurrection of %r failed", name,
                           exc_info=True)
            try:
                engine.set_supervised(False)
            except Exception:
                _LOG.debug("unsupervising %r failed", name, exc_info=True)

    @property
    def alive(self):
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------ inspection
    def describe(self):
        """Park/backoff state snapshot (the /debug surface + tests)."""
        with self._lock:
            return {
                "alive": self.alive,
                "parked": sorted("%s:%s:r%s" % (k, m, r)
                                 for (k, m, r) in self._parked),
                "pending": {"%s:%s:r%s" % (k, m, r): round(t, 3)
                            for (k, m, r), t in self._due.items()}}

    def parked(self, model, replica=None):
        """True when the replica (or, with replica=None, the model's
        decode loop) is parked by the crash-loop breaker."""
        key = (("gen", str(model), 0) if replica is None
               else ("replica", str(model), int(replica)))
        with self._lock:
            return key in self._parked

    def unpark(self, model, replica=None):
        """Operator verb: forget a parked component's crash history so
        the next poll respawns it."""
        key = (("gen", str(model), 0) if replica is None
               else ("replica", str(model), int(replica)))
        with self._lock:
            was = key in self._parked
            self._parked.discard(key)
            self._deaths.pop(key, None)
            self._due.pop(key, None)
        return was

    # -------------------------------------------------------------- internals
    def _engines(self):
        try:
            return dict(self.registry.engines())
        except Exception:
            _LOG.debug("engine scan failed", exc_info=True)
            return {}

    def _batchers(self):
        try:
            return dict(self.registry.batchers())
        except Exception:
            _LOG.debug("batcher scan failed", exc_info=True)
            return {}

    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:
                # the supervisor must outlive anything it supervises; a
                # scan hiccup is logged, never fatal (R005)
                _LOG.error("supervisor poll failed", exc_info=True)

    def poll_once(self):
        """One scan: schedule/execute respawns and resurrections that
        are due. Public so tests can drive the state machine without the
        poll thread."""
        now = time.monotonic()
        for name, batcher in self._batchers().items():
            if batcher.closed:
                continue
            for r in batcher.dead_replicas():
                self._consider(("replica", name, r), now,
                               lambda b=batcher, r=r: b.respawn_replica(r))
        for name, engine in self._engines().items():
            if engine.closed:
                continue
            # mark supervised on sight, so the NEXT death preserves
            # state; an engine loaded mid-flight is adopted within one
            # poll period
            try:
                if not getattr(engine, "_supervised", False):
                    engine.set_supervised(True)
            except Exception:
                _LOG.debug("supervising %r failed", name, exc_info=True)
            if not engine.alive:
                self._consider(("gen", name, 0), now,
                               lambda e=engine: e.resurrect())

    def _consider(self, key, now, repair):
        """Backoff/park state machine for one dead component: first
        sighting records the death and schedules the repair after the
        backoff; a later poll past the due time runs it; crash-looping
        parks it."""
        with self._lock:
            if key in self._parked:
                return
            due = self._due.get(key)
            if due is None:
                dq = self._deaths.setdefault(key, deque())
                dq.append(now)
                while dq and now - dq[0] > self.crash_window_s:
                    dq.popleft()
                if len(dq) >= self.crash_n:
                    self._parked.add(key)
                    deaths = len(dq)
                    park = True
                else:
                    delay = min(self.backoff_cap_s,
                                self.backoff_base_s * 2 ** (len(dq) - 1))
                    delay *= 1.0 + 0.25 * self._rng.random()
                    self._due[key] = now + delay
                    return
            elif now >= due:
                del self._due[key]
                park = False
            else:
                return
        kind, model, replica = key
        if park:
            _LOG.error(
                "%s %r%s crash-looped (%d deaths in %.1fs) — PARKED; "
                "health stays degraded until unpark()",
                "replica" if kind == "replica" else "decode loop", model,
                " r%d" % replica if kind == "replica" else "",
                deaths, self.crash_window_s)
            flightrec.record(
                "replica_parked" if kind == "replica" else "genloop_parked",
                model=model, replica=replica, deaths=deaths,
                window_s=self.crash_window_s)
            return
        try:
            repair()
        except Exception:
            _LOG.error("repair of %s %r r%s failed (will re-observe)",
                       kind, model, replica, exc_info=True)
