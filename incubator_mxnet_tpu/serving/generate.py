"""Generative-inference serving: continuous batching over a paged KV
cache (the subsystem ROADMAP item 1's kernels exist to feed).

One ``GenerativeEngine`` owns one model's autoregressive serving:

- **Prefill** rides the existing :class:`~.batcher.DynamicBatcher`:
  prompts are padded to the fixed ``MXTPU_GEN_PREFILL_LEN`` shape (true
  length rides as a scalar input), so cross-request coalescing hits the
  same handful of compiled batch buckets one-shot predict traffic does.
  The prefill program returns the prompt's full K/V stack plus the FIRST
  sampled token — TTFT is one batched dispatch, never a decode-loop wait.
- **Decode** is a persistent single-thread loop over an in-flight batch:
  each step embeds every live sequence's last token, appends its K/V
  into the paged pool (ops/kvcache.py), attends over the cache, and
  samples the next token. Requests JOIN the batch between steps (their
  prefill K/V is scattered into freshly allocated blocks) and LEAVE the
  moment they retire (EOS / max-tokens / client disconnect / pool
  exhaustion), freeing their blocks — batch composition changes per
  step, compiled shapes never do: the loop pads the live set up to a
  fixed ladder of decode-batch buckets, every bucket AOT-prewarmed via
  ``aot.compile_cached`` (kind="decode"), so steady-state decode
  performs ZERO XLA compiles (the CI generate stage asserts it on the
  compile counter and on ``gen:compile`` span absence).

Per-row numerics are BATCH-COMPOSITION-INDEPENDENT by construction: the
sampling key is ``fold_in(PRNGKey(seed_row), n_generated_row)`` computed
inside the program, every attention read is masked by the row's own
length, and row-wise matmul/softmax results are bitwise identical across
bucket sizes on a fixed backend — so a sequence decoded mid-batch,
joined and left around by strangers, emits exactly the tokens the
sequential reference (``generate_sequential``, same compiled programs at
bucket 1 on a private pool) emits. tests/test_generate.py pins that
bit-exactness; the CI stage uses the sequential path as the goodput
baseline continuous batching must beat.

Donation contract: the decode and KV-join programs donate the pool
argument (``donate_argnums=(0,)``), so the multi-MB cache updates in
place instead of round-tripping HBM every step. tools/hlolint's H002
generalization lints the persisted ``decode-*`` artifacts for exactly
this input→output aliasing at error severity; ``warm()`` routes its
fresh artifacts through the same load gate the predict registry uses.

The model served here is ``TinyLM`` — a self-contained two-layer
pre-norm transformer (tied embeddings, paramless RMSNorm, no positional
encoding) whose weights are derived from a seed and baked into the
compiled programs as constants. It is deliberately small: the subsystem
under test is the serving machinery (paging, batching, zero-compile
steady state, streaming, SLOs), not the language model.

Telemetry: ``gen:prefill`` / ``gen:decode_step`` spans (request_ids
attached, so the loadgen span join attributes device time per request),
``mxtpu_gen_tokens_total{model,tenant,phase}``,
``mxtpu_gen_inflight_seqs``, ``mxtpu_gen_kv_blocks_{used,total}``, the
``mxtpu_gen_inter_token_ms`` histogram, and — when
``MXTPU_GEN_SLO_INTER_TOKEN_MS`` is set — one per-tenant
``<model>/inter_token/<tenant>`` SLO fed a 200-coded outcome per token
gap (telemetry/slo.py ``observe_named``). See docs/GENERATE.md.
"""
from __future__ import annotations

import logging
import queue as _queue
import threading
import time
from collections import deque

import numpy as onp

from .. import aot, config
from .. import jit as jit_mod
from .. import telemetry
from ..ops import kvcache
from ..telemetry import faultlab, flightrec, numwatch, spans, watchdog
from ..telemetry import slo as slo_mod
from . import accesslog
from .batcher import DynamicBatcher, QueueFullError, ServingClosedError, \
    default_buckets

__all__ = ["GenerativeEngine", "GenStream", "BadGenRequest", "TinyLM",
           "EOS_TOKEN", "QueueFullError", "ServingClosedError"]

_LOG = logging.getLogger(__name__)

#: Retiring token id: a sampled 0 ends the sequence (reason "eos").
EOS_TOKEN = 0

_FINISH_REASONS = ("eos", "max_tokens", "disconnect", "kv_oom", "error",
                   "numeric_error", "engine_restart")

_TOKENS = telemetry.counter(
    "mxtpu_gen_tokens_total",
    "Tokens through the generative engine: phase=prefill counts prompt "
    "tokens ingested, phase=decode counts tokens GENERATED (the goodput "
    "numerator loadgen --generate reports).",
    ("model", "tenant", "phase"))
_INFLIGHT = telemetry.gauge(
    "mxtpu_gen_inflight_seqs",
    "Sequences currently owned by the engine: decoding in the in-flight "
    "batch plus admitted-but-waiting joins.", ("model",))
_KV_USED = telemetry.gauge(
    "mxtpu_gen_kv_blocks_used",
    "KV pool blocks held by live sequences.", ("model",))
_KV_TOTAL = telemetry.gauge(
    "mxtpu_gen_kv_blocks_total",
    "KV pool capacity in blocks (MXTPU_GEN_KV_BLOCKS).", ("model",))
_INTER_TOKEN_MS = telemetry.histogram(
    "mxtpu_gen_inter_token_ms",
    "Gap between consecutive streamed tokens of one sequence, measured "
    "at engine emit (excludes HTTP write). The p99 here is what the "
    "per-tenant inter_token SLO objectives budget.",
    buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500),
    labelnames=("model",))


class BadGenRequest(ValueError):
    """Client-malformed generate request (HTTP 400): bad token ids,
    empty/oversized prompt, max_new_tokens out of range."""


# ------------------------------------------------------------------ TinyLM
class TinyLM:
    """Seed-derived two-layer pre-norm transformer, 256-token byte
    vocabulary, tied embeddings, no positional encoding. The weights are
    CLOSED OVER by the compiled programs (baked constants): the whole
    model is ~100 KB, and constant-baking keeps every program's runtime
    argument list down to the serving state (pool / tables / tokens),
    which is what the donation and zero-compile contracts are about."""

    VOCAB = 256
    D_MODEL = 64
    LAYERS = 2
    HEADS = 2
    HEAD_DIM = 32

    def __init__(self, seed=0):
        import jax
        self.seed = int(seed)
        key = jax.random.PRNGKey(self.seed)
        def draw(shape):
            nonlocal key
            key, sub = jax.random.split(key)
            return jax.random.normal(sub, shape, "float32") * 0.02
        d, h, hd = self.D_MODEL, self.HEADS, self.HEAD_DIM
        self.emb = draw((self.VOCAB, d))
        self.layers = [
            {"wq": draw((d, h * hd)), "wk": draw((d, h * hd)),
             "wv": draw((d, h * hd)), "wo": draw((h * hd, d)),
             "w1": draw((d, 4 * d)), "w2": draw((4 * d, d))}
            for _ in range(self.LAYERS)]

    def model_id(self):
        """Stable digest (aot.CacheKey model_id): seed + architecture —
        a fresh process with the same seed resolves the same persisted
        artifacts."""
        return "tinylm-s%d-v%d-d%d-l%d-h%dx%d" % (
            self.seed, self.VOCAB, self.D_MODEL, self.LAYERS, self.HEADS,
            self.HEAD_DIM)

    # -------------------------------------------------------- pure pieces
    @staticmethod
    def _rms(x):
        import jax.numpy as jnp
        ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return x / jnp.sqrt(ms + 1e-6)

    def _mlp(self, layer, x):
        import jax.numpy as jnp
        return jnp.maximum(self._rms(x) @ layer["w1"], 0.0) @ layer["w2"]

    def _sample(self, logits, key, temperature, top_k):
        """Greedy when temperature <= 0; else temperature softmax
        restricted to the top_k ranked logits (top_k <= 0 = full vocab).
        Rank masking (argsort of argsort) instead of a dynamic slice
        keeps per-row top_k jit-safe."""
        import jax
        import jax.numpy as jnp
        greedy = jnp.argmax(logits).astype(jnp.int32)
        scaled = (logits / jnp.maximum(temperature, 1e-6)
                  ).astype(jnp.float32)
        k_eff = jnp.where(top_k > 0, top_k, logits.shape[-1])
        rank = jnp.argsort(jnp.argsort(-scaled))
        masked = jnp.where(rank < k_eff, scaled, -jnp.inf)
        sampled = jax.random.categorical(key, masked).astype(jnp.int32)
        return jnp.where(temperature <= 0.0, greedy, sampled)

    def prefill_one(self, tokens, length, seed, temperature, top_k):
        """One row's prompt pass: causal self-attention over the padded
        prompt -> (k_all, v_all) in write_seq layout (L, layers, heads,
        head_dim) + the first generated token, sampled inside the
        program with fold_in(key(seed), 0)."""
        import jax
        import jax.numpy as jnp
        L = tokens.shape[0]
        h, hd = self.HEADS, self.HEAD_DIM
        x = self.emb[tokens]                          # (L, d)
        pos = jnp.arange(L, dtype=jnp.int32)
        causal = pos[None, :] <= pos[:, None]         # (Lq, Lk)
        ks, vs = [], []
        scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        for layer in self.layers:
            hn = self._rms(x)
            q = (hn @ layer["wq"]).reshape(L, h, hd)
            k = (hn @ layer["wk"]).reshape(L, h, hd)
            v = (hn @ layer["wv"]).reshape(L, h, hd)
            s = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) * scale
            s = jnp.where(causal[None], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
            o = jnp.einsum("hqk,khd->qhd", p, v).reshape(L, h * hd)
            x = x + o @ layer["wo"]
            x = x + self._mlp(layer, x)
            ks.append(k)
            vs.append(v)
        k_all = jnp.stack(ks, axis=1)                 # (L, layers, h, hd)
        v_all = jnp.stack(vs, axis=1)
        x_last = jnp.take(x, length - 1, axis=0)      # clamp-safe; len >= 1
        logits = x_last @ self.emb.T
        key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)
        first = self._sample(logits, key, temperature, top_k)
        return k_all, v_all, first

    def decode_step(self, pool, block_tables, lengths, last_tokens, seeds,
                    n_generated, temperatures, top_ks, active):
        """One continuous-batching step over the whole bucket: append
        every row's last token K/V at its own position, attend over its
        own cache prefix, sample its next token with its own
        fold_in(key(seed_row), n_generated_row). ``pool`` is DONATED by
        the compiled program — the in-place cache update H002-decode
        lints for."""
        import jax
        import jax.numpy as jnp
        B = last_tokens.shape[0]
        h, hd = self.HEADS, self.HEAD_DIM
        x = self.emb[last_tokens]                     # (B, d)
        for li, layer in enumerate(self.layers):
            hn = self._rms(x)
            q = (hn @ layer["wq"]).reshape(B, h, hd)
            k = (hn @ layer["wk"]).reshape(B, h, hd)
            v = (hn @ layer["wv"]).reshape(B, h, hd)
            pool = kvcache.append_token(pool, block_tables, lengths, li,
                                        k, v, active=active)
            keys, vals = kvcache.gather_layer(pool, block_tables, li)
            att_len = jnp.maximum(lengths + 1, 1)
            o = kvcache.paged_attention(q, keys, vals, att_len)
            x = x + o.reshape(B, h * hd) @ layer["wo"]
            x = x + self._mlp(layer, x)
        logits = x @ self.emb.T                       # (B, V)
        keys_r = jax.vmap(
            lambda s, n: jax.random.fold_in(jax.random.PRNGKey(s), n)
        )(seeds, n_generated)
        next_t = jax.vmap(self._sample)(logits, keys_r, temperatures,
                                        top_ks)
        # numerics sentinel: per-row logit health, fused into the step
        # program — a row with any non-finite logit samples garbage, and
        # the engine retires it (finish_reason "numeric_error") instead
        # of streaming the garbage token. One extra bool[B] output rides
        # the existing host transfer; no separate tap dispatch.
        row_finite = jnp.all(jnp.isfinite(logits), axis=-1)
        return pool, next_t, row_finite


# ------------------------------------------------------------- stream handle
class GenStream:
    """The streaming handle one submit() returns: a bounded queue of
    ``("tok", id)`` events terminated by one ``("end", reason)``. The
    HTTP front-end iterates it into chunked-response lines; a consumer
    that dies calls ``cancel()`` and the decode loop retires the row at
    its next step (reason "disconnect")."""

    def __init__(self, request_id, tenant, maxsize=0):
        self.request_id = request_id
        self.tenant = tenant
        self.finish_reason = None
        self._q = _queue.Queue(maxsize=maxsize)
        self._cancel = threading.Event()

    @property
    def cancelled(self):
        return self._cancel.is_set()

    def cancel(self):
        """Client-gone signal: the engine frees the row's KV blocks at
        the next decode step. Idempotent; safe from any thread."""
        self._cancel.set()

    def get(self, timeout=None):
        """Next event, ('tok', id) or ('end', reason); raises
        queue.Empty on timeout."""
        return self._q.get(timeout=timeout)

    def __iter__(self):
        """Token ids until the terminal event (blocking; the engine's
        step cadence bounds the gaps)."""
        while True:
            kind, val = self.get(timeout=600.0)
            if kind == "end":
                self.finish_reason = val
                return
            yield val

    def tokens(self, timeout=600.0):
        """Drain to completion -> (token list, finish reason)."""
        out = []
        deadline = time.monotonic() + timeout
        while True:
            kind, val = self.get(timeout=max(0.0, deadline -
                                             time.monotonic()))
            if kind == "end":
                self.finish_reason = val
                return out, val
            out.append(val)

    # engine side
    def _emit(self, tok):
        self._q.put(("tok", int(tok)))

    def _end(self, reason):
        self.finish_reason = reason
        self._q.put(("end", reason))


class _Seq:
    """Decode-loop state of one admitted sequence."""

    __slots__ = ("stream", "request_id", "tenant", "seed", "temperature",
                 "top_k", "max_new", "length", "last_token", "n_generated",
                 "blocks", "table", "k_all", "v_all", "t_last", "slo_name")

    def __init__(self, stream, k_all, v_all, length, first_token, seed,
                 temperature, top_k, max_new, slo_name):
        self.stream = stream
        self.request_id = stream.request_id
        self.tenant = stream.tenant
        self.k_all = k_all          # (PREFILL_LEN, layers, h, hd), numpy
        self.v_all = v_all
        self.length = int(length)   # K/V entries in cache once joined
        self.last_token = int(first_token)
        self.seed = int(seed)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.max_new = int(max_new)
        self.n_generated = 1        # the prefill-sampled first token
        self.blocks = None
        self.table = None
        self.t_last = time.monotonic()
        self.slo_name = slo_name


# ------------------------------------------------------------------- engine
class GenerativeEngine:
    """Continuous-batching generative server for one model.

    Lifecycle: construct -> (``prewarm`` compiles/loads every program
    bucket and lints the fresh decode artifacts) -> ``submit()`` per
    request -> ``close()``. The decode loop thread starts at
    construction and idles at ``MXTPU_GEN_STEP_IDLE_MS`` granularity
    when no sequence is live.
    """

    def __init__(self, name="tinylm", model=None, seed=0, block_size=None,
                 num_blocks=None, max_batch=None, prefill_len=None,
                 max_tokens=None, prewarm=None, eos_token=EOS_TOKEN,
                 batch_timeout_ms=None):
        self.name = name
        self.model = model if model is not None else TinyLM(seed)
        self.eos_token = int(eos_token)
        self.block_size = int(block_size if block_size is not None
                              else config.get_env("MXTPU_GEN_BLOCK_SIZE"))
        self.num_blocks = int(num_blocks if num_blocks is not None
                              else config.get_env("MXTPU_GEN_KV_BLOCKS"))
        self.max_batch = int(max_batch if max_batch is not None
                             else config.get_env("MXTPU_GEN_MAX_BATCH"))
        self.prefill_len = int(prefill_len if prefill_len is not None
                               else config.get_env("MXTPU_GEN_PREFILL_LEN"))
        self.max_tokens = int(max_tokens if max_tokens is not None
                              else config.get_env("MXTPU_GEN_MAX_TOKENS"))
        self.step_idle_s = float(
            config.get_env("MXTPU_GEN_STEP_IDLE_MS")) / 1000.0
        self._slo_ms = config.get_env("MXTPU_GEN_SLO_INTER_TOKEN_MS")
        if prewarm is None:
            prewarm = bool(config.get_env("MXTPU_GEN_PREWARM"))
        # longest cache a sequence can need: full prompt + every
        # generated token but the last (whose K/V is never appended)
        self.max_blocks = kvcache.blocks_for(
            self.prefill_len + self.max_tokens, self.block_size)
        if self.max_blocks > self.num_blocks:
            raise ValueError(
                "one max-length sequence needs %d KV blocks but the pool "
                "holds %d — raise MXTPU_GEN_KV_BLOCKS or shrink "
                "MXTPU_GEN_PREFILL_LEN/MXTPU_GEN_MAX_TOKENS"
                % (self.max_blocks, self.num_blocks))
        self.decode_buckets = default_buckets(self.max_batch)
        self._model_id = self.model.model_id()
        m = self.model
        self._alloc = kvcache.BlockAllocator(self.num_blocks)
        # every pool rebind keeps this shape; spec builders read the
        # immutable tuple so only the decode loop ever touches _pool
        self._pool_shape = kvcache.pool_shape(
            self.num_blocks, self.block_size, m.LAYERS, m.HEADS, m.HEAD_DIM)
        self._pool = kvcache.make_pool(
            self.num_blocks, self.block_size, m.LAYERS, m.HEADS, m.HEAD_DIM)
        # program tables (bucket -> compiled fn); misses compile through
        # aot.compile_cached, so post-warm lookups never build
        self._fn_lock = threading.Lock()
        self._prefill_fns = {}
        self._decode_fns = {}
        self._write_fn_cached = None
        # decode-loop state: _active is owned by the loop thread; _pending
        # and the wake condition are the submit->loop handoff
        self._active = []
        self._pend_lock = threading.Lock()
        self._pending = deque()
        self._pending_cap = max(16, 4 * self.max_batch)
        self._wake = threading.Condition(self._pend_lock)
        self._closed = False
        # resilience state (serving/resilience.py): _supervised flips the
        # decode loop's death path from retire-everything to
        # preserve-for-resurrect; _pool_hazard is True exactly while the
        # pool is donated to a compiled call — a loop that dies inside
        # that window lost every active row's KV (resurrect() retires
        # them as "engine_restart"), a loop that dies outside it left
        # survivors bit-exactly resumable
        self._supervised = False
        self._pool_hazard = False
        self._inflight_fn = lambda: self._inflight_count()
        self._kv_used_fn = lambda: self._alloc.used
        self._kv_total_fn = lambda: self._alloc.total
        try:
            _INFLIGHT.set_function(self._inflight_fn, model=self.name)
            _KV_USED.set_function(self._kv_used_fn, model=self.name)
            _KV_TOTAL.set_function(self._kv_total_fn, model=self.name)
        except Exception:
            _LOG.debug("gen gauge binding failed", exc_info=True)
        # prefill coalescing rides the standard batcher; its servable is
        # the bucket-compiled prefill program lookup
        self._prefill = DynamicBatcher(
            self._prefill_dispatch, max_batch_size=self.max_batch,
            batch_timeout_ms=batch_timeout_ms,
            name="%s-prefill" % self.name, replicas=1)
        if prewarm:
            self.warm()
        self._hb = watchdog.register("genloop:%s" % self.name)
        self._thread = threading.Thread(target=self._decode_loop,
                                        daemon=True,
                                        name="mxtpu-gen-%s" % self.name)
        self._thread.start()

    # ------------------------------------------------------------ compiling
    def _specs(self, *shape_dtypes):
        import jax
        return tuple(jax.ShapeDtypeStruct(s, d) for s, d in shape_dtypes)

    def _compile(self, tag, fn, arg_specs, kind, donate=()):
        """Build-or-load one program through the shared AOT cache. Fresh
        builds are counted on the jit compile counter under this
        program's kind and traced as ``gen:compile`` spans — the
        steady-state zero-compile assertion watches exactly these."""
        import jax
        key = aot.cache_key(
            self._model_id, aot.input_signature(arg_specs), kind=kind,
            extra=(tag,))

        def build():
            t0 = time.monotonic()
            donate_n = jit_mod._donate(tuple(donate))
            jitted = jax.jit(fn, donate_argnums=donate_n) if donate_n \
                else jax.jit(fn)
            exported = None
            try:
                from jax import export as jax_export
                exported = jax_export.export(jitted)(*arg_specs)
                inner = jax.jit(exported.call, donate_argnums=donate_n) \
                    if donate_n else jax.jit(exported.call)
                compiled = inner.lower(*arg_specs).compile()
            except Exception:
                _LOG.debug("gen %s export failed; direct AOT", tag,
                           exc_info=True)
                exported = None
                compiled = jitted.lower(*arg_specs).compile()
            dur = time.monotonic() - t0
            try:
                jit_mod._COMPILES.inc(kind=kind)
                jit_mod._COMPILE_SECONDS.inc(dur, kind=kind)
            except Exception:
                pass
            jit_mod._record_compile_span("gen:compile", dur)
            return compiled, {}, exported

        entry = aot.compile_cached(key, build, exportable=True,
                                   arg_specs=arg_specs)
        return entry.fn

    def _prefill_fn(self, bucket):
        with self._fn_lock:
            fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        import jax
        m = self.model
        L = self.prefill_len
        batched = jax.vmap(m.prefill_one)
        specs = self._specs(
            ((bucket, L), "int32"), ((bucket,), "int32"),
            ((bucket,), "int32"), ((bucket,), "float32"),
            ((bucket,), "int32"))
        fn = self._compile("prefill-b%d" % bucket, batched, specs,
                           kind="serve")
        with self._fn_lock:
            self._prefill_fns[bucket] = fn
        return fn

    def _decode_fn(self, bucket):
        with self._fn_lock:
            fn = self._decode_fns.get(bucket)
        if fn is not None:
            return fn
        m = self.model
        specs = self._specs(
            (self._pool_shape, "float32"),
            ((bucket, self.max_blocks), "int32"), ((bucket,), "int32"),
            ((bucket,), "int32"), ((bucket,), "int32"),
            ((bucket,), "int32"), ((bucket,), "float32"),
            ((bucket,), "int32"), ((bucket,), "bool"))
        fn = self._compile("decode-b%d" % bucket, m.decode_step, specs,
                           kind="decode", donate=(0,))
        with self._fn_lock:
            self._decode_fns[bucket] = fn
        return fn

    def _write_fn(self):
        if self._write_fn_cached is not None:
            return self._write_fn_cached
        m = self.model
        specs = self._specs(
            (self._pool_shape, "float32"),
            ((self.max_blocks,), "int32"),
            ((self.prefill_len, m.LAYERS, m.HEADS, m.HEAD_DIM), "float32"),
            ((self.prefill_len, m.LAYERS, m.HEADS, m.HEAD_DIM), "float32"),
            ((), "int32"))
        fn = self._compile("kvjoin", kvcache.write_seq, specs,
                           kind="decode", donate=(0,))
        self._write_fn_cached = fn
        return fn

    def warm(self):
        """Compile/load every fixed-shape program — all prefill batch
        buckets, all decode-batch buckets, the KV-join scatter — then
        route the freshly inserted decode artifacts through the hlolint
        load gate (MXTPU_HLOLINT_GATE): a decode program that copies its
        pool (H002 at error severity) refuses to serve."""
        t0 = time.monotonic()
        with aot.collect_inserts() as fresh:
            for b in self._prefill.buckets:
                with spans.span("aot:warm", model=self.name,
                                what="gen-prefill", bucket=b):
                    self._prefill_fn(b)
            for b in self.decode_buckets:
                with spans.span("aot:warm", model=self.name,
                                what="gen-decode", bucket=b):
                    self._decode_fn(b)
            with spans.span("aot:warm", model=self.name, what="gen-kvjoin"):
                self._write_fn()
        self._gate_artifacts(fresh)
        flightrec.record("gen_warm", model=self.name,
                         prefill_buckets=len(self._prefill.buckets),
                         decode_buckets=len(self.decode_buckets),
                         dur_ms=round((time.monotonic() - t0) * 1e3, 1))

    def _gate_artifacts(self, entries):
        """The registry's hlolint load-gate discipline, engine-side: lint
        what the warm just produced; error findings (a decode program
        with zero aliasing) fail the load instead of serving slow."""
        if not config.get_env("MXTPU_HLOLINT_GATE"):
            return
        try:
            from tools.hlolint import gate as hlogate
        except ImportError:
            return
        try:
            errors, warns = hlogate.lint_entries(entries)
            hlogate.publish(errors + warns, model=self.name)
        except Exception:
            _LOG.warning("gen hlolint gate failed open", exc_info=True)
            return
        if errors:
            flightrec.record("hlolint_refused", model=self.name,
                             errors=[f.rule for f in errors])
            raise RuntimeError(
                "hlolint refused generative load of %r: %s"
                % (self.name, "; ".join("%s %s: %s" % (f.path, f.rule,
                                                       f.message)
                                        for f in errors)))

    # -------------------------------------------------------------- metrics
    def _inflight_count(self):
        with self._pend_lock:
            pend = len(self._pending)
        return len(self._active) + pend

    def kv_blocks(self):
        """(used, total) — test/debug hook mirroring the gauges."""
        return self._alloc.used, self._alloc.total

    # --------------------------------------------------------------- submit
    def _validate(self, prompt, max_new_tokens, temperature, top_k, seed):
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError):
            raise BadGenRequest("prompt must be a list of token ids")
        if not prompt:
            raise BadGenRequest("prompt must not be empty")
        if len(prompt) > self.prefill_len:
            raise BadGenRequest(
                "prompt length %d exceeds MXTPU_GEN_PREFILL_LEN=%d"
                % (len(prompt), self.prefill_len))
        if any(t < 0 or t >= self.model.VOCAB for t in prompt):
            raise BadGenRequest("token ids must be in [0, %d)"
                               % self.model.VOCAB)
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_tokens)
        if not 1 <= max_new <= self.max_tokens:
            raise BadGenRequest(
                "max_new_tokens must be in [1, %d] (MXTPU_GEN_MAX_TOKENS)"
                % self.max_tokens)
        try:
            temperature = float(temperature)
            top_k = int(top_k)
            # PRNG seeds ride as int32 program inputs
            seed = int(seed) & 0x7FFFFFFF
        except (TypeError, ValueError):
            raise BadGenRequest(
                "temperature/top_k/seed must be numeric")
        return prompt, max_new, temperature, top_k, seed

    def submit(self, prompt, max_new_tokens=None, temperature=0.0,
               top_k=0, seed=0, tenant=None, request_id=None,
               deadline_ms=None):
        """Prefill NOW (batched, synchronous — the returned stream
        already holds the first token), then hand the sequence to the
        decode loop. Raises BadGenRequest (400), QueueFullError (429),
        ServingClosedError (503); batcher deadline errors propagate
        (504)."""
        if self._closed:
            raise ServingClosedError("engine %r is shut down" % self.name)
        prompt, max_new, temperature, top_k, seed = self._validate(
            prompt, max_new_tokens, temperature, top_k, seed)
        tenant = accesslog.clamp_tenant(tenant)
        slo_name = None
        if self._slo_ms is not None:
            slo_name = "%s/inter_token/%s" % (self.name, tenant)
            try:
                slo_mod.REGISTRY.define(slo_name, self.name,
                                        kind="inter_token",
                                        latency_ms=self._slo_ms)
            except Exception:
                _LOG.debug("inter_token SLO define failed", exc_info=True)
                slo_name = None
        with self._pend_lock:
            backlog = len(self._pending)
        if backlog >= self._pending_cap:
            raise QueueFullError(
                "engine %r: %d sequences awaiting decode admission "
                "(cap %d) — the KV pool or decode batch is saturated"
                % (self.name, backlog, self._pending_cap))
        P = len(prompt)
        padded = onp.zeros(self.prefill_len, onp.int32)
        padded[:P] = prompt
        with spans.span("gen:prefill", model=self.name,
                        request_id=request_id, tenant=tenant,
                        prompt_len=P):
            req = self._prefill.submit(
                padded, onp.int32(P), onp.int32(seed),
                onp.float32(temperature), onp.int32(top_k),
                deadline_ms=deadline_ms, request_id=request_id,
                tenant=tenant)
            k_all, v_all, first = req.result(
                self._prefill.result_timeout(req))
        first = int(first)
        stream = GenStream(request_id, tenant)
        try:
            _TOKENS.inc(P, model=self.name, tenant=tenant, phase="prefill")
        except Exception:
            pass
        seq = _Seq(stream, k_all, v_all, P, first, seed, temperature,
                   top_k, max_new, slo_name)
        self._emit_token(seq, first, first_token=True)
        if first == self.eos_token:
            stream._end("eos")
            return stream
        if max_new <= 1:
            stream._end("max_tokens")
            return stream
        with self._wake:
            if self._closed:
                stream._end("error")
                raise ServingClosedError("engine %r is shut down"
                                         % self.name)
            self._pending.append(seq)
            self._wake.notify()
        return stream

    def _emit_token(self, seq, tok, first_token=False):
        """Account then deliver (instrument-before-deliver: a scrape the
        moment the client unblocks must already see this token). The
        first token's delay is TTFT, not an inter-token gap — it counts
        on the token counter but not on the gap histogram/SLO."""
        now = time.monotonic()
        gap_ms = (now - seq.t_last) * 1e3
        seq.t_last = now
        try:
            _TOKENS.inc(model=self.name, tenant=seq.tenant, phase="decode")
            if not first_token:
                _INTER_TOKEN_MS.observe(gap_ms, model=self.name)
        except Exception:
            _LOG.debug("gen token metrics failed", exc_info=True)
        if seq.slo_name is not None and not first_token:
            try:
                slo_mod.REGISTRY.observe_named(seq.slo_name, 200,
                                               latency_ms=gap_ms)
            except Exception:
                _LOG.debug("inter_token SLO observe failed", exc_info=True)
        seq.stream._emit(tok)

    # ---------------------------------------------------------- decode loop
    def _admit(self):
        """Move pending sequences into the in-flight batch: allocate
        their block tables, scatter their prefill K/V into the pool
        (donated join program). A pool too full for the HEAD sequence
        leaves the queue intact — retirements keep freeing blocks, so
        admission is backpressure, never failure, while anything is
        still decoding. With NOTHING decoding the pool is empty, so an
        OOM then means the pool can never hold the sequence (guarded at
        construction) — retire it as kv_oom rather than deadlock."""
        while len(self._active) < self.max_batch:
            with self._wake:
                if not self._pending:
                    return
                seq = self._pending[0]
                if seq.stream.cancelled:
                    self._pending.popleft()
                    seq.stream._end("disconnect")
                    continue
                need = kvcache.blocks_for(seq.length + seq.max_new - 1,
                                          self.block_size)
                try:
                    blocks = self._alloc.alloc(need)
                except kvcache.KVCacheOOM:
                    if self._active:
                        return
                    self._pending.popleft()
                    flightrec.record("gen_kv_oom", model=self.name,
                                     request_id=seq.request_id, need=need)
                    seq.stream._end("kv_oom")
                    continue
                self._pending.popleft()
            seq.blocks = blocks
            table = onp.full(self.max_blocks, self.num_blocks, onp.int32)
            table[:len(blocks)] = blocks
            seq.table = table
            # adopt into _active BEFORE the donated join: a loop death
            # inside the write window must find this sequence somewhere
            # (resurrect()'s hazard path retires it as engine_restart) —
            # popped-from-pending but not-yet-active would strand it
            self._active.append(seq)
            # reviewed cross-thread flag: resurrect() reads this only
            # AFTER the decode thread is observed dead (is_alive()
            # false), which is the happens-before edge; a GIL-atomic
            # bool write needs no lock
            self._pool_hazard = True  # mxtpulint: disable=R010
            self._pool = self._write_fn()(
                self._pool, table, seq.k_all, seq.v_all,
                onp.int32(seq.length))
            self._pool_hazard = False  # mxtpulint: disable=R010
            seq.k_all = seq.v_all = None
            flightrec.record("gen_join", model=self.name,
                             request_id=seq.request_id, blocks=len(blocks),
                             batch=len(self._active))

    def _retire(self, seq, reason):
        self._active.remove(seq)
        if seq.blocks:
            self._alloc.free(seq.blocks)
            seq.blocks = None
        seq.stream._end(reason)
        flightrec.record("gen_retire", model=self.name,
                         request_id=seq.request_id, reason=reason,
                         generated=seq.n_generated)

    def _bucket_for(self, n):
        for b in self.decode_buckets:
            if b >= n:
                return b
        return self.decode_buckets[-1]

    def _step(self):
        if faultlab.armed:
            # faultlab site "generate.step": fires BEFORE the donated
            # decode call, so an injected loop death leaves the pool —
            # and every survivor's KV — intact for a bit-exact
            # resurrection (the _pool_hazard window below is the real
            # donation hazard)
            faultlab.fire("generate.step", model=self.name,
                          batch=len(self._active))
        act = list(self._active)
        n = len(act)
        B = self._bucket_for(n)
        mb = self.max_blocks
        tables = onp.full((B, mb), self.num_blocks, onp.int32)
        lengths = onp.ones(B, onp.int32)
        last = onp.zeros(B, onp.int32)
        seeds = onp.zeros(B, onp.int32)
        ngen = onp.zeros(B, onp.int32)
        temps = onp.zeros(B, onp.float32)
        topks = onp.zeros(B, onp.int32)
        active = onp.zeros(B, bool)
        for i, s in enumerate(act):
            tables[i] = s.table
            lengths[i] = s.length
            last[i] = s.last_token
            seeds[i] = s.seed
            ngen[i] = s.n_generated
            temps[i] = s.temperature
            topks[i] = s.top_k
            active[i] = True
        fn = self._decode_fn(B)
        with spans.span("gen:decode_step", model=self.name, batch=n,
                        bucket=B,
                        request_ids=[s.request_id for s in act
                                     if s.request_id is not None]):
            # reviewed cross-thread flag: resurrect() reads this only
            # after the decode thread is observed dead — see _admit's
            # twin bracket
            self._pool_hazard = True  # mxtpulint: disable=R010
            self._pool, next_t, row_finite = fn(self._pool, tables, lengths,
                                                last, seeds, ngen, temps,
                                                topks, active)
            self._pool_hazard = False  # mxtpulint: disable=R010
            # reviewed sync point: one host transfer for the whole step's
            # sampled tokens (plus the fused per-row logit-health bools),
            # inside the step span so the span measures true step
            # latency
            next_t = onp.asarray(next_t)
            finite = onp.asarray(row_finite)
        # feed the sentinel the step's finite fraction over LIVE rows
        # (note() applies the nonfinite counter + nan_storm hysteresis
        # and never raises; padding rows carry zero activations and
        # must not dilute the signal)
        if n:
            numwatch.note(self.name, "gen:logits",
                          float(onp.mean(finite[:n])))
        for i, s in enumerate(act):
            tok = int(next_t[i])
            s.length += 1
            s.last_token = tok
            s.n_generated += 1
            if s.stream.cancelled:
                self._retire(s, "disconnect")
                continue
            if not bool(finite[i]):
                # non-finite decode logits: the sampled token is garbage —
                # free the row's KV blocks and end the stream loudly
                # instead of emitting it (gen_retire carries the reason)
                self._retire(s, "numeric_error")
                continue
            self._emit_token(s, tok)
            if tok == self.eos_token:
                self._retire(s, "eos")
            elif s.n_generated >= s.max_new:
                self._retire(s, "max_tokens")

    def _decode_loop(self):
        try:
            while True:
                watchdog.heartbeat(self._hb)
                self._admit()
                if self._active:
                    self._step()
                    continue
                with self._wake:
                    if self._closed and not self._pending:
                        return
                    if not self._pending:
                        self._wake.wait(max(self.step_idle_s, 0.001)
                                        if not self._closed else 0.01)
        except BaseException as e:
            _LOG.error("gen decode loop for %r died", self.name,
                       exc_info=True)
            if self._supervised and not self._closed:
                # a supervisor owns this corpse: PRESERVE _active and
                # _pending for resurrect() — survivors continue
                # bit-exactly from their KV state, and rows the donated
                # pool took with it are retired there as
                # "engine_restart". Never preserve without a supervisor:
                # that would strand every stream forever.
                flightrec.record("genloop_died", model=self.name,
                                 active=len(self._active),
                                 pending=len(self._pending),
                                 pool_hazard=self._pool_hazard)
            else:
                for s in list(self._active):
                    try:
                        self._retire(s, "error")
                    except Exception:
                        _LOG.error(
                            "retiring %r after decode-loop death failed",
                            s.request_id, exc_info=True)
                with self._wake:
                    pend, self._pending = list(self._pending), deque()
                for s in pend:
                    s.stream._end("error")
            if not isinstance(e, Exception):
                raise
        finally:
            watchdog.unregister(self._hb)

    # ------------------------------------------------------------ resilience
    def set_supervised(self, flag=True):
        """Resilience-contract toggle (serving/resilience.py): with a
        supervisor attached, a dying decode loop preserves its sequence
        state for :meth:`resurrect` instead of ending every stream as
        "error". Only a supervisor that guarantees a resurrection may
        set this — preserved sequences are otherwise stranded."""
        self._supervised = bool(flag)

    def resurrect(self):
        """Rebuild a dead decode loop (the supervisor's repair verb).

        Sequences still in ``_active``/``_pending`` are re-adopted by the
        fresh thread and continue bit-exactly from their KV state —
        per-row numerics are batch-composition-independent, and a step
        interrupted before its donated call re-derives the same
        ``fold_in(key(seed), n_generated)`` tokens. Rows whose KV went
        down with a mid-donation pool (``_pool_hazard``) are retired NOW
        as ``finish_reason="engine_restart"`` — loudly, never silently
        stranded — and the pool is rebuilt empty for the survivors in
        ``_pending``. Returns False (no-op) when the engine is closed or
        the loop is still alive."""
        if self._closed or self._thread.is_alive():
            return False
        retired = 0
        if self._pool_hazard:
            for s in list(self._active):
                try:
                    self._retire(s, "engine_restart")
                    retired += 1
                except Exception:
                    _LOG.error("engine_restart retirement of %r failed",
                               s.request_id, exc_info=True)
            m = self.model
            self._pool = kvcache.make_pool(
                self.num_blocks, self.block_size, m.LAYERS, m.HEADS,
                m.HEAD_DIM)
            self._pool_hazard = False
        self._hb = watchdog.register("genloop:%s" % self.name)
        self._thread = threading.Thread(target=self._decode_loop,
                                        daemon=True,
                                        name="mxtpu-gen-%s" % self.name)
        self._thread.start()
        with self._wake:
            self._wake.notify_all()
        flightrec.record("genloop_resurrected", model=self.name,
                         survivors=len(self._active),
                         pending=len(self._pending), retired=retired)
        return True

    # ------------------------------------------------- sequential reference
    def generate_sequential(self, prompt, max_new_tokens=None,
                            temperature=0.0, top_k=0, seed=0):
        """Decode one sequence alone, through the SAME compiled programs
        at bucket 1 on a PRIVATE pool -> (tokens, finish_reason). This
        is both the bit-exactness oracle for the join/leave tests and
        the per-request baseline the CI stage requires continuous
        batching to beat on tokens/s."""
        prompt, max_new, temperature, top_k, seed = self._validate(
            prompt, max_new_tokens, temperature, top_k, seed)
        P = len(prompt)
        padded = onp.zeros((1, self.prefill_len), onp.int32)
        padded[0, :P] = prompt
        k_all, v_all, first = self._prefill_fn(1)(
            padded, onp.array([P], onp.int32),
            onp.array([seed], onp.int32),
            onp.array([temperature], onp.float32),
            onp.array([top_k], onp.int32))
        tokens = [int(first[0])]
        if tokens[0] == self.eos_token:
            return tokens, "eos"
        if max_new <= 1:
            return tokens, "max_tokens"
        m = self.model
        pool = kvcache.make_pool(self.num_blocks, self.block_size,
                                 m.LAYERS, m.HEADS, m.HEAD_DIM)
        need = kvcache.blocks_for(P + max_new - 1, self.block_size)
        table = onp.full(self.max_blocks, self.num_blocks, onp.int32)
        table[:need] = onp.arange(need)
        pool = self._write_fn()(pool, table, onp.asarray(k_all[0]),
                                onp.asarray(v_all[0]), onp.int32(P))
        fn = self._decode_fn(1)
        length, last, ngen = P, tokens[0], 1
        reason = "max_tokens"
        while ngen < max_new:
            pool, nt, fin = fn(pool, table[None],
                               onp.array([length], onp.int32),
                               onp.array([last], onp.int32),
                               onp.array([seed], onp.int32),
                               onp.array([ngen], onp.int32),
                               onp.array([temperature], onp.float32),
                               onp.array([top_k], onp.int32),
                               onp.array([True]))
            if not bool(onp.asarray(fin)[0]):
                reason = "numeric_error"
                break
            last = int(onp.asarray(nt)[0])
            tokens.append(last)
            length += 1
            ngen += 1
            if last == self.eos_token:
                reason = "eos"
                break
        return tokens, reason

    # -------------------------------------------------------------- dispatch
    def _prefill_dispatch(self, prompts, lengths, seeds, temps, top_ks):
        """The batcher's servable: route the stacked bucket through that
        bucket's compiled prefill program."""
        fn = self._prefill_fn(int(prompts.shape[0]))
        return fn(prompts, lengths, seeds, temps, top_ks)

    # ------------------------------------------------------------ inspection
    @property
    def alive(self):
        """Decode-loop thread still running (health surface)."""
        return self._thread.is_alive()

    @property
    def closed(self):
        return self._closed

    def describe(self):
        """The GET /v1/models-shaped description of this engine."""
        return {"name": self.name,
                "kind": "generator",
                "model_id": self._model_id,
                "block_size": self.block_size,
                "kv_blocks_total": self._alloc.total,
                "kv_blocks_used": self._alloc.used,
                "max_batch": self.max_batch,
                "decode_buckets": list(self.decode_buckets),
                "prefill_len": self.prefill_len,
                "max_tokens": self.max_tokens,
                "inflight": self._inflight_count(),
                "eos_token": self.eos_token,
                "closed": self._closed}

    # ---------------------------------------------------------------- close
    def close(self, timeout=30.0):
        """Stop intake, finish/fail what's in flight, release telemetry
        bindings. Live sequences finish their natural retirement (the
        loop drains active + pending before exiting)."""
        self._closed = True
        try:
            self._prefill.close(drain=True, timeout=timeout)
        except Exception:
            _LOG.debug("prefill batcher close failed", exc_info=True)
        with self._wake:
            self._wake.notify_all()
        self._thread.join(timeout)
        for g, fn in ((_INFLIGHT, self._inflight_fn),
                      (_KV_USED, self._kv_used_fn),
                      (_KV_TOTAL, self._kv_total_fn)):
            try:
                g.remove_function(fn)
            except Exception:
                pass
        try:
            slo_mod.REGISTRY.detach_model(self.name)
        except Exception:
            pass
        # numerics sentinel: drop this engine's tap series and any open
        # storm episode (detach-on-close; the prefill batcher's close
        # already detached its own sites)
        try:
            numwatch.detach_model(self.name)
        except Exception:
            pass
