"""Production inference serving (TF-Serving analog, arXiv:1605.08695 §4.3).

The training side of this framework compiles whole steps; this package is
the traffic side: it turns exported ``.mxtpu`` artifacts
(``contrib.serving``) and live Gluon blocks into a servable endpoint that
saturates an accelerator under many small requests.

Layers (each importable alone):

- ``batcher``  — DynamicBatcher: N data-parallel replica workers
  (MXTPU_SERVE_REPLICAS), each with a bounded queue + size-or-deadline
  coalescing into bucketed batch shapes (each bucket compiles once),
  fed by a least-depth router; dead replicas drain back to survivors.
- ``registry`` — ModelRegistry: named, versioned models, hot reload with
  connection draining and (bucket x replica) AOT prewarm, one batcher
  per model.
- ``sharded``  — MeshServable: tensor-parallel predict over a device
  mesh (weights follow parallel.tensor_parallel annotations via
  jax.sharding.NamedSharding), composable with replica groups
  (docs/SERVING.md "Sharded serving").
- ``resilience`` — Supervisor: self-healing reflexes — dead replica
  workers respawned under exponential backoff + jitter, crash-looping
  ones parked by a circuit breaker, dead decode loops resurrected with
  their in-flight sequences preserved (docs/RESILIENCE.md; pairs with
  the bounded predict retry in ``batcher`` and last-known-good version
  rollback in ``registry``).
- ``metrics``  — ServingMetrics: counters, batch-size histogram,
  p50/p95/p99 latency from a ring buffer; every update is mirrored onto
  the process-wide telemetry registry (docs/OBSERVABILITY.md).
- ``server``   — ServingServer: stdlib ThreadingHTTPServer front-end with
  JSON tensors, /healthz, Prometheus text at /metrics (legacy JSON at
  /metrics.json), per-request X-Request-Id tracing, and explicit 429
  backpressure.

Sixty-second start::

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import serving

    reg = serving.ModelRegistry()
    reg.load("mnist", mx.contrib.serving.load("model.mxtpu"))
    with serving.ServingServer(reg, port=8080) as srv:
        ...   # POST /v1/models/mnist:predict

Capacity knobs are the ``MXTPU_SERVE_*`` env vars (config.py registry;
docs/SERVING.md has tuning guidance). Single-host scope: one process,
one registry — put a load balancer in front for fleet serving.
"""
from __future__ import annotations

from .batcher import (DynamicBatcher, QueueFullError, DeadlineExceededError,
                      NoReplicasError, ServingClosedError, default_buckets)
from .metrics import ServingMetrics, percentile
from .registry import ModelRegistry, BlockServable, ModelNotFoundError
from .resilience import Supervisor
from .server import ServingServer, serve
from .sharded import MeshServable, serving_mesh

__all__ = [
    "DynamicBatcher", "QueueFullError", "DeadlineExceededError",
    "NoReplicasError", "ServingClosedError", "default_buckets",
    "ServingMetrics", "percentile",
    "ModelRegistry", "BlockServable", "ModelNotFoundError",
    "Supervisor",
    "ServingServer", "serve",
    "MeshServable", "serving_mesh",
]
