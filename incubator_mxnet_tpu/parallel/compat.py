"""jax API compatibility shims for the parallel package.

``shard_map`` moved twice across jax releases: newest jax exposes it as
``jax.shard_map``, a long range of releases only as
``jax.experimental.shard_map.shard_map``, and very old ones not at all.
Every SPMD module in this package resolves it through THIS one shim
instead of touching ``jax.shard_map`` directly, so the installed jax
decides once, here — not as an AttributeError inside a traced pipeline
step.

When neither spelling exists, calling :func:`shard_map` raises
:class:`ShardMapUnavailable`, which subclasses ``unittest.SkipTest``:
a test that reaches a shard_map-backed path on such a jax records a
clean SKIP (pytest honors SkipTest) instead of an error, while
non-test callers still get a loud, descriptive exception.
"""
from __future__ import annotations

import unittest

import jax

__all__ = ["shard_map", "require_shard_map", "HAVE_SHARD_MAP",
           "ShardMapUnavailable", "axis_size", "pcast"]


class ShardMapUnavailable(unittest.SkipTest):
    """No shard_map in the installed jax (neither ``jax.shard_map`` nor
    ``jax.experimental.shard_map.shard_map``). Subclasses
    ``unittest.SkipTest`` so tests skip cleanly; production callers see
    the message below."""


def _resolve():
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, False
    try:
        from jax.experimental.shard_map import shard_map as fn
        return fn, True
    except ImportError:
        return None, False


_IMPL, _IMPL_IS_LEGACY = _resolve()

#: True when the installed jax provides a shard_map implementation.
HAVE_SHARD_MAP = _IMPL is not None


def _kwarg_names():
    import inspect
    try:
        return frozenset(inspect.signature(_IMPL).parameters)
    except (TypeError, ValueError):
        return frozenset()


_IMPL_KWARGS = _kwarg_names() if HAVE_SHARD_MAP else frozenset()


def require_shard_map():
    """The resolved shard_map callable, or raise ShardMapUnavailable."""
    if _IMPL is None:
        raise ShardMapUnavailable(
            "the installed jax (%s) has neither jax.shard_map nor "
            "jax.experimental.shard_map.shard_map — shard_map-backed "
            "parallelism (pipeline, ring/ulysses attention) is "
            "unavailable" % jax.__version__)
    return _IMPL


def shard_map(f, *args, **kwargs):
    """``jax.shard_map`` resolved against the installed jax (falls back
    to ``jax.experimental.shard_map.shard_map``). Same calling
    convention; raises :class:`ShardMapUnavailable` when neither exists.

    The replication-check kwarg renamed across the move
    (``check_rep`` -> ``check_vma``); callers may use either spelling
    and the shim translates to whatever the resolved implementation
    accepts, so parallel/ modules are written once against the new API.
    """
    impl = require_shard_map()
    for ours, theirs in (("check_vma", "check_rep"),
                         ("check_rep", "check_vma")):
        if ours in kwargs and ours not in _IMPL_KWARGS \
                and theirs in _IMPL_KWARGS:
            kwargs[theirs] = kwargs.pop(ours)
    mapped = impl(f, *args, **kwargs)
    if not _IMPL_IS_LEGACY:
        return mapped
    mesh = kwargs.get("mesh", args[0] if args else None)
    if mesh is None or not hasattr(mesh, "devices"):
        return mapped
    return _pin_operands_replicated(mapped, mesh)


def _pin_operands_replicated(mapped, mesh):
    """Correctness workaround for the legacy (pre-``jax.shard_map``)
    implementation: under an outer jit, an operand COMPUTED inside the
    trace (e.g. ``jnp.stack`` of per-stage params) whose in_spec leaves a
    mesh axis unmentioned is mis-partitioned on multi-axis meshes — every
    value arrives multiplied by the unmentioned axis size (verified on
    jax 0.4.37: stack -> shard_map(P('pp')) on a dp x pp mesh doubles).
    Pinning traced operands to an explicitly REPLICATED NamedSharding
    right before the shard_map restores correct slicing; values are
    unchanged, the cost is an all-gather on operands that were laid out
    sharded — acceptable on the compat path (current jax takes the
    ``jax.shard_map`` branch, which passes through untouched)."""
    from jax.sharding import NamedSharding, PartitionSpec
    repl = NamedSharding(mesh, PartitionSpec())

    def _pin(x):
        if isinstance(x, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(x, repl)
        return x

    def wrapped(*operands):
        return mapped(*jax.tree_util.tree_map(_pin, operands))

    return wrapped


def axis_size(axis_name):
    """``lax.axis_size`` where the installed jax has it; otherwise the
    classic static idiom ``psum(1, axis)`` (a unit constant reduces to
    the axis size without touching data)."""
    from jax import lax
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def pcast(x, axes, to):
    """``lax.pcast`` (varying-manual-axes annotation, new jax) or the
    identity on jaxes that predate the vma system. The pre-vma
    replication checker never consults vma annotations (it has its own
    inference over collectives), so dropping the cast loses nothing
    there — it only exists to satisfy the NEW checker."""
    from jax import lax
    fn = getattr(lax, "pcast", None)
    if fn is not None:
        return fn(x, axes, to=to)
    return x
