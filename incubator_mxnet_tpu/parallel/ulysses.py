"""Ulysses-style sequence parallelism — all-to-all head scatter.

NEW capability (SURVEY §5: the reference has no sequence parallelism; this
is the all-to-all alternative to ring attention, after DeepSpeed-Ulysses).

Where ring attention keeps the sequence sharded and streams K/V around the
ICI ring, Ulysses re-shards with two all-to-alls: tokens arrive sharded on
the sequence axis, an all-to-all converts to HEAD-sharded (each device
holds ALL tokens for H/n heads), attention runs fully local (any kernel —
here the dense/flash local path), and a second all-to-all restores
sequence sharding. Cost: 2 all-to-alls of activation size per layer vs the
ring's (n-1) K/V hops; Ulysses wins when heads >> devices and the
per-device sequence is long.

Requires num_heads % axis_size == 0 and S % axis_size == 0.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .compat import axis_size, shard_map
from .ring_attention import local_attention

__all__ = ["ulysses_attention"]


def _ulysses_sharded(q, k, v, axis_name, causal, scale):
    """Inside shard_map: q/k/v local shapes (B, H, S/n, D)."""
    n = axis_size(axis_name)

    def seq_to_heads(x):
        # (B, H, s, D) -> (B, H/n, S, D): split heads across devices,
        # gather the full sequence. all_to_all splits axis 1, concats axis 2.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # full-sequence local attention on H/n heads: the Pallas flash kernel
    # when the (B, H/n, S, D) shape supports it — O(block^2) VMEM instead
    # of the dense path's O(S^2) HBM score block
    from ..ops.attention import flash_attention, flash_attention_supported
    if flash_attention_supported(qh.shape):
        out = flash_attention(qh, kh, vh, causal, scale)
    else:
        o, m, l = local_attention(qh, kh, vh, scale=scale, causal=causal)
        out = (o / jnp.maximum(l, 1e-37)).astype(q.dtype)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh=None, axis="sp", causal=False, scale=None):
    """Sequence-parallel attention via head-scatter all-to-all.

    q/k/v global shapes (B, H, S, D), sequence-sharded on mesh axis
    ``axis``; returns the same layout. H and S must divide the axis size.
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError("num_heads %d not divisible by %s=%d"
                         % (q.shape[1], axis, n))
    fn = functools.partial(_ulysses_sharded, axis_name=axis, causal=causal,
                           scale=scale)
    spec = P(None, None, axis, None)
    # check_vma=False: the local flash pallas_call carries no vma annotation
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
