"""Tensor parallelism — NEW capability (absent in reference, SURVEY §2.5).

Megatron-style column/row sharded linear layers expressed as GSPMD sharding
annotations: the weight carries a PartitionSpec over the ``tp`` mesh axis and
XLA partitions the matmul and inserts the all-reduce/all-gather on ICI.
No explicit collective calls are needed in the layer code.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..gluon import nn

__all__ = ["ColParallelDense", "RowParallelDense", "shard_params"]


class ColParallelDense(nn.Dense):
    """Dense with output features sharded over ``tp`` (weight rows split).

    y = x W^T : W is (units, in) → shard dim 0. Output is sharded on features;
    follow with RowParallelDense to contract back (Megatron MLP pattern).
    """

    def __init__(self, units, tp_axis="tp", **kwargs):
        super().__init__(units, **kwargs)
        self.weight.sharding = P(tp_axis, None)
        if self.bias is not None:
            self.bias.sharding = P(tp_axis)


class RowParallelDense(nn.Dense):
    """Dense with input features sharded over ``tp`` (weight cols split).

    The partial products are psum'd by XLA automatically (GSPMD)."""

    def __init__(self, units, tp_axis="tp", **kwargs):
        super().__init__(units, **kwargs)
        self.weight.sharding = P(None, tp_axis)
        # bias replicated


def shard_params(block, rules, mesh=None):
    """Annotate parameters by name-pattern → PartitionSpec.

    rules: list of (regex, PartitionSpec). First match wins. This is the
    declarative analog of the reference's manual group2ctx model-parallel
    placement (symbol.py:1554) — placement by annotation, not device copies.
    """
    import re
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    for name, p in block.collect_params().items():
        for pat, spec in compiled:
            if pat.search(name):
                p.sharding = spec
                break
    return block
