"""Multi-host initialisation over DCN (ref ps-lite scheduler/worker roles).

TPU-native: jax.distributed — every host runs the same SPMD program; the
coordinator address replaces the parameter-server scheduler. Reads the env
set by tools/launch.py (MXTPU_COORD_ADDR / MXTPU_NUM_PROC / MXTPU_PROC_ID).
"""
from __future__ import annotations


import jax

__all__ = ["init_distributed", "rank", "num_workers", "is_initialized"]

_STATE = {"initialized": False}


def init_distributed(coordinator_address=None, num_processes=None, process_id=None):
    """Initialise jax.distributed from args or launcher env.

    Idempotent: importing incubator_mxnet_tpu under tools/launch.py already
    initialises the runtime (package __init__), because it must happen before
    anything touches the XLA backend.
    """
    if _STATE["initialized"]:
        return
    from ..base import distributed_is_initialized
    if distributed_is_initialized():  # already up (package import)
        _STATE["initialized"] = True
        return
    from ..config import get_env
    coordinator_address = coordinator_address or get_env("MXTPU_COORD_ADDR")
    num_processes = num_processes or get_env("MXTPU_NUM_PROC")
    process_id = process_id if process_id is not None else get_env("MXTPU_PROC_ID")
    if num_processes > 1 and coordinator_address:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    _STATE["initialized"] = True


def is_initialized():
    return _STATE["initialized"]


def rank():
    try:
        return jax.process_index()
    except Exception:
        return 0


def num_workers():
    try:
        return jax.process_count()
    except Exception:
        return 1
