"""Device mesh construction & sharding helpers.

The reference's device topology handling (src/kvstore/gpu_topology.h link-matrix
tree reduce) becomes: declare a jax.sharding.Mesh over the ICI torus and let
XLA place collectives on it. DCN (multi-host) is just an outer mesh axis.
"""
from __future__ import annotations

import numpy as onp

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "current_mesh", "set_current_mesh", "replicated",
           "shard_spec", "P", "NamedSharding", "Mesh"]

_CURRENT = [None]


def make_mesh(axes=None, devices=None):
    """Create a Mesh from {'axis': size} (sizes must multiply to #devices;
    one axis may be -1 to absorb the remainder)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise ValueError("mesh axes %s do not cover %d devices" % (dict(zip(names, sizes)), n))
    arr = onp.array(devices).reshape(sizes)
    mesh = Mesh(arr, axis_names=tuple(names))
    set_current_mesh(mesh)
    return mesh


def set_current_mesh(mesh):
    _CURRENT[0] = mesh


def current_mesh():
    return _CURRENT[0]


def replicated(mesh):
    return NamedSharding(mesh, P())


def shard_spec(mesh, *axes):
    """NamedSharding partitioning consecutive dims over the given axis names
    (None entries mean 'replicated on that dim')."""
    return NamedSharding(mesh, P(*axes))
