"""Pipeline parallelism — NEW capability (SURVEY §2.5: absent in reference).

GPipe-style microbatching over structurally-identical stages expressed with
shard_map + ppermute over the ``pp`` mesh axis: stage weights are stacked on
a leading stage dim sharded over ``pp``; activations circulate the ring once
per microbatch tick. XLA overlaps the permute with stage compute on ICI.

The whole transform is differentiable (ppermute/scan have transposes), so
loss and gradients flow through the pipeline — see parallel.gluon_pipeline
for the Gluon block that pipelines a trunk between an embedding and a head
with TrainStep/Trainer integration.

``data_axis`` composes pp with data parallelism: the microbatch dim stays
sharded over ``dp`` while activations ring over ``pp``. ``key`` threads PRNG
randomness into stages (folded per-stage and per-tick) for dropout.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .compat import axis_size, pcast, shard_map

__all__ = ["PipelineParallel", "pipeline_spmd", "pipeline_1f1b_grads"]


def _pipeline_sharded(x_mb, stacked_params, key, stage_fn, axis_name,
                      n_microbatches, vary_axes=None):
    """Inside shard_map: each device holds ONE stage's params (leading stage
    dim of size 1 locally) and processes the stream of microbatches.

    x_mb: (n_micro, mb, ...) — full microbatch stream, replicated.
    Returns (n_micro, mb, ...) outputs (valid on the last stage; all-gathered).
    """
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
    mb_shape = x_mb.shape[1:]
    total_ticks = n_microbatches + n_stages - 1
    stage_key = None if key is None else jax.random.fold_in(key, stage)

    def tick(t, carry):
        state, outputs = carry  # state: activation currently held (mb, ...)
        # stage 0 injects microbatch t (if any); others use what arrived
        inject = jnp.where(t < n_microbatches, t, n_microbatches - 1)
        fresh = x_mb[inject]
        cur = jnp.where(stage == 0, fresh, state)
        if stage_key is None:
            out = stage_fn(params, cur)
        else:
            out = stage_fn(params, cur, jax.random.fold_in(stage_key, t))
        # last stage records its result for microbatch (t - n_stages + 1)
        done_idx = t - (n_stages - 1)
        record = jnp.logical_and(stage == n_stages - 1, done_idx >= 0)
        write_idx = jnp.clip(done_idx, 0, n_microbatches - 1)
        outputs = jnp.where(record, outputs.at[write_idx].set(out), outputs)
        # shift activations to the next stage on the ring
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        state = lax.ppermute(out, axis_name, perm)
        return state, outputs

    axes = vary_axes or (axis_name,)
    out0 = pcast(jnp.zeros((n_microbatches,) + mb_shape, x_mb.dtype),
                     axes, to="varying")
    state0 = pcast(jnp.zeros(mb_shape, x_mb.dtype), axes, to="varying")
    _, outputs = lax.fori_loop(0, total_ticks, tick, (state0, out0))
    # only the last stage holds real outputs; broadcast them to all stages
    return _bcast_from_last(outputs, axis_name, n_stages)


def _bcast_from_last(x, axis_name, n_stages):
    # psum with a mask selects the last stage's copy on every device
    stage = lax.axis_index(axis_name)
    mask = (stage == n_stages - 1).astype(x.dtype)
    return lax.psum(x * mask, axis_name)


def pipeline_spmd(stage_fn, stacked_params, x, mesh, n_microbatches, axis="pp",
                  data_axis=None, key=None):
    """Run a structurally-identical-stage pipeline.

    stage_fn(params, x[, key])->y with identical in/out shapes; stacked_params
    has a leading dim = n_stages sharded over ``axis``; x: (batch, ...) split
    into n_microbatches along dim 0. With ``data_axis``, the microbatch dim
    stays sharded over that mesh axis (pp x dp composition). ``key`` (optional
    PRNG key) is folded per-stage/per-tick and passed as stage_fn's 3rd arg.
    """
    from jax.sharding import NamedSharding

    n_stages = int(mesh.shape[axis])
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if leaves and leaves[0].shape[0] != n_stages:
        raise ValueError(
            "stacked_params leading dim (%d stages) must equal the %r mesh "
            "axis size (%d) — a divisible mismatch would silently drop "
            "stages" % (leaves[0].shape[0], axis, n_stages))
    if x.shape[0] % n_microbatches:
        raise ValueError("batch %d not divisible by n_microbatches %d"
                         % (x.shape[0], n_microbatches))
    mb = x.shape[0] // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])
    fn = functools.partial(
        _pipeline_sharded, stage_fn=stage_fn, axis_name=axis,
        n_microbatches=n_microbatches,
        vary_axes=(axis, data_axis) if data_axis else (axis,))
    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    io_spec = P(None, data_axis) if data_axis else P()
    # operands may arrive committed to a single device (eager NDArray data);
    # lay them out on the mesh so shard_map accepts them (no-op under jit
    # steady state — becomes a sharding constraint)
    x_mb = jax.device_put(x_mb, NamedSharding(mesh, io_spec))
    stacked_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        stacked_params, param_specs)
    if key is not None:
        key = jax.device_put(key, NamedSharding(mesh, P()))
    if key is None:
        out = shard_map(
            lambda xm, sp: fn(xm, sp, None), mesh=mesh,
            in_specs=(io_spec, param_specs),
            out_specs=io_spec)(x_mb, stacked_params)
    else:
        out = shard_map(
            fn, mesh=mesh,
            in_specs=(io_spec, param_specs, P()),
            out_specs=io_spec)(x_mb, stacked_params, key)
    return out.reshape((x.shape[0],) + out.shape[2:])


class PipelineParallel:
    """Convenience wrapper: pipeline a stack of identical HybridBlocks.

    Used for transformer-layer stacks: all stages share one structure; their
    parameters are stacked on a leading dim and sharded over ``pp``.
    """

    def __init__(self, stage_fn, n_stages, mesh, axis="pp", n_microbatches=None):
        self.stage_fn = stage_fn
        self.n_stages = n_stages
        self.mesh = mesh
        self.axis = axis
        self.n_microbatches = n_microbatches or n_stages

    def __call__(self, stacked_params, x):
        return pipeline_spmd(self.stage_fn, stacked_params, x, self.mesh,
                             self.n_microbatches, self.axis)


# ----------------------------------------------------------------- 1F1B
def _pipeline_1f1b_sharded(x_mb, y_mb, stacked_params, stage_fn, loss_fn,
                           axis_name):
    """Hand-scheduled 1F1B (PipeDream-flush) inside shard_map.

    Non-interleaved 1F1B timing on the ring: stage s runs F_i at global
    tick t = s + 2i and B_i at t = 2(p+i) - s - 1 — per stage the two
    predicates have opposite tick parity, so each tick is one F, one B, or
    idle. Activations shift +1 on the ring every tick, gradients shift -1;
    a value produced at tick t is consumed by its neighbour at exactly
    t+1 in both directions (ticks on other parities carry garbage that no
    predicate ever reads). Total ticks 2(m+p-1): the SAME bubble fraction
    as GPipe-by-autodiff — 1F1B's win is the activation stash, which is
    bounded by p slots per stage instead of GPipe's m (in-flight
    microbatches at stage s: ceil((2(p-s)-1)/2) <= p).

    The backward recomputes each stage under jax.vjp from the stashed
    INPUT at its B tick (activation recompute, the standard memory/compute
    trade); the last stage folds loss_fn into its vjp so the loss gradient
    needs no self-handoff on the ring.

    Returns (mean loss over microbatches, param grads summed over
    microbatches (each stage holds its own slice), dx per microbatch for
    composing with an upstream embedding).
    """
    p = axis_size(axis_name)
    s = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda q: q[0], stacked_params)
    m = x_mb.shape[0]
    mb_shape = x_mb.shape[1:]
    K = p  # stash slots: the 1F1B in-flight bound
    total_ticks = 2 * (m + p - 1)
    fwd_perm = [(j, (j + 1) % p) for j in range(p)]
    bwd_perm = [(j, (j - 1) % p) for j in range(p)]

    def tick(t, carry):
        a_reg, g_reg, stash, pgrads, dx_buf, loss_acc = carry
        iF = (t - s) // 2
        is_F = ((t - s) % 2 == 0) & (t >= s) & (iF < m)
        iF = jnp.clip(iF, 0, m - 1)
        iB = (t + s + 1 - 2 * p) // 2
        is_B = ((t + s + 1 - 2 * p) % 2 == 0) & (iB >= 0) & (iB < m)
        iB = jnp.clip(iB, 0, m - 1)

        finp = jnp.where(s == 0, x_mb[iF], a_reg)

        def do_F(stash):
            out = stage_fn(params, finp)
            return out, stash.at[iF % K].set(finp)

        def no_F(stash):
            return jnp.zeros(mb_shape, x_mb.dtype), stash

        a_out, stash = lax.cond(is_F, do_F, no_F, stash)

        def do_B(pgrads, dx_buf, loss_acc):
            binp = stash[iB % K]

            def last_branch(binp):
                # fold the loss into the stage vjp: the loss gradient needs
                # no self-handoff on the ring
                lv, vjp = jax.vjp(
                    lambda q, x: loss_fn(stage_fn(q, x), y_mb[iB]),
                    params, binp)
                dpar, dx = vjp(jnp.ones_like(lv))
                return lv.astype(jnp.float32), dpar, dx

            def mid_branch(binp):
                # vjp at cotangent g_reg, phrased as a scalar vdot so both
                # branches share the (loss, dpar, dx) structure
                lv, vjp = jax.vjp(
                    lambda q, x: jnp.vdot(
                        stage_fn(q, x).astype(jnp.float32),
                        lax.stop_gradient(g_reg).astype(jnp.float32)),
                    params, binp)
                dpar, dx = vjp(jnp.float32(1.0))
                return jnp.float32(0.0), dpar, dx

            lv, dpar, dx = lax.cond(s == p - 1, last_branch, mid_branch,
                                    binp)
            pgrads = jax.tree_util.tree_map(lambda g, d: g + d, pgrads,
                                            dpar)
            dx_buf = jnp.where(s == 0, dx_buf.at[iB].set(dx), dx_buf)
            return dx, pgrads, dx_buf, loss_acc + lv

        def no_B(pgrads, dx_buf, loss_acc):
            return (jnp.zeros(mb_shape, x_mb.dtype), pgrads, dx_buf,
                    loss_acc)

        g_out, pgrads, dx_buf, loss_acc = lax.cond(
            is_B, do_B, no_B, pgrads, dx_buf, loss_acc)

        a_reg = lax.ppermute(a_out, axis_name, fwd_perm)
        g_reg = lax.ppermute(g_out.astype(x_mb.dtype), axis_name, bwd_perm)
        return a_reg, g_reg, stash, pgrads, dx_buf, loss_acc

    zeros_mb = jnp.zeros(mb_shape, x_mb.dtype)
    carry0 = (
        pcast(zeros_mb, (axis_name,), to="varying"),
        pcast(zeros_mb, (axis_name,), to="varying"),
        pcast(jnp.zeros((K,) + mb_shape, x_mb.dtype), (axis_name,),
                  to="varying"),
        jax.tree_util.tree_map(
            lambda q: pcast(jnp.zeros_like(q, jnp.float32),
                                (axis_name,), to="varying"), params),
        pcast(jnp.zeros((m,) + mb_shape, x_mb.dtype), (axis_name,),
                  to="varying"),
        pcast(jnp.float32(0.0), (axis_name,), to="varying"),
    )
    _, _, _, pgrads, dx_buf, loss_acc = lax.fori_loop(
        0, total_ticks, tick, carry0)
    # loss lives on the last stage; dx on stage 0 — broadcast both
    loss = lax.psum(jnp.where(s == p - 1, loss_acc, 0.0), axis_name) / m
    dx_buf = lax.psum(jnp.where(s == 0, dx_buf, jnp.zeros_like(dx_buf)),
                      axis_name)
    # re-stack param grads: each stage contributes its own slice
    pgrads = jax.tree_util.tree_map(lambda g: g[None], pgrads)
    return loss, pgrads, dx_buf


def pipeline_1f1b_grads(stage_fn, loss_fn, stacked_params, x, y, mesh,
                        n_microbatches, axis="pp"):
    """1F1B pipeline train-step core: returns (loss, stage param grads,
    input grads). Same bubble as the GPipe/autodiff path (2(m+p-1) ticks);
    activation stash bounded by n_stages slots per stage instead of
    n_microbatches — the 1F1B memory win (see _pipeline_1f1b_sharded).

    stage_fn(params, x)->y shape-preserving; loss_fn(out, y_mb)->scalar
    (applied on the last stage); stacked_params leading dim = pp axis size;
    x/y: (batch, ...) split into n_microbatches on dim 0.
    """
    from jax.sharding import NamedSharding

    p = int(mesh.shape[axis])
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if leaves and leaves[0].shape[0] != p:
        raise ValueError("stacked_params leading dim must equal the %r "
                         "axis size %d" % (axis, p))
    if x.shape[0] % n_microbatches:
        raise ValueError("batch %d not divisible by n_microbatches %d"
                         % (x.shape[0], n_microbatches))
    mb = x.shape[0] // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])
    y_mb = y.reshape((n_microbatches, mb) + y.shape[1:])
    param_specs = jax.tree_util.tree_map(
        lambda q: P(axis, *([None] * (q.ndim - 1))), stacked_params)
    x_mb = jax.device_put(x_mb, NamedSharding(mesh, P()))
    y_mb = jax.device_put(y_mb, NamedSharding(mesh, P()))
    stacked_params = jax.tree_util.tree_map(
        lambda q, sp: jax.device_put(q, NamedSharding(mesh, sp)),
        stacked_params, param_specs)
    fn = functools.partial(_pipeline_1f1b_sharded, stage_fn=stage_fn,
                           loss_fn=loss_fn, axis_name=axis)
    loss, pgrads, dx = shard_map(
        fn, mesh=mesh, in_specs=(P(), P(), param_specs),
        out_specs=(P(), param_specs, P()), check_vma=False)(
            x_mb, y_mb, stacked_params)
    return loss, pgrads, dx.reshape((x.shape[0],) + dx.shape[2:])
