"""Pipeline parallelism — NEW capability (SURVEY §2.5: absent in reference).

GPipe-style microbatching over structurally-identical stages expressed with
shard_map + ppermute over the ``pp`` mesh axis: stage weights are stacked on
a leading stage dim sharded over ``pp``; activations circulate the ring once
per microbatch tick. XLA overlaps the permute with stage compute on ICI.

The whole transform is differentiable (ppermute/scan have transposes), so
loss and gradients flow through the pipeline — see parallel.gluon_pipeline
for the Gluon block that pipelines a trunk between an embedding and a head
with TrainStep/Trainer integration.

``data_axis`` composes pp with data parallelism: the microbatch dim stays
sharded over ``dp`` while activations ring over ``pp``. ``key`` threads PRNG
randomness into stages (folded per-stage and per-tick) for dropout.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["PipelineParallel", "pipeline_spmd"]


def _pipeline_sharded(x_mb, stacked_params, key, stage_fn, axis_name,
                      n_microbatches, vary_axes=None):
    """Inside shard_map: each device holds ONE stage's params (leading stage
    dim of size 1 locally) and processes the stream of microbatches.

    x_mb: (n_micro, mb, ...) — full microbatch stream, replicated.
    Returns (n_micro, mb, ...) outputs (valid on the last stage; all-gathered).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
    mb_shape = x_mb.shape[1:]
    total_ticks = n_microbatches + n_stages - 1
    stage_key = None if key is None else jax.random.fold_in(key, stage)

    def tick(t, carry):
        state, outputs = carry  # state: activation currently held (mb, ...)
        # stage 0 injects microbatch t (if any); others use what arrived
        inject = jnp.where(t < n_microbatches, t, n_microbatches - 1)
        fresh = x_mb[inject]
        cur = jnp.where(stage == 0, fresh, state)
        if stage_key is None:
            out = stage_fn(params, cur)
        else:
            out = stage_fn(params, cur, jax.random.fold_in(stage_key, t))
        # last stage records its result for microbatch (t - n_stages + 1)
        done_idx = t - (n_stages - 1)
        record = jnp.logical_and(stage == n_stages - 1, done_idx >= 0)
        write_idx = jnp.clip(done_idx, 0, n_microbatches - 1)
        outputs = jnp.where(record, outputs.at[write_idx].set(out), outputs)
        # shift activations to the next stage on the ring
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        state = lax.ppermute(out, axis_name, perm)
        return state, outputs

    axes = vary_axes or (axis_name,)
    out0 = lax.pcast(jnp.zeros((n_microbatches,) + mb_shape, x_mb.dtype),
                     axes, to="varying")
    state0 = lax.pcast(jnp.zeros(mb_shape, x_mb.dtype), axes, to="varying")
    _, outputs = lax.fori_loop(0, total_ticks, tick, (state0, out0))
    # only the last stage holds real outputs; broadcast them to all stages
    return _bcast_from_last(outputs, axis_name, n_stages)


def _bcast_from_last(x, axis_name, n_stages):
    # psum with a mask selects the last stage's copy on every device
    stage = lax.axis_index(axis_name)
    mask = (stage == n_stages - 1).astype(x.dtype)
    return lax.psum(x * mask, axis_name)


def pipeline_spmd(stage_fn, stacked_params, x, mesh, n_microbatches, axis="pp",
                  data_axis=None, key=None):
    """Run a structurally-identical-stage pipeline.

    stage_fn(params, x[, key])->y with identical in/out shapes; stacked_params
    has a leading dim = n_stages sharded over ``axis``; x: (batch, ...) split
    into n_microbatches along dim 0. With ``data_axis``, the microbatch dim
    stays sharded over that mesh axis (pp x dp composition). ``key`` (optional
    PRNG key) is folded per-stage/per-tick and passed as stage_fn's 3rd arg.
    """
    from jax.sharding import NamedSharding

    n_stages = int(mesh.shape[axis])
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if leaves and leaves[0].shape[0] != n_stages:
        raise ValueError(
            "stacked_params leading dim (%d stages) must equal the %r mesh "
            "axis size (%d) — a divisible mismatch would silently drop "
            "stages" % (leaves[0].shape[0], axis, n_stages))
    if x.shape[0] % n_microbatches:
        raise ValueError("batch %d not divisible by n_microbatches %d"
                         % (x.shape[0], n_microbatches))
    mb = x.shape[0] // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])
    fn = functools.partial(
        _pipeline_sharded, stage_fn=stage_fn, axis_name=axis,
        n_microbatches=n_microbatches,
        vary_axes=(axis, data_axis) if data_axis else (axis,))
    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    io_spec = P(None, data_axis) if data_axis else P()
    # operands may arrive committed to a single device (eager NDArray data);
    # lay them out on the mesh so shard_map accepts them (no-op under jit
    # steady state — becomes a sharding constraint)
    x_mb = jax.device_put(x_mb, NamedSharding(mesh, io_spec))
    stacked_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        stacked_params, param_specs)
    if key is not None:
        key = jax.device_put(key, NamedSharding(mesh, P()))
    if key is None:
        out = jax.shard_map(
            lambda xm, sp: fn(xm, sp, None), mesh=mesh,
            in_specs=(io_spec, param_specs),
            out_specs=io_spec)(x_mb, stacked_params)
    else:
        out = jax.shard_map(
            fn, mesh=mesh,
            in_specs=(io_spec, param_specs, P()),
            out_specs=io_spec)(x_mb, stacked_params, key)
    return out.reshape((x.shape[0],) + out.shape[2:])


class PipelineParallel:
    """Convenience wrapper: pipeline a stack of identical HybridBlocks.

    Used for transformer-layer stacks: all stages share one structure; their
    parameters are stacked on a leading dim and sharded over ``pp``.
    """

    def __init__(self, stage_fn, n_stages, mesh, axis="pp", n_microbatches=None):
        self.stage_fn = stage_fn
        self.n_stages = n_stages
        self.mesh = mesh
        self.axis = axis
        self.n_microbatches = n_microbatches or n_stages

    def __call__(self, stacked_params, x):
        return pipeline_spmd(self.stage_fn, stacked_params, x, self.mesh,
                             self.n_microbatches, self.axis)
