"""Ring attention — sequence/context parallelism over the ICI ring.

NEW capability (SURVEY §5: absent in the reference; required for long-context
parity with modern workloads). The sequence axis is sharded over the ``sp``
mesh axis; each device holds a Q block and streams K/V blocks around the ring
with ``ppermute`` while maintaining an online-softmax (flash-style) running
max/denominator in fp32. Compute and ICI transfer overlap because XLA
schedules the collective-permute asynchronously with the local matmuls.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import axis_size, shard_map

__all__ = ["ring_attention", "local_attention"]


def local_attention(q, k, v, scale=None, causal=False, q_offset=0, kv_offset=0):
    """Plain blockwise attention on local shards (fp32 softmax accumulators).

    q: (B, H, Sq, D), k/v: (B, H, Sk, D).
    Returns (out, row_max, row_sumexp) for online-softmax combination.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None] + q_offset
        ki = jnp.arange(k.shape[2])[None, :] + kv_offset
        s = jnp.where(qi >= ki, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)                       # (B,H,Sq,1)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)                       # (B,H,Sq,1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return o, m, l


def _combine(o1, m1, l1, o2, m2, l2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(jnp.where(jnp.isfinite(m1), m1 - m, -jnp.inf))
    a2 = jnp.exp(jnp.where(jnp.isfinite(m2), m2 - m, -jnp.inf))
    a1 = jnp.where(jnp.isnan(a1), 0.0, a1)
    a2 = jnp.where(jnp.isnan(a2), 0.0, a2)
    o = o1 * a1 + o2 * a2
    l = l1 * a1 + l2 * a2
    return o, m, l


def _local_partials(q, k, v, scale, causal):
    """One local attention step as an online-softmax partial triple
    (o, m, l). Rides the Pallas flash kernel when the local shard shape
    supports it — (out, lse) from the kernel is the equivalent partial
    (out, lse, 1): out*1*e^lse == numerator, 1*e^lse == denominator —
    so per-shard memory is O(block^2), not O((S/n)^2). Dense fallback
    otherwise (small shards / non-TPU)."""
    from ..ops.attention import attention_with_lse, flash_attention_supported
    if flash_attention_supported(q.shape):
        out, lse = attention_with_lse(q, k, v, causal=causal, scale=scale)
        return (out.astype(jnp.float32), lse[..., None],
                jnp.ones(lse.shape + (1,), jnp.float32))
    return local_attention(q, k, v, scale=scale, causal=causal)


def _ring_attention_sharded(q, k, v, axis_name, causal, scale):
    """Runs inside shard_map: local blocks + ring exchange of K/V.

    Causal masking is decomposed at BLOCK granularity (no in-kernel offset
    support needed): the shard's own K/V block uses the plain causal mask,
    earlier shards (src < idx) are fully visible (dense step), later shards
    contribute nothing (skipped partial) — the standard ring-attention
    causal decomposition."""
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)

    o0, m0, l0 = _local_partials(q, k, v, scale, causal)

    def body(i, carry):
        o, m, l, kk, vv = carry
        # pass K/V to the next device on the ring (ICI neighbour)
        perm = [(j, (j + 1) % n) for j in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        src = (idx - i - 1) % n  # which shard we now hold
        if causal:
            oi, mi, li = lax.cond(
                src < idx,
                lambda kk, vv: _local_partials(q, kk, vv, scale, False),
                lambda kk, vv: (jnp.zeros_like(o),
                                jnp.full_like(m, -jnp.inf),
                                jnp.zeros_like(l)),
                kk, vv)
        else:
            oi, mi, li = _local_partials(q, kk, vv, scale, False)
        o, m, l = _combine(o, m, l, oi, mi, li)
        return o, m, l, kk, vv

    o, m, l, _, _ = lax.fori_loop(0, n - 1, body, (o0, m0, l0, k, v))
    return (o / jnp.maximum(l, 1e-37)).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis="sp", causal=False, scale=None):
    """Sequence-parallel attention: q/k/v sharded on the sequence dim (axis 2)
    over mesh axis ``axis``. Shapes (B, H, S, D) global.

    Use inside a jit under the mesh; arrives/leaves with seq-sharded layout.
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    fn = functools.partial(_ring_attention_sharded, axis_name=axis,
                           causal=causal, scale=scale)
    spec = P(None, None, axis, None)
    # check_vma=False: pallas_call out_shapes carry no vma annotation, and
    # the local flash kernel runs inside this shard_map
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
