"""Interleaved (virtual-stage) 1F1B pipeline schedule — NEW capability
(SURVEY §2.5; the reference has no pipeline parallelism at all).

Megatron-LM-style interleaving (arXiv:2104.04473 §2.2): each of the p
devices hosts ``v`` model CHUNKS (virtual stages), so the model is cut into
V = v*p stages of w/v work each.  The pipeline fill still takes ~p*w of
wall-clock, but during it every device works on OTHER microbatches' chunks,
so the idle (bubble) time per device shrinks ~v-fold:
bubble ≈ (p-1)/(v*m) of the step vs (p-1)/m non-interleaved.

Implementation: the schedule is computed AT TRACE TIME by a greedy list
scheduler over the op DAG (one op per device per tick, +1-ring activation /
-1-ring gradient hops with 1-tick latency, 1F1B drain priority: backwards
run as soon as ready).  The resulting static tick tables (op / chunk /
micro / arrival per device) ride the compiled program as small int32
arrays; the SPMD body just indexes them with (tick, axis_index) and runs
the predicated F/B — so the schedule is data, not control flow, and XLA
compiles ONE tick body (lax.fori_loop) regardless of m, p, v.

``schedule_stats`` exposes the exact bubble fraction of any schedule
(idle device-ticks / total device-ticks) — the committed numbers in
docs/PERF_PIPELINE.md come from it, weighted by measured F/B tick costs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax
from jax.sharding import PartitionSpec as P

from .compat import axis_size, pcast, shard_map

__all__ = ["interleaved_schedule", "schedule_stats",
           "pipeline_interleaved_grads", "schedule_1f1b", "schedule_gpipe"]


# ------------------------------------------------------------- scheduler
def interleaved_schedule(m, p, v):
    """Greedy 1F1B list schedule for m microbatches, p devices, v chunks.

    Returns a list of ticks; each tick is a list of p entries
    ``None | ('F'|'B', chunk, micro)``.  Dependency model (1-tick ring
    latency, matching the executor's ppermute placement):

    * F(S, i) needs F(S-1, i) to have finished by tick t-1 (activation
      arrives at t); F(0, i) is always ready.
    * B(S, i) needs B(S+1, i) finished by t-1 (cotangent arrives at t);
      B(V-1, i) needs F(V-1, i) finished by t-1 (its stash slot written).
    * One op per device per tick; B preferred over F (1F1B drain rule),
      lower micro first, then lower chunk (FIFO).
    """
    V = v * p
    done_F = {}   # (S, i) -> finish tick
    done_B = {}
    ticks = []
    total = 2 * V * m
    ndone = 0
    t = 0
    while ndone < total:
        row = [None] * p
        for d in range(p):
            best = None
            # backwards first (1F1B), FIFO by micro then chunk
            for c in range(v - 1, -1, -1):
                S = c * p + d
                for i in range(m):
                    if (S, i) in done_B:
                        continue
                    if S == V - 1:
                        ready = done_F.get((S, i), t) < t
                    else:
                        ready = done_B.get((S + 1, i), t) < t
                    if ready:
                        cand = ("B", c, i)
                        if best is None or (cand[2], cand[1]) < \
                                (best[2], best[1]):
                            best = cand
                        break   # FIFO in i for this chunk
            if best is None:
                for c in range(v):
                    S = c * p + d
                    for i in range(m):
                        if (S, i) in done_F:
                            continue
                        ready = S == 0 or done_F.get((S - 1, i), t) < t
                        if ready:
                            cand = ("F", c, i)
                            if best is None or (cand[2], cand[1]) < \
                                    (best[2], best[1]):
                                best = cand
                            break
            if best is not None:
                typ, c, i = best
                S = c * p + d
                if typ == "F":
                    done_F[(S, i)] = t
                else:
                    done_B[(S, i)] = t
                ndone += 1
                row[d] = best
        ticks.append(row)
        t += 1
        assert t < 8 * total + 64, "scheduler livelock"
    return ticks


def schedule_1f1b(m, p):
    """Non-interleaved 1F1B = interleaved with v=1 (same dependency model)."""
    return interleaved_schedule(m, p, 1)


def schedule_gpipe(m, p):
    """GPipe: all forwards, then all backwards (synchronous flush) —
    expressed in the same tick table format for comparable stats."""
    ticks = []
    # forward wave
    for t in range(m + p - 1):
        row = [None] * p
        for d in range(p):
            i = t - d
            if 0 <= i < m:
                row[d] = ("F", 0, i)
        ticks.append(row)
    # backward wave (reverse ring)
    for t in range(m + p - 1):
        row = [None] * p
        for d in range(p):
            i = t - (p - 1 - d)
            if 0 <= i < m:
                row[d] = ("B", 0, i)
        ticks.append(row)
    return ticks


def schedule_stats(ticks, p, f_cost=1.0, b_cost=2.0):
    """Bubble fraction of a schedule, cost-weighted (backward ≈ 2x forward).

    Tick duration = the max op cost issued that tick (devices are
    lock-stepped by the ring); idle time = Σ_device (step − busy)."""
    step = 0.0
    busy = [0.0] * p
    for row in ticks:
        dur = max([f_cost if op[0] == "F" else b_cost
                   for op in row if op] or [0.0])
        step += dur
        for d in range(p):
            if row[d]:
                busy[d] += f_cost if row[d][0] == "F" else b_cost
    total = step * p
    return {
        "ticks": len(ticks),
        "step_cost": step,
        "bubble_fraction": (total - sum(busy)) / total,
        "per_device_busy": busy,
    }


# ------------------------------------------------------------- executor
def _stash_bound(ticks, p, v, m):
    """Exact stash-slot bound from the schedule: the max number of
    microbatches simultaneously in flight through any (device, chunk)'s
    forward-input / arrived-activation / arrived-cotangent windows.  The
    greedy scheduler issues FIFO per stage, so in-flight micros form a
    contiguous index range and ``i % K`` slots never collide for
    K >= the window size.  This is what makes interleaved memory bounded
    by the SCHEDULE depth (~p + v) instead of n_microbatches."""
    V = v * p
    fin_F, fin_B = {}, {}
    for t, row in enumerate(ticks):
        for d, op in enumerate(row):
            if op:
                typ, c, i = op
                (fin_F if typ == "F" else fin_B)[(c * p + d, i)] = t
    bound = 1
    T = len(ticks)
    for S in range(V):
        windows = [(lambda i: fin_F[(S, i)], lambda i: fin_B[(S, i)]),
                   (lambda i: (fin_B[(S + 1, i)] + 1) if S < V - 1
                    else fin_F[(S, i)], lambda i: fin_B[(S, i)])]
        if S > 0:
            # arrived-activation window; stage 0 has NO ring arrival (its
            # input is read straight from the replicated x_mb at F time),
            # so no window — counting one would make the bound linear in m
            windows.append((lambda i: fin_F[(S - 1, i)] + 1,
                            lambda i: fin_F[(S, i)]))
        for lo_fn, hi_fn in windows:
            events = [(lo_fn(i), hi_fn(i)) for i in range(m)]
            for t in range(T):
                live = sum(1 for lo, hi in events if lo <= t <= hi)
                bound = max(bound, live)
    return bound


def _tables(ticks, p, v, m):
    """Static numpy tick tables for the SPMD body (+ arrival decode)."""
    T = len(ticks)
    V = v * p
    op = onp.zeros((T, p), onp.int32)       # 0 none, 1 F, 2 B
    chk = onp.zeros((T, p), onp.int32)
    mic = onp.zeros((T, p), onp.int32)
    for t, row in enumerate(ticks):
        for d in range(p):
            if row[d]:
                typ, c, i = row[d]
                op[t, d] = 1 if typ == "F" else 2
                chk[t, d] = c
                mic[t, d] = i
    # arrivals at tick t on device d = neighbour's op at t-1
    arrF = onp.zeros((T, p), onp.int32)     # 1 if an activation arrives
    arrF_c = onp.zeros((T, p), onp.int32)   # destination chunk
    arrF_i = onp.zeros((T, p), onp.int32)
    arrB = onp.zeros((T, p), onp.int32)
    arrB_c = onp.zeros((T, p), onp.int32)
    arrB_i = onp.zeros((T, p), onp.int32)
    for t in range(1, T):
        for d in range(p):
            src = (d - 1) % p
            if op[t - 1, src] == 1:
                S = chk[t - 1, src] * p + src
                if S < V - 1:               # last stage's output: no consumer
                    arrF[t, d] = 1
                    arrF_c[t, d] = (S + 1) // p
                    arrF_i[t, d] = mic[t - 1, src]
            src = (d + 1) % p
            if op[t - 1, src] == 2:
                S = chk[t - 1, src] * p + src
                if S > 0:
                    arrB[t, d] = 1
                    arrB_c[t, d] = (S - 1) // p
                    arrB_i[t, d] = mic[t - 1, src]
    return [onp.asarray(a) for a in
            (op, chk, mic, arrF, arrF_c, arrF_i, arrB, arrB_c, arrB_i)]


def _interleaved_sharded(x_mb, y_mb, stacked_params, tables, stage_fn,
                         loss_fn, axis_name, v, m, kslots):
    """SPMD body: execute the static tick tables on the pp ring."""
    p = axis_size(axis_name)
    d = lax.axis_index(axis_name)
    V = v * p
    # local params: (v, 1, ...) -> per-chunk pytree list indexed by c
    params = jax.tree_util.tree_map(lambda q: q[:, 0], stacked_params)
    mb_shape = x_mb.shape[1:]
    (opT, chkT, micT, arrF, arrFc, arrFi, arrB, arrBc, arrBi) = [
        jnp.asarray(a) for a in tables]
    T = opT.shape[0]

    def tick(t, carry):
        (a_in, g_in, a_stash, f_stash, g_stash, pgrads, dx_buf,
         loss_acc) = carry
        # ---- bank arrivals (activation from d-1, cotangent from d+1)
        a_stash = lax.cond(
            arrF[t, d] == 1,
            lambda st: st.at[arrFc[t, d], arrFi[t, d] % kslots].set(a_in),
            lambda st: st, a_stash)
        g_stash = lax.cond(
            arrB[t, d] == 1,
            lambda st: st.at[arrBc[t, d], arrBi[t, d] % kslots].set(g_in),
            lambda st: st, g_stash)

        c, i = chkT[t, d], micT[t, d]
        S = c * p + d
        prm = jax.tree_util.tree_map(lambda q: q[c], params)

        def do_F(f_stash):
            inp = jnp.where(S == 0, x_mb[i], a_stash[c, i % kslots])
            out = stage_fn(prm, inp)
            return out, f_stash.at[c, i % kslots].set(inp)

        def no_F(f_stash):
            return jnp.zeros(mb_shape, x_mb.dtype), f_stash

        a_out, f_stash = lax.cond(opT[t, d] == 1, do_F, no_F, f_stash)

        def do_B(pgrads, dx_buf, loss_acc):
            binp = f_stash[c, i % kslots]

            def last_branch(binp):
                lv, vjp = jax.vjp(
                    lambda q, x: loss_fn(stage_fn(q, x), y_mb[i]),
                    prm, binp)
                dpar, dx = vjp(jnp.ones_like(lv))
                return lv.astype(jnp.float32), dpar, dx

            def mid_branch(binp):
                lv, vjp = jax.vjp(
                    lambda q, x: jnp.vdot(
                        stage_fn(q, x).astype(jnp.float32),
                        lax.stop_gradient(g_stash[c, i % kslots]).astype(
                            jnp.float32)),
                    prm, binp)
                dpar, dx = vjp(jnp.float32(1.0))
                return jnp.float32(0.0), dpar, dx

            lv, dpar, dx = lax.cond(S == V - 1, last_branch, mid_branch,
                                    binp)
            pgrads = jax.tree_util.tree_map(
                lambda g, dp: g.at[c].add(dp), pgrads, dpar)
            dx_buf = jnp.where(S == 0, dx_buf.at[i].set(dx), dx_buf)
            return dx, pgrads, dx_buf, loss_acc + lv

        def no_B(pgrads, dx_buf, loss_acc):
            return (jnp.zeros(mb_shape, x_mb.dtype), pgrads, dx_buf,
                    loss_acc)

        g_out, pgrads, dx_buf, loss_acc = lax.cond(
            opT[t, d] == 2, do_B, no_B, pgrads, dx_buf, loss_acc)

        a_in = lax.ppermute(a_out, axis_name,
                            [(j, (j + 1) % p) for j in range(p)])
        g_in = lax.ppermute(g_out.astype(x_mb.dtype), axis_name,
                            [(j, (j - 1) % p) for j in range(p)])
        return (a_in, g_in, a_stash, f_stash, g_stash, pgrads, dx_buf,
                loss_acc)

    zeros_mb = jnp.zeros(mb_shape, x_mb.dtype)

    def vary(x):
        return pcast(x, (axis_name,), to="varying")

    carry0 = (
        vary(zeros_mb), vary(zeros_mb),
        vary(jnp.zeros((v, kslots) + mb_shape, x_mb.dtype)),
        vary(jnp.zeros((v, kslots) + mb_shape, x_mb.dtype)),
        vary(jnp.zeros((v, kslots) + mb_shape, x_mb.dtype)),
        jax.tree_util.tree_map(
            lambda q: vary(jnp.zeros_like(q, jnp.float32)), params),
        vary(jnp.zeros((m,) + mb_shape, x_mb.dtype)),
        vary(jnp.float32(0.0)),
    )
    out = lax.fori_loop(0, T, tick, carry0)
    pgrads, dx_buf, loss_acc = out[5], out[6], out[7]
    loss = lax.psum(jnp.where(d == p - 1, loss_acc, 0.0), axis_name) / m
    dx_buf = lax.psum(jnp.where(d == 0, dx_buf, jnp.zeros_like(dx_buf)),
                      axis_name)
    pgrads = jax.tree_util.tree_map(lambda g: g[:, None], pgrads)
    return loss, pgrads, dx_buf


def pipeline_interleaved_grads(stage_fn, loss_fn, stacked_params, x, y,
                               mesh, n_microbatches, v, axis="pp"):
    """Interleaved-1F1B train-step core.

    ``stacked_params``: leading dims (v, p) — chunk-major; virtual stage
    S = c*p + d runs chunk c's slice on device d, so a microbatch flows
    device 0..p-1 through chunk 0, wraps the ring, then chunk 1, etc.
    Returns (mean loss, param grads (v, p, ...), input grads) — the same
    contract as pipeline_1f1b_grads, which is this with v=1.
    """
    from jax.sharding import NamedSharding

    p = int(mesh.shape[axis])
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if leaves and leaves[0].shape[:2] != (v, p):
        raise ValueError("stacked_params leading dims must be (v=%d, p=%d)"
                         % (v, p))
    if x.shape[0] % n_microbatches:
        raise ValueError("batch %d not divisible by n_microbatches %d"
                         % (x.shape[0], n_microbatches))
    m = n_microbatches
    mb = x.shape[0] // m
    x_mb = x.reshape((m, mb) + x.shape[1:])
    y_mb = y.reshape((m, mb) + y.shape[1:])
    ticks = interleaved_schedule(m, p, v)
    tables = _tables(ticks, p, v, m)
    kslots = _stash_bound(ticks, p, v, m)
    param_specs = jax.tree_util.tree_map(
        lambda q: P(None, axis, *([None] * (q.ndim - 2))), stacked_params)
    x_mb = jax.device_put(x_mb, NamedSharding(mesh, P()))
    y_mb = jax.device_put(y_mb, NamedSharding(mesh, P()))
    stacked_params = jax.tree_util.tree_map(
        lambda q, sp: jax.device_put(q, NamedSharding(mesh, sp)),
        stacked_params, param_specs)
    fn = functools.partial(_interleaved_sharded, stage_fn=stage_fn,
                           loss_fn=loss_fn, axis_name=axis, v=v, m=m,
                           kslots=kslots)
    loss, pgrads, dx = shard_map(
        lambda a, b, c: fn(a, b, c, tables), mesh=mesh,
        in_specs=(P(), P(), param_specs),
        out_specs=(P(), param_specs, P()), check_vma=False)(
            x_mb, y_mb, stacked_params)
    return loss, pgrads, dx.reshape((x.shape[0],) + dx.shape[2:])
