"""Gluon pipeline parallelism: embed → pipelined trunk → head.

NEW capability (SURVEY §2.5 — the reference has no pipeline parallelism).
TPU-native design: the repeated trunk (N structurally-identical stage blocks,
e.g. transformer layers) rides the GPipe ppermute ring over the ``pp`` mesh
axis (parallel.pipeline), while the heterogeneous ends — embedding and head —
run OUTSIDE the ring, sharded over tp/dp like any other layer. On TPU this is
strictly better than putting embed/head inside the ring: they are single
matmuls that shard perfectly over the MXU, and excluding them keeps every
ring stage shape-identical, which is what lets XLA overlap ppermute with
stage compute on ICI. Loss and gradients flow through the whole composite
(the ring is differentiable), so one TrainStep trains embed + trunk + head
together — the "embed→layers→head with loss/grad through the pipeline" shape.

Usage::

    trunk  = PipelineStack([make_layer() for _ in range(4)], mesh, n_microbatches=8)
    net    = nn.HybridSequential()
    net.add(embed, trunk, head)
    step   = TrainStep(net, loss_fn, trainer)   # grads reach all three parts
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..gluon.block import HybridBlock
from ..gluon import _functional
from ..ndarray import _apply
from .pipeline import pipeline_spmd

__all__ = ["PipelineStack"]


class PipelineStack(HybridBlock):
    """Pipeline N structurally-identical blocks over the ``pp`` mesh axis.

    Each stage keeps its own Parameters (so ``collect_params``/Trainer see
    them all); at call time the per-stage tensors are stacked on a leading
    stage dim inside the traced program (gradient of stack = per-stage
    unstack) and the stack rides the GPipe ring. Stages must map
    (batch, ...) -> (batch, ...) with identical shapes — transformer layers.

    Stages with BatchNorm-style aux-state updates are rejected: aux writes
    cannot cross the shard_map boundary. Use LayerNorm inside ring stages.
    """

    def __init__(self, stages, mesh, axis="pp", n_microbatches=None,
                 data_axis=None, **kwargs):
        super().__init__(**kwargs)
        self.mesh = mesh
        self.axis = axis
        self.data_axis = data_axis
        self.n_stages = len(stages)
        self.n_microbatches = n_microbatches or self.n_stages
        self.stages = list(stages)
        for s in self.stages:
            self.register_child(s)
        self._stage_pure = None

    def _build(self):
        # pure fns traced from stage 0 (per train/eval mode); every stage
        # shares its structure
        self._stage_pure = {
            mode: _functional.make_pure_fn(self.stages[0], train_mode=mode)[2]
            for mode in (False, True)}
        self._per_stage = [list(s.collect_params().values())
                           for s in self.stages]
        def sig(stage, ps):
            # drop the stage's own name prefix; compare structure + shapes
            pre = len(getattr(stage, "prefix", "") or "")
            return [(p.name[pre:], p.shape) for p in ps]

        n0 = sig(self.stages[0], self._per_stage[0])
        for st, ps in zip(self.stages[1:], self._per_stage[1:]):
            if sig(st, ps) != n0:
                raise ValueError("pipeline stages must be structurally "
                                 "identical (same parameter structure)")

    def forward(self, x):
        from .. import autograd
        if self._stage_pure is None:
            self._build()
        train_mode = autograd.is_training()
        n_stages, nper = self.n_stages, len(self._per_stage[0])
        pure_fn = self._stage_pure[train_mode]
        mesh, axis, n_micro = self.mesh, self.axis, self.n_microbatches
        data_axis = self.data_axis
        flat = [p.data() for ps in self._per_stage for p in ps]

        if _functional.in_functional_mode():
            key = _functional.next_functional_key()
        elif train_mode:
            from ..gluon.block import _split_global_key
            key = _split_global_key()
        else:
            key = jax.random.PRNGKey(0)

        def fn(xd, *param_datas):
            eager = not isinstance(xd, jax.core.Tracer)
            stacked = [jnp.stack([param_datas[i * nper + j]
                                  for i in range(n_stages)])
                       for j in range(nper)]

            def stage_fn(stage_params, h, k):
                outs, aux = pure_fn(stage_params, [h], k)
                if aux:
                    raise ValueError(
                        "PipelineStack stages cannot carry aux-state updates "
                        "(BatchNorm running stats); use LayerNorm in ring "
                        "stages")
                return outs[0]

            out = pipeline_spmd(stage_fn, stacked, xd, mesh, n_micro,
                                axis=axis, data_axis=data_axis, key=key)
            if eager:
                # back to the caller's device so downstream eager ops (head,
                # loss) see a consistent placement; under jit the mesh-sharded
                # result flows on unchanged
                out = jax.device_put(out, next(iter(xd.devices())))
            return out

        return _apply(fn, x, *flat)
