"""Parallelism over TPU meshes (SURVEY §2.5 — the kvstore/NCCL/ps-lite stack
re-expressed as SPMD sharding + XLA collectives over ICI/DCN).

- mesh:              device mesh construction (dp/tp/pp/sp/ep axes)
- data_parallel:     sharded fused train step (≙ dist_device_sync kvstore)
- tensor_parallel:   row/col-sharded layers (NEW capability vs reference)
- ring_attention:    sequence/context parallelism over the ring (NEW)
- pipeline:          GPipe ring + hand-scheduled 1F1B pipeline (NEW)
- pipeline_interleaved: virtual-stage (interleaved) 1F1B — static greedy
                     tick tables, schedule-bounded stash; measured
                     disposition in docs/PERF_PIPELINE.md (NEW)
- moe:               expert parallel mixture-of-experts (NEW)
- compression:       2-bit gradient compression analog (ref gradient_compression.h)
"""
from .compat import shard_map, HAVE_SHARD_MAP, ShardMapUnavailable  # noqa
from .mesh import make_mesh, current_mesh, set_current_mesh, replicated, shard_spec  # noqa
from .data_parallel import DataParallelTrainStep  # noqa
from .tensor_parallel import ColParallelDense, RowParallelDense, shard_params  # noqa
from .ring_attention import ring_attention, local_attention  # noqa
from .ulysses import ulysses_attention  # noqa
from .pipeline import PipelineParallel, pipeline_spmd, pipeline_1f1b_grads  # noqa
from .pipeline_interleaved import (  # noqa
    pipeline_interleaved_grads, interleaved_schedule, schedule_stats)
from .gluon_pipeline import PipelineStack  # noqa
from .moe import MoELayer, load_balancing_loss, router_z_loss  # noqa
from .compression import GradientCompression  # noqa
from .dist import init_distributed, rank, num_workers  # noqa
