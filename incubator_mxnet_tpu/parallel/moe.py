"""Mixture-of-Experts with expert parallelism — NEW capability
(SURVEY §2.5: no MoE ops in the reference).

Experts are sharded over the ``ep`` mesh axis (expert dim of the stacked
weights carries PartitionSpec('ep', ...)); token routing follows the GShard
recipe: top-k gating, per-expert capacity ``C = ceil(k*T/E * capacity_factor)``
with position-in-expert computed by cumulative sum, tokens over capacity
dropped, and a dispatch/combine einsum whose token→expert resharding GSPMD
lowers to an all-to-all over ``ep``. An auxiliary load-balancing loss
(Switch-Transformer form, ``E * sum_e fraction_routed_e * mean_gate_e``)
is returned alongside the output so the trainer can add it to the task loss.

``capacity_factor=None`` selects dense (capacity-free) dispatch: every token
reaches its top-k experts with no dropping — exact but O(T*E) compute, used
for small expert counts and in tests as the reference for the dropped path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..gluon.block import HybridBlock
from ..ndarray import _apply

__all__ = ["MoELayer", "load_balancing_loss", "router_z_loss"]


def load_balancing_loss(gates, top_idx, num_experts):
    """Switch-Transformer aux loss: E * sum_e f_e * p_e.

    gates: (T, E) softmax router probabilities; top_idx: (T, k) chosen experts.
    f_e = fraction of tokens whose FIRST choice is e; p_e = mean gate prob.
    """
    p = jnp.mean(gates, axis=0)                                   # (E,)
    f = jnp.mean(jax.nn.one_hot(top_idx[:, 0], num_experts,
                                dtype=gates.dtype), axis=0)       # (E,)
    return num_experts * jnp.sum(f * p)


def router_z_loss(logits):
    """ST-MoE router z-loss: mean(logsumexp(logits)^2) — keeps router
    logits small so the softmax stays out of its saturated/overflow-prone
    region (bf16 routers drift without it)."""
    z = jax.nn.logsumexp(logits, axis=-1)
    return jnp.mean(jnp.square(z))


def _route_dense(tokens, gates, top_vals, top_idx, num_experts, w1, w2, act):
    """Capacity-free dispatch: every token to its top-k experts (no drops)."""
    oh = jax.nn.one_hot(top_idx, num_experts, dtype=gates.dtype)  # (T,k,E)
    combine = jnp.einsum("tk,tke->te", top_vals, oh)              # (T,E)
    h = jnp.einsum("td,edh->eth", tokens, w1)
    h = act(h)
    y = jnp.einsum("eth,ehd->etd", h, w2)
    return jnp.einsum("etd,te->td", y, combine)


def _route_capacity(tokens, top_vals, top_idx, num_experts, capacity, w1, w2,
                    act):
    """GShard capacity dispatch with token dropping.

    Position-in-expert: all 1st choices fill expert queues before any 2nd
    choice (priority by k, then token order), matching GShard's semantics.
    """
    T, k = top_idx.shape
    oh = jax.nn.one_hot(top_idx, num_experts, dtype=jnp.float32)  # (T,k,E)
    # (k,T,E) so cumsum order = all k=0 assignments first, then k=1, ...
    flat = oh.transpose(1, 0, 2).reshape(k * T, num_experts)
    pos = jnp.cumsum(flat, axis=0) - flat                         # (k*T,E)
    pos = (pos * flat).sum(-1).reshape(k, T).transpose(1, 0)      # (T,k)
    pos = pos.astype(jnp.int32)  # exact slot ids for one_hot / comparison
    keep = (pos < capacity)                                       # (T,k)
    gate_w = jnp.where(keep, top_vals, 0.0)
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)     # (T,k,C)
    # combine (T,E,C): gate weight at each token's slot; dispatch = combine>0
    combine = jnp.einsum("tk,tke,tkc->tec", gate_w, oh, pos_oh)
    dispatch = (combine > 0.0).astype(tokens.dtype)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, tokens)       # (E,C,D)
    h = jnp.einsum("ecd,edh->ech", expert_in, w1)
    h = act(h)
    y = jnp.einsum("ech,ehd->ecd", h, w2)
    return jnp.einsum("tec,ecd->td", combine.astype(y.dtype), y)


class MoELayer(HybridBlock):
    """Top-k gated MoE FFN: y = sum_k g_k * FFN_{e_k}(x).

    Weights: w1 (E, D, H), w2 (E, H, D) with E sharded over ``ep``.
    ``forward`` returns the output only; ``forward_with_aux`` additionally
    returns the load-balancing loss for the trainer to add to the task loss.
    """

    def __init__(self, num_experts, hidden_size, ffn_hidden, top_k=2,
                 ep_axis="ep", activation="relu", capacity_factor=None,
                 z_loss_coef=1e-3, **kwargs):
        super().__init__(**kwargs)
        if capacity_factor is None and num_experts >= 8:
            import warnings
            warnings.warn(
                "MoELayer(num_experts=%d, capacity_factor=None): the dense "
                "capacity-free dispatch is O(T*E) compute and defeats "
                "expert parallelism at scale — pass capacity_factor "
                "(GShard default 1.25) for real workloads" % num_experts,
                stacklevel=2)
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.z_loss_coef = z_loss_coef
        self._act = activation
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(num_experts, hidden_size), init="xavier")
            self.w1 = self.params.get("w1", shape=(num_experts, hidden_size, ffn_hidden),
                                      init="xavier")
            self.w2 = self.params.get("w2", shape=(num_experts, ffn_hidden, hidden_size),
                                      init="xavier")
        self.w1.sharding = P(ep_axis, None, None)
        self.w2.sharding = P(ep_axis, None, None)

    def _fn(self, xd, gw, w1, w2, compute_aux):
        top_k, num_experts = self.top_k, self.num_experts
        act = jax.nn.relu if self._act == "relu" else jax.nn.gelu
        shape = xd.shape
        tokens = xd.reshape(-1, shape[-1])                        # (T, D)
        logits = tokens @ gw.T                                    # (T, E)
        gates = jax.nn.softmax(logits, axis=-1)
        top_vals, top_idx = jax.lax.top_k(gates, top_k)           # (T, k)
        top_vals = top_vals / jnp.sum(top_vals, -1, keepdims=True)
        if self.capacity_factor is None:
            out = _route_dense(tokens, gates, top_vals, top_idx, num_experts,
                               w1, w2, act)
        else:
            T = tokens.shape[0]
            capacity = max(1, int(-(-top_k * T * self.capacity_factor
                                    // num_experts)))
            out = _route_capacity(tokens, top_vals, top_idx, num_experts,
                                  capacity, w1, w2, act)
        out = out.reshape(shape)
        if compute_aux:
            aux = load_balancing_loss(gates, top_idx, num_experts) \
                + self.z_loss_coef * router_z_loss(logits)
            return out, aux
        return out

    def forward(self, x):
        """x: (..., D) → (..., D)."""
        return _apply(lambda *a: self._fn(*a, compute_aux=False), x,
                      self.gate_weight.data(), self.w1.data(), self.w2.data())

    def forward_with_aux(self, x):
        """Returns (y, aux) where aux = Switch load-balancing loss +
        z_loss_coef * ST-MoE router z-loss (add to the task loss)."""
        return _apply(lambda *a: self._fn(*a, compute_aux=True), x,
                      self.gate_weight.data(), self.w1.data(), self.w2.data())
