"""Mixture-of-Experts with expert parallelism — NEW capability
(SURVEY §2.5: no MoE ops in the reference).

Experts are sharded over the ``ep`` mesh axis (expert dim of the stacked
weights carries PartitionSpec('ep', ...)); token routing is dense top-k with
capacity-free einsum dispatch — the all-to-all falls out of GSPMD resharding
between the token-sharded and expert-sharded einsum operands.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import ndarray as nd
from ..gluon.block import HybridBlock
from ..ndarray import NDArray, _apply

__all__ = ["MoELayer"]


class MoELayer(HybridBlock):
    """Top-k gated MoE FFN: y = sum_k g_k * FFN_{e_k}(x).

    Weights: w1 (E, D, H), w2 (E, H, D) with E sharded over ``ep``.
    """

    def __init__(self, num_experts, hidden_size, ffn_hidden, top_k=2,
                 ep_axis="ep", activation="relu", **kwargs):
        super().__init__(**kwargs)
        self.num_experts = num_experts
        self.top_k = top_k
        self._act = activation
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(num_experts, hidden_size), init="xavier")
            self.w1 = self.params.get("w1", shape=(num_experts, hidden_size, ffn_hidden),
                                      init="xavier")
            self.w2 = self.params.get("w2", shape=(num_experts, ffn_hidden, hidden_size),
                                      init="xavier")
        self.w1.sharding = P(ep_axis, None, None)
        self.w2.sharding = P(ep_axis, None, None)

    def forward(self, x):
        """x: (..., D) → (..., D); dense dispatch (no token dropping)."""
        top_k, num_experts, act = self.top_k, self.num_experts, self._act

        def fn(xd, gw, w1, w2):
            shape = xd.shape
            tokens = xd.reshape(-1, shape[-1])                       # (T, D)
            logits = tokens @ gw.T                                    # (T, E)
            import jax
            gates = jax.nn.softmax(logits, axis=-1)
            top_vals, top_idx = jax.lax.top_k(gates, top_k)           # (T, k)
            top_vals = top_vals / jnp.sum(top_vals, -1, keepdims=True)
            # dense one-hot combine weights (T, E)
            oh = jax.nn.one_hot(top_idx, num_experts, dtype=gates.dtype)  # (T,k,E)
            combine = jnp.einsum("tk,tke->te", top_vals, oh)
            # expert compute: (E, T, H) — GSPMD reshards tokens→experts (a2a)
            h = jnp.einsum("td,edh->eth", tokens, w1)
            h = jax.nn.relu(h) if act == "relu" else jax.nn.gelu(h)
            y = jnp.einsum("eth,ehd->etd", h, w2)
            out = jnp.einsum("etd,te->td", y, combine)
            return out.reshape(shape)

        return _apply(fn, x, self.gate_weight.data(), self.w1.data(), self.w2.data())
