"""Data-parallel fused train step over a mesh.

TPU-native replacement for the reference's data-parallel stack
(DataParallelExecutorGroup executor_group.py:144 + kvstore comm.h/NCCL/dist):
the batch is sharded over the ``dp`` mesh axis inside ONE compiled program;
XLA emits the gradient all-reduce on ICI. Multi-host (DCN) runs the same
program under jax.distributed with a process-spanning mesh.
"""
from __future__ import annotations

from ..jit import TrainStep
from .mesh import current_mesh

__all__ = ["DataParallelTrainStep"]


class DataParallelTrainStep(TrainStep):
    """TrainStep with the batch sharded over a mesh axis.

    Parameters follow their per-parameter ``sharding`` (so tensor/expert
    parallel compose with dp on a 2D+ mesh); unannotated params replicate.
    """

    def __init__(self, net, loss_fn, trainer, mesh=None, data_axis="dp", **kw):
        mesh = mesh or current_mesh()
        if mesh is None:
            raise ValueError("DataParallelTrainStep needs a mesh "
                             "(parallel.make_mesh({'dp': N}))")
        super().__init__(net, loss_fn, trainer, mesh=mesh, data_axis=data_axis, **kw)
