"""Gradient compression (ref src/kvstore/gradient_compression.h:37-127).

2-bit stochastic-threshold quantization with error-feedback residual, as a
pure JAX transform usable either through the kvstore facade or as a
``grad_postprocess`` hook on the fused train step (where it models the
bandwidth/precision trade-off of the reference's dist push path).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray import NDArray

__all__ = ["GradientCompression"]


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):
        if type != "2bit":
            raise ValueError("only 2bit compression is supported (ref parity)")
        self.threshold = float(threshold)
        self._residuals = {}

    def compress_decompress(self, grad, key):
        """Quantize to {-t, 0, +t} with error feedback (ref Quantize/Dequantize).

        ``key`` is mandatory: residuals are error-feedback state that must be
        keyed by the stable parameter key (kvstore key / param name), never by
        object identity — Python id() reuse would silently corrupt feedback.
        """
        data = grad._data if isinstance(grad, NDArray) else grad
        k = key
        res = self._residuals.get(k)
        if res is None:
            res = jnp.zeros_like(data)
        acc = data + res
        t = self.threshold
        q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t, 0.0)).astype(data.dtype)
        self._residuals[k] = acc - q
        if isinstance(grad, NDArray):
            return NDArray(q)
        return q
