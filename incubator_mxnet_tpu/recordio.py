"""RecordIO: packed binary record format + indexed random access
(ref python/mxnet/recordio.py, src/io/image_recordio.h, dmlc RecordIO).

Binary-compatible with the reference: records framed as
``[kMagic u32][lrec u32][data][pad to 4B]`` with ``lrec = cflag<<29 | len``,
and the image header ``IRHeader = (flag u32, label f32, id u64, id2 u64)``.
A C++ reader/writer with the same framing lives in native/ for the hot path.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as onp

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xCED7230A

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential reader/writer (ref recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.record.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["record"] = None
        if self.is_open:
            d["_pos"] = self.record.tell()
        return d

    def __setstate__(self, d):
        pos = d.pop("_pos", None)
        self.__dict__.update(d)
        self.open()
        if pos is not None:
            self.record.seek(pos)

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        lrec = len(buf)  # cflag = 0 (single full record)
        self.record.write(struct.pack("<II", _kMagic, lrec))
        self.record.write(buf)
        pad = (4 - (len(buf) & 3)) & 3
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        head = self.record.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _kMagic:
            raise IOError("invalid RecordIO magic in %s" % self.uri)
        length = lrec & ((1 << 29) - 1)
        buf = self.record.read(length)
        pad = (4 - (length & 3)) & 3
        if pad:
            self.record.read(pad)
        return buf

    def tell(self):
        return self.record.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access via .idx sidecar (ref recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if getattr(self, "is_open", False) and self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (k, self.idx[k]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        pos = self.record.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack IRHeader + payload (ref recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id, header.id2)
        return hdr + s
    label = onp.asarray(header.label, dtype=onp.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """ref recordio.py unpack → (IRHeader, payload)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = onp.frombuffer(s[: flag * 4], dtype=onp.float32)
        header = IRHeader(flag, arr, id_, id2)
        s = s[flag * 4:]
    else:
        header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode image + pack (ref recordio.py pack_img; PIL backend)."""
    import io as _io

    from PIL import Image

    arr = onp.asarray(img).astype("uint8")
    pil = Image.fromarray(arr)
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    pil.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """ref recordio.py unpack_img → (IRHeader, np image HWC)."""
    import io as _io

    from PIL import Image

    header, img_bytes = unpack(s)
    pil = Image.open(_io.BytesIO(img_bytes))
    if iscolor == 0:
        pil = pil.convert("L")
    elif iscolor == 1:
        pil = pil.convert("RGB")
    return header, onp.asarray(pil)
