// C predict API — flat C ABI for running exported .mxtpu serving artifacts
// from C/C++ without writing any Python (ref src/c_api/c_predict_api.cc:
// MXPredCreate/SetInput/Forward/GetOutputShape/GetOutput/Free; error
// convention ref MXGetLastError).
//
// Design (TPU-native): the artifact is a serialized COMPILED program
// (StableHLO via jax.export — see contrib/serving.py), not an op graph, so
// there is no operator registry to re-implement natively. This library
// embeds a CPython interpreter to host the XLA runtime that executes the
// artifact — the same layering as the reference, where c_predict_api.cc is
// a thin shim over the full core; here the "core" is the Python/JAX layer
// by design (SURVEY §7). The ABI itself is pure C: opaque handles, raw
// byte buffers, int return codes, thread-local error strings.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC c_predict_api.cc
//        -I$(python3-config --includes) -lpython3.12 -o libmxtpu_predict.so
// Loading from an already-running Python process (ctypes) also works: the
// library detects the live interpreter and just uses it.
//
// Thread-safety: calls are serialized through the GIL; distinct handles
// may be used from distinct threads.
#include <Python.h>

#include <dlfcn.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

namespace {

thread_local std::string g_err;

int fail(const std::string& msg) {
  g_err = msg;
  return -1;
}

// Fetch the pending Python exception into g_err.
int fail_py(const char* where) {
  std::string msg = std::string(where) + ": python error";
  if (PyErr_Occurred()) {
    PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
    PyErr_Fetch(&type, &value, &tb);
    PyErr_NormalizeException(&type, &value, &tb);
    if (value) {
      PyObject* s = PyObject_Str(value);
      if (s) {
        const char* c = PyUnicode_AsUTF8(s);
        if (c) msg = std::string(where) + ": " + c;
        Py_DECREF(s);
      }
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
    PyErr_Clear();
  }
  return fail(msg);
}

std::once_flag g_init_once;
bool g_init_ok = false;
std::string g_init_err;

// Directory containing this .so → repo root two levels up
// (<root>/incubator_mxnet_tpu/native/libmxtpu_predict.so).
std::string repo_root_from_so() {
  Dl_info info;
  if (!dladdr(reinterpret_cast<void*>(&repo_root_from_so), &info) ||
      !info.dli_fname)
    return "";
  std::string p(info.dli_fname);
  for (int up = 0; up < 3; ++up) {
    auto pos = p.find_last_of('/');
    if (pos == std::string::npos) return "";
    p.resize(pos);
  }
  return p;
}

void init_python() {
  if (Py_IsInitialized()) {  // hosted inside a live interpreter (ctypes)
    g_init_ok = true;
    return;
  }
  PyConfig config;
  PyConfig_InitPythonConfig(&config);
  const char* exe = getenv("MXTPU_PYTHON");
  if (exe && *exe) {
    PyStatus st = PyConfig_SetBytesString(&config, &config.executable, exe);
    if (PyStatus_Exception(st)) {
      PyConfig_Clear(&config);
      g_init_err = "bad MXTPU_PYTHON";
      return;
    }
  }
  PyStatus st = Py_InitializeFromConfig(&config);
  PyConfig_Clear(&config);
  if (PyStatus_Exception(st)) {
    g_init_err = std::string("Py_InitializeFromConfig failed: ") +
                 (st.err_msg ? st.err_msg : "?");
    return;
  }
  std::string root = repo_root_from_so();
  if (!root.empty()) {
    std::string quoted;  // escape for a single-quoted python literal
    for (char ch : root) {
      if (ch == '\\' || ch == '\'') quoted += '\\';
      quoted += ch;
    }
    std::string code = "import sys; sys.path.insert(0, '" + quoted + "')";
    PyRun_SimpleString(code.c_str());
  }
  // Pin the JAX platform from the caller's env BEFORE any framework import:
  // the deployment env's sitecustomize may register accelerator plugins that
  // would otherwise win during package import (backend init is first-touch).
  PyRun_SimpleString(
      "import os\n"
      "if os.environ.get('JAX_PLATFORMS'):\n"
      "    import jax\n"
      "    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])\n");
  g_init_ok = true;
  // Drop the GIL acquired by initialization so PyGILState_Ensure works
  // from any caller thread (including this one).
  PyEval_SaveThread();
}

// RAII: ensure interpreter + hold GIL for the scope.
struct Gil {
  PyGILState_STATE state;
  bool ok;
  Gil() : ok(false) {
    std::call_once(g_init_once, init_python);
    if (!g_init_ok) return;
    state = PyGILState_Ensure();
    ok = true;
  }
  ~Gil() {
    if (ok) PyGILState_Release(state);
  }
};

PyObject* embed_module() {  // borrowed-style: cached strong ref
  static PyObject* mod = nullptr;
  if (!mod)
    mod = PyImport_ImportModule("incubator_mxnet_tpu.native._predict_embed");
  return mod;
}

struct PredHandle {
  PyObject* state;  // strong ref to _PredState
};

// Call module fn with args; returns new ref or null.
PyObject* call(const char* fn, PyObject* args) {
  PyObject* mod = embed_module();
  if (!mod) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (!f) return nullptr;
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return r;
}

int get_int(const char* fn, PredHandle* h, int* out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(O)", h->state);
  PyObject* r = call(fn, args);
  Py_DECREF(args);
  if (!r) return fail_py(fn);
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  if (PyErr_Occurred()) return fail_py(fn);
  return 0;
}

int get_shape(const char* fn, PredHandle* h, int index, int64_t* out_shape,
              int cap, int* out_ndim) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(Oi)", h->state, index);
  PyObject* r = call(fn, args);
  Py_DECREF(args);
  if (!r) return fail_py(fn);
  Py_ssize_t n = PyTuple_Size(r);
  *out_ndim = (int)n;
  if (out_shape) {
    if (n > cap) {
      Py_DECREF(r);
      return fail("shape buffer too small");
    }
    for (Py_ssize_t i = 0; i < n; ++i)
      out_shape[i] = PyLong_AsLongLong(PyTuple_GetItem(r, i));
  }
  Py_DECREF(r);
  return 0;
}

int get_dtype(const char* fn, PredHandle* h, int index, char* buf, int cap) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(Oi)", h->state, index);
  PyObject* r = call(fn, args);
  Py_DECREF(args);
  if (!r) return fail_py(fn);
  const char* s = PyUnicode_AsUTF8(r);
  if (!s || (int)strlen(s) + 1 > cap) {
    Py_DECREF(r);
    return fail("dtype buffer too small");
  }
  snprintf(buf, cap, "%s", s);
  Py_DECREF(r);
  return 0;
}

}  // namespace

extern "C" {

const char* MXTPUPredGetLastError() { return g_err.c_str(); }

// Load a .mxtpu serving artifact (contrib/serving.export_model output).
// ≙ MXPredCreate (the artifact replaces symbol-json + param-blob).
int MXTPUPredCreate(const char* artifact_path, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(s)", artifact_path);
  if (!args) return fail_py("MXTPUPredCreate");
  PyObject* st = call("create", args);
  Py_DECREF(args);
  if (!st) return fail_py("MXTPUPredCreate");
  auto* h = new PredHandle{st};
  *out = h;
  return 0;
}

int MXTPUPredNumInputs(void* handle, int* out) {
  return get_int("num_inputs", static_cast<PredHandle*>(handle), out);
}

int MXTPUPredNumOutputs(void* handle, int* out) {
  return get_int("num_outputs", static_cast<PredHandle*>(handle), out);
}

int MXTPUPredGetInputShape(void* handle, int index, int64_t* shape, int cap,
                           int* out_ndim) {
  return get_shape("input_shape", static_cast<PredHandle*>(handle), index,
                   shape, cap, out_ndim);
}

int MXTPUPredGetOutputShape(void* handle, int index, int64_t* shape, int cap,
                            int* out_ndim) {
  return get_shape("output_shape", static_cast<PredHandle*>(handle), index,
                   shape, cap, out_ndim);
}

// dtype as its numpy name ("float32", "int8", "bfloat16", ...).
int MXTPUPredGetInputDType(void* handle, int index, char* buf, int cap) {
  return get_dtype("input_dtype", static_cast<PredHandle*>(handle), index,
                   buf, cap);
}

int MXTPUPredGetOutputDType(void* handle, int index, char* buf, int cap) {
  return get_dtype("output_dtype", static_cast<PredHandle*>(handle), index,
                   buf, cap);
}

// data: C-contiguous row-major buffer of exactly the input's
// shape-product x dtype-size bytes. ≙ MXPredSetInput.
int MXTPUPredSetInput(void* handle, int index, const void* data,
                      int64_t nbytes) {
  auto* h = static_cast<PredHandle*>(handle);
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* view = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)), nbytes, PyBUF_READ);
  if (!view) return fail_py("MXTPUPredSetInput");
  PyObject* args = Py_BuildValue("(OiN)", h->state, index, view);
  if (!args) {
    Py_DECREF(view);
    return fail_py("MXTPUPredSetInput");
  }
  PyObject* r = call("set_input", args);
  Py_DECREF(args);  // releases view too ("N")
  if (!r) return fail_py("MXTPUPredSetInput");
  Py_DECREF(r);
  return 0;
}

int MXTPUPredForward(void* handle) {
  auto* h = static_cast<PredHandle*>(handle);
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(O)", h->state);
  PyObject* r = call("forward", args);
  Py_DECREF(args);
  if (!r) return fail_py("MXTPUPredForward");
  Py_DECREF(r);
  return 0;
}

// Copies output `index` into data (must be exactly the output's byte size).
// ≙ MXPredGetOutput.
int MXTPUPredGetOutput(void* handle, int index, void* data, int64_t nbytes) {
  auto* h = static_cast<PredHandle*>(handle);
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(Oi)", h->state, index);
  PyObject* r = call("output_bytes", args);
  Py_DECREF(args);
  if (!r) return fail_py("MXTPUPredGetOutput");
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    return fail_py("MXTPUPredGetOutput");
  }
  if (len != nbytes) {
    Py_DECREF(r);
    return fail("output size mismatch: have " + std::to_string(len) +
                " bytes, caller gave " + std::to_string(nbytes));
  }
  memcpy(data, buf, len);
  Py_DECREF(r);
  return 0;
}

int MXTPUPredFree(void* handle) {
  auto* h = static_cast<PredHandle*>(handle);
  if (Py_IsInitialized()) {
    PyGILState_STATE s = PyGILState_Ensure();
    Py_XDECREF(h->state);
    PyGILState_Release(s);
  }
  delete h;
  return 0;
}

int mxtpu_predict_abi_version() { return 2; }

}  // extern "C"

// ---------------------------------------------------------------------------
// Imperative invoke slice (ref include/mxnet/c_api.h MXImperativeInvokeEx,
// MXNDArrayCreateEx, MXNDArraySyncCopyToCPU): name-dispatched EAGER op
// calls on opaque NDArray handles, so non-Python frontends (cpp_package,
// julia_package) can run any registered operator — not just exported
// predict artifacts. Dispatch goes through native/_invoke_embed.py into the
// same nd/nd.contrib op registry the Python frontend uses.
// ---------------------------------------------------------------------------
namespace {

PyObject* invoke_module() {
  static PyObject* mod = nullptr;
  if (!mod)
    mod = PyImport_ImportModule("incubator_mxnet_tpu.native._invoke_embed");
  return mod;
}

struct NDHandle {
  PyObject* arr;  // strong ref to an incubator_mxnet_tpu NDArray
};

PyObject* call_invoke(const char* fn, PyObject* args) {
  PyObject* mod = invoke_module();
  if (!mod) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (!f) return nullptr;
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return r;
}

}  // namespace

extern "C" {

// Create an NDArray from host bytes (C-contiguous). ≙ MXNDArrayCreateEx.
int MXTPUNDCreate(const char* dtype, const int64_t* shape, int ndim,
                  const void* data, int64_t nbytes, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* shp = PyTuple_New(ndim);
  if (!shp) return fail_py("MXTPUNDCreate");
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject* view = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)), nbytes, PyBUF_READ);
  if (!view) {
    Py_DECREF(shp);
    return fail_py("MXTPUNDCreate");
  }
  PyObject* args = Py_BuildValue("(sNN)", dtype, shp, view);
  if (!args) return fail_py("MXTPUNDCreate");
  PyObject* r = call_invoke("nd_create", args);
  Py_DECREF(args);
  if (!r) return fail_py("MXTPUNDCreate");
  *out = new NDHandle{r};
  return 0;
}

int MXTPUNDGetShape(void* handle, int64_t* shape, int cap, int* out_ndim) {
  auto* h = static_cast<NDHandle*>(handle);
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(O)", h->arr);
  PyObject* r = call_invoke("nd_shape", args);
  Py_DECREF(args);
  if (!r) return fail_py("MXTPUNDGetShape");
  Py_ssize_t n = PyTuple_Size(r);
  *out_ndim = (int)n;
  if (shape) {
    if (n > cap) {
      Py_DECREF(r);
      return fail("shape buffer too small");
    }
    for (Py_ssize_t i = 0; i < n; ++i)
      shape[i] = PyLong_AsLongLong(PyTuple_GetItem(r, i));
  }
  Py_DECREF(r);
  return 0;
}

int MXTPUNDGetDType(void* handle, char* buf, int cap) {
  auto* h = static_cast<NDHandle*>(handle);
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(O)", h->arr);
  PyObject* r = call_invoke("nd_dtype", args);
  Py_DECREF(args);
  if (!r) return fail_py("MXTPUNDGetDType");
  const char* s = PyUnicode_AsUTF8(r);
  if (!s || (int)strlen(s) + 1 > cap) {
    Py_DECREF(r);
    return fail("dtype buffer too small");
  }
  snprintf(buf, cap, "%s", s);
  Py_DECREF(r);
  return 0;
}

// Copy the array out as contiguous bytes; pass data=null to query size.
// ≙ MXNDArraySyncCopyToCPU.
int MXTPUNDGetData(void* handle, void* data, int64_t cap,
                   int64_t* out_nbytes) {
  auto* h = static_cast<NDHandle*>(handle);
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* args = Py_BuildValue("(O)", h->arr);
  PyObject* r = call_invoke("nd_bytes", args);
  Py_DECREF(args);
  if (!r) return fail_py("MXTPUNDGetData");
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    return fail_py("MXTPUNDGetData");
  }
  if (out_nbytes) *out_nbytes = (int64_t)len;
  if (data) {
    if (len > cap) {
      Py_DECREF(r);
      return fail("data buffer too small: need " + std::to_string(len));
    }
    memcpy(data, buf, len);
  }
  Py_DECREF(r);
  return 0;
}

int MXTPUNDFree(void* handle) {
  auto* h = static_cast<NDHandle*>(handle);
  if (Py_IsInitialized()) {
    PyGILState_STATE s = PyGILState_Ensure();
    Py_XDECREF(h->arr);
    PyGILState_Release(s);
  }
  delete h;
  return 0;
}

// Name-dispatched eager op call. kwargs_json: JSON object of op attributes
// (numbers/strings/lists), may be null/empty. Outputs land in out_handles
// (capacity cap); *n_out reports how many. ≙ MXImperativeInvokeEx.
int MXTPUImperativeInvoke(const char* op_name, void** inputs, int n_inputs,
                          const char* kwargs_json, void** out_handles,
                          int cap, int* n_out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* ins = PyList_New(n_inputs);
  if (!ins) return fail_py("MXTPUImperativeInvoke");
  for (int i = 0; i < n_inputs; ++i) {
    PyObject* a = static_cast<NDHandle*>(inputs[i])->arr;
    Py_INCREF(a);
    PyList_SET_ITEM(ins, i, a);
  }
  PyObject* args = Py_BuildValue(
      "(sNs)", op_name, ins, kwargs_json ? kwargs_json : "");
  if (!args) return fail_py("MXTPUImperativeInvoke");
  PyObject* r = call_invoke("invoke", args);
  Py_DECREF(args);
  if (!r) return fail_py("MXTPUImperativeInvoke");
  Py_ssize_t n = PyTuple_Size(r);
  if (n > cap) {
    Py_DECREF(r);
    return fail("output handle array too small: need " + std::to_string(n));
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyTuple_GetItem(r, i);
    Py_INCREF(o);
    out_handles[i] = new NDHandle{o};
  }
  *n_out = (int)n;
  Py_DECREF(r);
  return 0;
}

const char* MXTPUNDGetLastError() { return g_err.c_str(); }

}  // extern "C"

// ---- autograd slice (ref c_api.h MXAutogradSetIsRecording /
// MXAutogradBackwardEx / MXNDArrayGetGrad): with MXTPUImperativeInvoke,
// non-Python frontends can TRAIN from C — tape scope, backward, gradient
// readout, and parameter writeback. -----------------------------------

namespace {

int call_bool(const char* fn, PyObject* args) {
  PyObject* r = call_invoke(fn, args);
  Py_DECREF(args);
  if (!r) return fail_py(fn);
  Py_DECREF(r);
  return 0;
}

}  // namespace

extern "C" int MXTPUNDAttachGrad(void* handle) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<NDHandle*>(handle);
  return call_bool("attach_grad", Py_BuildValue("(O)", h->arr));
}

extern "C" int MXTPUAutogradRecordBegin() {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return call_bool("record_begin", PyTuple_New(0));
}

extern "C" int MXTPUAutogradRecordEnd() {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return call_bool("record_end", PyTuple_New(0));
}

extern "C" int MXTPUNDBackward(void* handle) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<NDHandle*>(handle);
  return call_bool("backward", Py_BuildValue("(O)", h->arr));
}

// Returns a NEW NDArray handle holding the gradient of `handle`.
extern "C" int MXTPUNDGetGrad(void* handle, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<NDHandle*>(handle);
  PyObject* args = Py_BuildValue("(O)", h->arr);
  PyObject* r = call_invoke("grad_of", args);
  Py_DECREF(args);
  if (!r) return fail_py("MXTPUNDGetGrad");
  *out = new NDHandle{r};
  return 0;
}

// Overwrite the array's buffer from host bytes (optimizer writeback).
extern "C" int MXTPUNDSetData(void* handle, const char* dtype,
                              const void* data, int64_t nbytes) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<NDHandle*>(handle);
  PyObject* view = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)), nbytes, PyBUF_READ);
  if (!view) return fail_py("MXTPUNDSetData");
  PyObject* args = Py_BuildValue("(ONs)", h->arr, view, dtype);
  if (!args) return fail_py("MXTPUNDSetData");
  return call_bool("set_data", args);
}

// ---------------------------------------------------------------------------
// Graph slice (ref include/mxnet/c_api.h MXSymbolCreateAtomicSymbol /
// MXSymbolCompose / MXSymbolListArguments / MXExecutorSimpleBindEx
// (src/c_api/c_api_executor.cc:860) / MXExecutorForward / MXExecutorBackward
// / MXExecutorOutputs): C frontends can BUILD and RUN a graph — compose
// symbols, simple_bind, forward/backward, and read/update bound arrays —
// not just predict or run eager ops. Dispatch goes through
// native/_graph_embed.py into the same symbol/executor stack the Python
// frontend uses; array traffic rides the existing ND ABI handles.
// ---------------------------------------------------------------------------

namespace {

PyObject* graph_module() {
  static PyObject* mod = nullptr;
  if (!mod)
    mod = PyImport_ImportModule("incubator_mxnet_tpu.native._graph_embed");
  return mod;
}

// STEALS the args reference (every call site passes a fresh
// Py_BuildValue tuple; decref here keeps the call sites leak-free —
// same contract as call_bool above). Shared by the graph and extended
// tiers; `modget` is the cached-import accessor for the target module.
PyObject* call_stealing(PyObject* (*modget)(), const char* fn,
                        PyObject* args) {
  if (!args) return nullptr;
  PyObject* mod = modget();
  if (!mod) {
    Py_DECREF(args);
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (!f) {
    Py_DECREF(args);
    return nullptr;
  }
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_DECREF(args);
  return r;
}

PyObject* call_graph(const char* fn, PyObject* args) {
  return call_stealing(graph_module, fn, args);
}

struct SymHandle {
  PyObject* obj;  // Symbol, atomic token, or Executor (opaque to C)
};

// buf == nullptr: size-probe handshake (required length incl. NUL via
// *needed) — the MXTPUNDGetData convention, so callers can retry with a
// right-sized buffer instead of dead-ending on big graphs.
int str_out(PyObject* r, char* buf, int cap, int64_t* needed,
            const char* where) {
  const char* c = PyUnicode_AsUTF8(r);
  if (!c) {
    Py_DECREF(r);
    return fail_py(where);
  }
  std::string s(c);
  Py_DECREF(r);
  if (needed) *needed = (int64_t)s.size() + 1;
  if (!buf) return 0;
  if ((int)s.size() + 1 > cap) return fail("buffer too small");
  std::snprintf(buf, cap, "%s", s.c_str());
  return 0;
}

}  // namespace

extern "C" {

// ≙ MXSymbolCreateVariable
int MXTPUSymbolCreateVariable(const char* name, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* r = call_graph("sym_variable", Py_BuildValue("(s)", name));
  if (!r) return fail_py("MXTPUSymbolCreateVariable");
  *out = new SymHandle{r};
  return 0;
}

// ≙ MXSymbolCreateAtomicSymbol (attrs as a JSON object string)
int MXTPUSymbolCreateAtomic(const char* op_name, const char* attrs_json,
                            void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* r = call_graph("sym_atomic",
                           Py_BuildValue("(ss)", op_name, attrs_json));
  if (!r) return fail_py("MXTPUSymbolCreateAtomic");
  *out = new SymHandle{r};
  return 0;
}

// ≙ MXSymbolCompose: mutates `handle` from atomic token to composed node.
// keys[i] names the operator input args[i] binds to (NULL/"" = positional).
int MXTPUSymbolCompose(void* handle, const char* name, int n,
                       const char** keys, void** args) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(handle);
  PyObject* kl = PyList_New(n);
  PyObject* al = PyList_New(n);
  if (!kl || !al) {
    Py_XDECREF(kl);
    Py_XDECREF(al);
    return fail_py("MXTPUSymbolCompose");
  }
  for (int i = 0; i < n; ++i) {
    PyList_SET_ITEM(kl, i, PyUnicode_FromString(keys && keys[i] ? keys[i]
                                                                : ""));
    PyObject* a = static_cast<SymHandle*>(args[i])->obj;
    Py_INCREF(a);
    PyList_SET_ITEM(al, i, a);
  }
  // N-format only steals kl/al on SUCCESS; drop them ourselves on failure
  PyObject* tup = Py_BuildValue("(OsNN)", h->obj, name ? name : "", kl, al);
  if (!tup) {
    Py_DECREF(kl);
    Py_DECREF(al);
    return fail_py("MXTPUSymbolCompose");
  }
  PyObject* r = call_graph("sym_compose", tup);
  if (!r) return fail_py("MXTPUSymbolCompose");
  Py_DECREF(h->obj);
  h->obj = r;
  return 0;
}

int MXTPUSymbolListArguments(void* handle, char* buf, int cap,
        int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(handle);
  PyObject* r = call_graph("sym_list_arguments",
                           Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("MXTPUSymbolListArguments");
  return str_out(r, buf, cap, needed, "MXTPUSymbolListArguments");
}

int MXTPUSymbolListOutputs(void* handle, char* buf, int cap,
        int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(handle);
  PyObject* r = call_graph("sym_list_outputs", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("MXTPUSymbolListOutputs");
  return str_out(r, buf, cap, needed, "MXTPUSymbolListOutputs");
}

// ≙ MXSymbolSaveToJSON
int MXTPUSymbolToJSON(void* handle, char* buf, int cap,
        int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(handle);
  PyObject* r = call_graph("sym_tojson", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("MXTPUSymbolToJSON");
  return str_out(r, buf, cap, needed, "MXTPUSymbolToJSON");
}

int MXTPUSymbolFree(void* handle) {
  Gil gil;
  auto* h = static_cast<SymHandle*>(handle);
  if (gil.ok) Py_XDECREF(h->obj);
  delete h;
  return 0;
}

// ≙ MXExecutorSimpleBindEx: shapes as a JSON object {"name": [dims...]}
int MXTPUExecutorSimpleBind(void* sym, const char* shapes_json,
                            const char* grad_req, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(sym);
  PyObject* r = call_graph("executor_simple_bind",
                           Py_BuildValue("(Oss)", h->obj, shapes_json,
                                         grad_req));
  if (!r) return fail_py("MXTPUExecutorSimpleBind");
  *out = new SymHandle{r};
  return 0;
}

// ≙ MXExecutorForward (+ the feed: names/arrays pairs bind data vars)
int MXTPUExecutorForward(void* ex, int is_train, int n, const char** names,
                         void** nd_handles) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(ex);
  PyObject* kl = PyList_New(n);
  PyObject* al = PyList_New(n);
  if (!kl || !al) {
    Py_XDECREF(kl);
    Py_XDECREF(al);
    return fail_py("MXTPUExecutorForward");
  }
  for (int i = 0; i < n; ++i) {
    PyList_SET_ITEM(kl, i, PyUnicode_FromString(names[i]));
    PyObject* a = static_cast<NDHandle*>(nd_handles[i])->arr;
    Py_INCREF(a);
    PyList_SET_ITEM(al, i, a);
  }
  // N-format only steals kl/al on SUCCESS; drop them ourselves on failure
  PyObject* tup = Py_BuildValue("(OiNN)", h->obj, is_train, kl, al);
  if (!tup) {
    Py_DECREF(kl);
    Py_DECREF(al);
    return fail_py("MXTPUExecutorForward");
  }
  PyObject* r = call_graph("executor_forward", tup);
  if (!r) return fail_py("MXTPUExecutorForward");
  Py_DECREF(r);
  return 0;
}

int MXTPUExecutorNumOutputs(void* ex, int* out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(ex);
  PyObject* r = call_graph("executor_num_outputs",
                           Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("MXTPUExecutorNumOutputs");
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

// ≙ MXExecutorOutputs — returns a new ND handle usable with the ND ABI
int MXTPUExecutorOutput(void* ex, int index, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(ex);
  PyObject* r = call_graph("executor_output",
                           Py_BuildValue("(Oi)", h->obj, index));
  if (!r) return fail_py("MXTPUExecutorOutput");
  *out = new NDHandle{r};
  return 0;
}

// ≙ MXExecutorBackwardEx (head_grads NULL/0 = ones like the reference)
int MXTPUExecutorBackward(void* ex, int n, void** head_grads) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(ex);
  PyObject* hl = PyList_New(n);
  if (!hl) return fail_py("MXTPUExecutorBackward");
  for (int i = 0; i < n; ++i) {
    PyObject* a = static_cast<NDHandle*>(head_grads[i])->arr;
    Py_INCREF(a);
    PyList_SET_ITEM(hl, i, a);
  }
  PyObject* r = call_graph("executor_backward",
                           Py_BuildValue("(ON)", h->obj, hl));
  if (!r) return fail_py("MXTPUExecutorBackward");
  Py_DECREF(r);
  return 0;
}

// Bound argument array by name (read/update via the ND ABI; updates are
// seen by the next forward — the executor reads args at call time).
int MXTPUExecutorArg(void* ex, const char* name, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(ex);
  PyObject* r = call_graph("executor_arg",
                           Py_BuildValue("(Os)", h->obj, name));
  if (!r) return fail_py("MXTPUExecutorArg");
  *out = new NDHandle{r};
  return 0;
}

// ≙ the grad arrays MXExecutorSimpleBindEx returns
int MXTPUExecutorArgGrad(void* ex, const char* name, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(ex);
  PyObject* r = call_graph("executor_arg_grad",
                           Py_BuildValue("(Os)", h->obj, name));
  if (!r) return fail_py("MXTPUExecutorArgGrad");
  *out = new NDHandle{r};
  return 0;
}

int MXTPUExecutorFree(void* handle) { return MXTPUSymbolFree(handle); }

}  // extern "C"

// ---------------------------------------------------------------------------
// Extended tier (ref include/mxnet/c_api.h MXKVStore* (~30 fns), MXProfile*,
// MXNDArraySave/Load, MXSymbolInferShape, MXListAllOpNames, MXRandomSeed,
// MXLoadLib regions): kvstore init/push/pull/broadcast from C, profiler
// control, NDArray file io, shape inference, op-registry listing, custom-op
// library loading. Dispatch through native/_ext_embed.py; arrays ride the
// existing ND ABI handles, symbols the graph-slice handles.
// ---------------------------------------------------------------------------

namespace {

PyObject* ext_module() {
  static PyObject* mod = nullptr;
  if (!mod)
    mod = PyImport_ImportModule("incubator_mxnet_tpu.native._ext_embed");
  return mod;
}

// STEALS args (delegates to the shared stealing-call helper).
PyObject* call_ext(const char* fn, PyObject* args) {
  return call_stealing(ext_module, fn, args);
}

// int keys -> new PyList
PyObject* int_list(const int* keys, int n) {
  PyObject* l = PyList_New(n);
  if (!l) return nullptr;
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(l, i, PyLong_FromLong(keys[i]));
  return l;
}

// ND handles -> new PyList of borrowed-then-increfed arrs
PyObject* nd_list(void** handles, int n) {
  PyObject* l = PyList_New(n);
  if (!l) return nullptr;
  for (int i = 0; i < n; ++i) {
    PyObject* a = static_cast<NDHandle*>(handles[i])->arr;
    Py_INCREF(a);
    PyList_SET_ITEM(l, i, a);
  }
  return l;
}

int call_ext_void(const char* fn, PyObject* args, const char* where) {
  PyObject* r = call_ext(fn, args);
  if (!r) return fail_py(where);
  Py_DECREF(r);
  return 0;
}

}  // namespace

extern "C" {

// ------------------------------------------------------- NDArray save/load
// ≙ MXNDArraySave (names may be NULL / empty strings for a positional list)
int MXTPUNDArraySave(const char* fname, int n, void** nd_handles,
                     const char** names) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* kl = PyList_New(n);
  PyObject* al = nd_list(nd_handles, n);
  if (!kl || !al) {
    Py_XDECREF(kl);
    Py_XDECREF(al);
    return fail_py("MXTPUNDArraySave");
  }
  for (int i = 0; i < n; ++i) {
    PyObject* s = PyUnicode_FromString(names && names[i] ? names[i] : "");
    if (!s) {  // invalid UTF-8 etc. — error out, never store a NULL slot
      Py_DECREF(kl);
      Py_DECREF(al);
      return fail_py("MXTPUNDArraySave");
    }
    PyList_SET_ITEM(kl, i, s);
  }
  PyObject* tup = Py_BuildValue("(sNN)", fname, kl, al);
  if (!tup) {
    Py_DECREF(kl);
    Py_DECREF(al);
    return fail_py("MXTPUNDArraySave");
  }
  return call_ext_void("nd_save", tup, "MXTPUNDArraySave");
}

// ≙ MXNDArrayLoad: returns an opaque bundle; read items out, then free it.
int MXTPUNDArrayLoad(const char* fname, void** out_bundle, int* out_count) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* r = call_ext("nd_load_bundle", Py_BuildValue("(s)", fname));
  if (!r) return fail_py("MXTPUNDArrayLoad");
  PyObject* n = call_ext("bundle_len", Py_BuildValue("(O)", r));
  if (!n) {
    Py_DECREF(r);
    return fail_py("MXTPUNDArrayLoad");
  }
  *out_count = (int)PyLong_AsLong(n);
  Py_DECREF(n);
  *out_bundle = new SymHandle{r};  // opaque PyObject carrier
  return 0;
}

// name of item i (empty string for positional lists)
int MXTPUNDArrayLoadName(void* bundle, int index, char* buf, int cap,
                         int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(bundle);
  PyObject* r = call_ext("bundle_name", Py_BuildValue("(Oi)", h->obj, index));
  if (!r) return fail_py("MXTPUNDArrayLoadName");
  return str_out(r, buf, cap, needed, "MXTPUNDArrayLoadName");
}

// item i as a NEW ND handle usable with the whole ND ABI
int MXTPUNDArrayLoadItem(void* bundle, int index, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(bundle);
  PyObject* r = call_ext("bundle_item", Py_BuildValue("(Oi)", h->obj, index));
  if (!r) return fail_py("MXTPUNDArrayLoadItem");
  *out = new NDHandle{r};
  return 0;
}

int MXTPUNDArrayLoadFree(void* bundle) { return MXTPUSymbolFree(bundle); }

// ------------------------------------------------------------------ Symbol
// ≙ MXSymbolCreateFromJSON
int MXTPUSymbolCreateFromJSON(const char* json_str, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* r = call_ext("sym_from_json", Py_BuildValue("(s)", json_str));
  if (!r) return fail_py("MXTPUSymbolCreateFromJSON");
  *out = new SymHandle{r};
  return 0;
}

// ≙ MXSymbolSaveToFile
int MXTPUSymbolSaveToFile(void* sym, const char* fname) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(sym);
  return call_ext_void("sym_save_file",
                       Py_BuildValue("(Os)", h->obj, fname),
                       "MXTPUSymbolSaveToFile");
}

// ≙ MXSymbolListAuxiliaryStates (JSON list out)
int MXTPUSymbolListAuxiliaryStates(void* sym, char* buf, int cap,
                                   int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(sym);
  PyObject* r = call_ext("sym_list_aux", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("MXTPUSymbolListAuxiliaryStates");
  return str_out(r, buf, cap, needed, "MXTPUSymbolListAuxiliaryStates");
}

// ≙ MXSymbolInferShape: shapes_json {"name": [dims]} in; JSON
// {"arg_shapes": [...], "out_shapes": [...], "aux_shapes": [...]} out.
int MXTPUSymbolInferShape(void* sym, const char* shapes_json, char* buf,
                          int cap, int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(sym);
  PyObject* r = call_ext("sym_infer_shape",
                         Py_BuildValue("(Os)", h->obj, shapes_json));
  if (!r) return fail_py("MXTPUSymbolInferShape");
  return str_out(r, buf, cap, needed, "MXTPUSymbolInferShape");
}

// ≙ MXSymbolGetAttr / MXSymbolSetAttr
int MXTPUSymbolGetAttr(void* sym, const char* key, char* buf, int cap,
                       int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(sym);
  PyObject* r = call_ext("sym_get_attr", Py_BuildValue("(Os)", h->obj, key));
  if (!r) return fail_py("MXTPUSymbolGetAttr");
  return str_out(r, buf, cap, needed, "MXTPUSymbolGetAttr");
}

int MXTPUSymbolSetAttr(void* sym, const char* key, const char* value) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(sym);
  return call_ext_void("sym_set_attr",
                       Py_BuildValue("(Oss)", h->obj, key, value),
                       "MXTPUSymbolSetAttr");
}

// ----------------------------------------------------------------- KVStore
// ≙ MXKVStoreCreate / MXKVStoreFree / MXKVStoreGetType / MXKVStoreGetRank /
//   MXKVStoreGetGroupSize
int MXTPUKVStoreCreate(const char* type, void** out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* r = call_ext("kv_create", Py_BuildValue("(s)", type));
  if (!r) return fail_py("MXTPUKVStoreCreate");
  *out = new SymHandle{r};
  return 0;
}

int MXTPUKVStoreFree(void* kv) { return MXTPUSymbolFree(kv); }

int MXTPUKVStoreGetType(void* kv, char* buf, int cap, int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(kv);
  PyObject* r = call_ext("kv_type", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("MXTPUKVStoreGetType");
  return str_out(r, buf, cap, needed, "MXTPUKVStoreGetType");
}

int MXTPUKVStoreGetRank(void* kv, int* out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(kv);
  PyObject* r = call_ext("kv_rank", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("MXTPUKVStoreGetRank");
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int MXTPUKVStoreGetGroupSize(void* kv, int* out) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(kv);
  PyObject* r = call_ext("kv_num_workers", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("MXTPUKVStoreGetGroupSize");
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

namespace {

// shared body for init/push/pull-style (kv, keys, arrays) calls
int kv_keys_arrays(const char* fn, const char* where, void* kv, int n,
                   const int* keys, void** nd_handles, PyObject* extra) {
  auto* h = static_cast<SymHandle*>(kv);
  PyObject* kl = int_list(keys, n);
  PyObject* al = nd_list(nd_handles, n);
  if (!kl || !al) {
    Py_XDECREF(kl);
    Py_XDECREF(al);
    Py_XDECREF(extra);
    return fail_py(where);
  }
  PyObject* tup = extra ? Py_BuildValue("(ONNN)", h->obj, kl, al, extra)
                        : Py_BuildValue("(ONN)", h->obj, kl, al);
  if (!tup) {
    Py_DECREF(kl);
    Py_DECREF(al);
    Py_XDECREF(extra);
    return fail_py(where);
  }
  return call_ext_void(fn, tup, where);
}

}  // namespace

// ≙ MXKVStoreInit / MXKVStorePush / MXKVStorePull (int keys)
int MXTPUKVStoreInit(void* kv, int n, const int* keys, void** nd_handles) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return kv_keys_arrays("kv_init", "MXTPUKVStoreInit", kv, n, keys,
                        nd_handles, nullptr);
}

int MXTPUKVStorePush(void* kv, int n, const int* keys, void** nd_handles,
                     int priority) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return kv_keys_arrays("kv_push", "MXTPUKVStorePush", kv, n, keys,
                        nd_handles, PyLong_FromLong(priority));
}

// pull writes INTO the passed handles (their buffers are rebound)
int MXTPUKVStorePull(void* kv, int n, const int* keys, void** nd_handles) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return kv_keys_arrays("kv_pull", "MXTPUKVStorePull", kv, n, keys,
                        nd_handles, nullptr);
}

namespace {

// shared body for (kv, keys, values, outs) two-list calls
int kv_keys_two_lists(const char* fn, const char* where, void* kv, int n,
                      const int* keys, void** values, void** outs) {
  auto* h = static_cast<SymHandle*>(kv);
  PyObject* kl = int_list(keys, n);
  PyObject* vl = nd_list(values, n);
  PyObject* ol = nd_list(outs, n);
  if (!kl || !vl || !ol) {
    Py_XDECREF(kl);
    Py_XDECREF(vl);
    Py_XDECREF(ol);
    return fail_py(where);
  }
  PyObject* tup = Py_BuildValue("(ONNN)", h->obj, kl, vl, ol);
  if (!tup) {
    Py_DECREF(kl);
    Py_DECREF(vl);
    Py_DECREF(ol);
    return fail_py(where);
  }
  return call_ext_void(fn, tup, where);
}

}  // namespace

// ≙ MXKVStorePushPull: values pushed, outs pulled, one call
int MXTPUKVStorePushPull(void* kv, int n, const int* keys, void** values,
                         void** outs) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return kv_keys_two_lists("kv_pushpull", "MXTPUKVStorePushPull", kv, n,
                           keys, values, outs);
}

// ≙ MXKVStoreBroadcast
int MXTPUKVStoreBroadcast(void* kv, int n, const int* keys, void** values,
                          void** outs) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return kv_keys_two_lists("kv_broadcast", "MXTPUKVStoreBroadcast", kv, n,
                           keys, values, outs);
}

// ≙ MXKVStoreSetGradientCompression (params as JSON object string)
int MXTPUKVStoreSetGradientCompression(void* kv, const char* params_json) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  auto* h = static_cast<SymHandle*>(kv);
  return call_ext_void("kv_set_compression",
                       Py_BuildValue("(Os)", h->obj, params_json),
                       "MXTPUKVStoreSetGradientCompression");
}

// ---------------------------------------------------------------- Profiler
// ≙ MXSetProcessProfilerConfig (kwargs as JSON object string)
int MXTPUProfilerSetConfig(const char* params_json) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return call_ext_void("profiler_set_config",
                       Py_BuildValue("(s)", params_json),
                       "MXTPUProfilerSetConfig");
}

// ≙ MXSetProcessProfilerState ("run"/"stop")
int MXTPUProfilerSetState(const char* state) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return call_ext_void("profiler_set_state", Py_BuildValue("(s)", state),
                       "MXTPUProfilerSetState");
}

// ≙ MXDumpProcessProfile
int MXTPUProfilerDump(int finished) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return call_ext_void("profiler_dump", Py_BuildValue("(i)", finished),
                       "MXTPUProfilerDump");
}

// ≙ MXAggregateProfileStatsPrint (table string out)
int MXTPUProfilerGetSummary(char* buf, int cap, int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* r = call_ext("profiler_summary", Py_BuildValue("()"));
  if (!r) return fail_py("MXTPUProfilerGetSummary");
  return str_out(r, buf, cap, needed, "MXTPUProfilerGetSummary");
}

// -------------------------------------------------------------------- misc
// ≙ MXRandomSeed
int MXTPURandomSeed(int seed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return call_ext_void("random_seed", Py_BuildValue("(i)", seed),
                       "MXTPURandomSeed");
}

// ≙ MXListAllOpNames (JSON list out)
int MXTPUListAllOpNames(char* buf, int cap, int64_t* needed) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  PyObject* r = call_ext("list_all_op_names", Py_BuildValue("()"));
  if (!r) return fail_py("MXTPUListAllOpNames");
  return str_out(r, buf, cap, needed, "MXTPUListAllOpNames");
}

// ≙ MXLoadLib: register a user custom-op extension library/module
int MXTPULoadLib(const char* path) {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return call_ext_void("load_lib", Py_BuildValue("(s)", path),
                       "MXTPULoadLib");
}

// ≙ MXNDArrayWaitAll
int MXTPUNDArrayWaitAll() {
  Gil gil;
  if (!gil.ok) return fail("python init failed: " + g_init_err);
  return call_ext_void("wait_all", Py_BuildValue("()"),
                       "MXTPUNDArrayWaitAll");
}

}  // extern "C"
