// Native image pipeline: JPEG decode + augmentation + batch assembly in C++
// worker threads — the TPU-native equivalent of the reference's OpenMP decode
// team (ref src/io/iter_image_recordio_2.cc:51 ImageRecordIOParser2 and
// image_aug_default.cc DefaultImageAugmenter): no Python/GIL in the decode
// loop. Batches are assembled as NCHW float32 host tensors ready for a
// single device_put.
//
// Record payload layout is dmlc image-recordio (ref src/io/image_recordio.h):
//   uint32 flag; float label; uint64 id; uint64 id2;   (24-byte IRHeader)
//   [flag > 0: flag x float extra labels]
//   JPEG bytes.
#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>

extern "C" long rio_scan(const char* path, int64_t* offsets, int64_t* lengths,
                         long cap);

namespace {

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  auto* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// Decode JPEG bytes to tightly-packed RGB8. Returns false on corrupt input.
bool decode_jpeg(const uint8_t* buf, size_t len, std::vector<uint8_t>* out,
                 int* w, int* h, int min_side_hint) {
  if (len < 2 || buf[0] != 0xFF || buf[1] != 0xD8) return false;  // not JPEG
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  // DCT-domain downscale: pick the largest 1/1..1/8 factor that keeps the
  // short side >= the target (fast path of the reference's resize augmenter)
  if (min_side_hint > 0) {
    int short_side = std::min((int)cinfo.image_width, (int)cinfo.image_height);
    int denom = 1;
    while (denom < 8 && short_side / (denom * 2) >= min_side_hint) denom *= 2;
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
  }
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize((size_t)(*w) * (*h) * 3);
  std::vector<uint8_t> row((size_t)(*w) * cinfo.output_components);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* dst = out->data() + (size_t)cinfo.output_scanline * (*w) * 3;
    if (cinfo.output_components == 3) {
      JSAMPROW r = dst;
      jpeg_read_scanlines(&cinfo, &r, 1);
    } else {  // grayscale -> replicate
      JSAMPROW r = row.data();
      jpeg_read_scanlines(&cinfo, &r, 1);
      for (int x = 0; x < *w; ++x)
        dst[3 * x] = dst[3 * x + 1] = dst[3 * x + 2] = row[x];
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear resize RGB8 (ref image_aug_default.cc resize via cv::resize).
void resize_bilinear(const uint8_t* src, int sw, int sh, uint8_t* dst, int dw,
                     int dh) {
  const float fx = (float)sw / dw, fy = (float)sh / dh;
  for (int y = 0; y < dh; ++y) {
    float syf = (y + 0.5f) * fy - 0.5f;
    int sy = (int)std::floor(syf);
    float wy = syf - sy;
    int sy0 = std::max(0, std::min(sy, sh - 1));
    int sy1 = std::max(0, std::min(sy + 1, sh - 1));
    for (int x = 0; x < dw; ++x) {
      float sxf = (x + 0.5f) * fx - 0.5f;
      int sx = (int)std::floor(sxf);
      float wx = sxf - sx;
      int sx0 = std::max(0, std::min(sx, sw - 1));
      int sx1 = std::max(0, std::min(sx + 1, sw - 1));
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(sy0 * sw + sx0) * 3 + c];
        float v01 = src[(sy0 * sw + sx1) * 3 + c];
        float v10 = src[(sy1 * sw + sx0) * 3 + c];
        float v11 = src[(sy1 * sw + sx1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(y * dw + x) * 3 + c] = (uint8_t)(v + 0.5f);
      }
    }
  }
}

struct ImgBatch {
  std::vector<float> data;    // N*C*H*W
  std::vector<float> labels;  // N*label_width
  long seq;
  int bad;                    // count of undecodable records
};

struct ImgPipe {
  std::string path;
  std::vector<int64_t> offsets, lengths;
  std::vector<long> order;
  long batch_size;
  int H, W, label_width;
  int resize_short;           // 0 = resize directly to (H,W)
  int rand_crop, rand_mirror;
  float mean[3], std[3], scale;
  bool shuffle;
  std::mt19937 rng;
  long n_batches;

  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::map<long, ImgBatch*> ready;
  long next_consume = 0, next_produce = 0;
  long epoch = 0;  // bumped by reset(); stale in-flight batches are discarded
  long max_ready;
  bool stop = false;
  std::vector<std::thread> workers;

  ~ImgPipe() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_ready.notify_all();
    cv_space.notify_all();
    for (auto& t : workers) t.join();
    for (auto& kv : ready) delete kv.second;
  }
};

void pipe_worker(ImgPipe* p, unsigned tseed) {
  FILE* f = fopen(p->path.c_str(), "rb");
  if (!f) return;
  std::mt19937 rng(tseed);
  std::vector<uint8_t> raw, rgb, resized;
  std::vector<long> idxs;
  while (true) {
    long seq, epoch;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      p->cv_space.wait(lk, [&] {
        return p->stop || (p->next_produce < p->n_batches &&
                           (long)p->ready.size() < p->max_ready + 1);
      });
      if (p->stop) break;
      seq = p->next_produce++;
      epoch = p->epoch;
      // snapshot record indices under the lock: reset() may reshuffle
      // p->order concurrently with decode
      idxs.resize(p->batch_size);
      long n = (long)p->order.size();
      for (long j = 0; j < p->batch_size; ++j)
        idxs[j] = p->order[(seq * p->batch_size + j) % n];
    }
    auto* b = new ImgBatch();
    b->seq = seq;
    b->bad = 0;
    const long plane = (long)p->H * p->W;
    b->data.assign((size_t)p->batch_size * 3 * plane, 0.f);
    b->labels.assign((size_t)p->batch_size * p->label_width, 0.f);
    for (long j = 0; j < p->batch_size; ++j) {
      long idx = idxs[j];
      int64_t len = p->lengths[idx];
      raw.resize(len);
      fseek(f, p->offsets[idx] + 8, SEEK_SET);
      if (fread(raw.data(), 1, len, f) != (size_t)len || len < 24) {
        b->bad++;
        continue;
      }
      uint32_t flag;
      float label;
      memcpy(&flag, raw.data(), 4);
      memcpy(&label, raw.data() + 4, 4);
      size_t off = 24;
      float* lab_dst = b->labels.data() + (size_t)j * p->label_width;
      if (flag == 0) {
        lab_dst[0] = label;
      } else {
        // extra-label section must fit inside the record
        if ((int64_t)(24 + (uint64_t)4 * flag) >= len) {
          b->bad++;
          continue;
        }
        for (uint32_t k = 0; k < flag && k < (uint32_t)p->label_width; ++k)
          memcpy(lab_dst + k, raw.data() + off + 4 * k, 4);
        off += (size_t)4 * flag;
      }
      int w = 0, h = 0;
      int hint = p->resize_short > 0 ? p->resize_short : std::min(p->H, p->W);
      if (!decode_jpeg(raw.data() + off, (size_t)(len - (int64_t)off), &rgb, &w,
                       &h, hint)) {
        b->bad++;
        continue;
      }
      // resize: short side to resize_short (keep aspect); with no resize,
      // rand_crop windows the (possibly DCT-downscaled) source directly,
      // else resize straight to HxW
      int rw, rh;
      if (p->resize_short > 0) {
        if (w < h) {
          rw = p->resize_short;
          rh = (int)((int64_t)h * p->resize_short / w);
        } else {
          rh = p->resize_short;
          rw = (int)((int64_t)w * p->resize_short / h);
        }
      } else if (p->rand_crop && w >= p->W && h >= p->H) {
        rw = w;
        rh = h;
      } else {
        rw = p->W;
        rh = p->H;
      }
      const uint8_t* img;
      if (rw == w && rh == h) {
        img = rgb.data();
      } else {
        resized.resize((size_t)rw * rh * 3);
        resize_bilinear(rgb.data(), w, h, resized.data(), rw, rh);
        img = resized.data();
      }
      // crop to (H, W): random if rand_crop else center
      int cx = std::max(0, (rw - p->W)), cy = std::max(0, (rh - p->H));
      int x0, y0;
      if (p->rand_crop) {
        x0 = cx ? (int)(rng() % (cx + 1)) : 0;
        y0 = cy ? (int)(rng() % (cy + 1)) : 0;
      } else {
        x0 = cx / 2;
        y0 = cy / 2;
      }
      bool mirror = p->rand_mirror && (rng() & 1);
      float* dst = b->data.data() + (size_t)j * 3 * plane;
      for (int y = 0; y < p->H && y + y0 < rh; ++y) {
        for (int x = 0; x < p->W && x + x0 < rw; ++x) {
          int sx = mirror ? (std::min(rw - 1, x0 + p->W - 1) - x) : (x0 + x);
          const uint8_t* px = img + ((size_t)(y0 + y) * rw + sx) * 3;
          for (int c = 0; c < 3; ++c)
            dst[(size_t)c * plane + (size_t)y * p->W + x] =
                (px[c] - p->mean[c]) * p->scale / p->std[c];
        }
      }
    }
    {
      std::lock_guard<std::mutex> lk(p->mu);
      if (epoch == p->epoch) {
        p->ready[seq] = b;
      } else {
        delete b;  // produced for a pre-reset epoch; discard
        b = nullptr;
      }
    }
    p->cv_ready.notify_all();
  }
  fclose(f);
}

}  // namespace

extern "C" {

void* img_pipe_create(const char* path, long batch_size, int h, int w,
                      int label_width, int resize_short, int rand_crop,
                      int rand_mirror, const float* mean_rgb,
                      const float* std_rgb, float scale, int shuffle, int seed,
                      int num_threads, long max_ready, long part_index,
                      long num_parts) {
  auto* p = new ImgPipe();
  p->path = path;
  long n = rio_scan(path, nullptr, nullptr, 0);
  if (n <= 0) {
    delete p;
    return nullptr;
  }
  p->offsets.resize(n);
  p->lengths.resize(n);
  rio_scan(path, p->offsets.data(), p->lengths.data(), n);
  long shard = n / num_parts;
  long lo = part_index * shard;
  long hi = (part_index == num_parts - 1) ? n : lo + shard;
  for (long i = lo; i < hi; ++i) p->order.push_back(i);
  p->batch_size = batch_size;
  p->H = h;
  p->W = w;
  p->label_width = label_width > 0 ? label_width : 1;
  p->resize_short = resize_short;
  p->rand_crop = rand_crop;
  p->rand_mirror = rand_mirror;
  for (int c = 0; c < 3; ++c) {
    p->mean[c] = mean_rgb ? mean_rgb[c] : 0.f;
    p->std[c] = (std_rgb && std_rgb[c] != 0.f) ? std_rgb[c] : 1.f;
  }
  p->scale = scale != 0.f ? scale : 1.f;
  p->shuffle = shuffle != 0;
  p->rng.seed(seed);
  if (p->shuffle) std::shuffle(p->order.begin(), p->order.end(), p->rng);
  p->n_batches = (long)(p->order.size() + batch_size - 1) / batch_size;
  p->max_ready = max_ready > 0 ? max_ready : 4;
  int nt = num_threads > 0 ? num_threads : 4;
  for (int i = 0; i < nt; ++i)
    p->workers.emplace_back(pipe_worker, p, (unsigned)(seed * 9973 + i));
  return p;
}

long img_pipe_num_batches(void* h) {
  return static_cast<ImgPipe*>(h)->n_batches;
}

long img_pipe_num_records(void* h) {
  return (long)static_cast<ImgPipe*>(h)->order.size();
}

// Copies the next batch into out_data (N*3*H*W floats) and out_labels
// (N*label_width floats). Returns #bad (undecodable) records, or -1 at
// epoch end.
long img_pipe_next(void* h, float* out_data, float* out_labels) {
  auto* p = static_cast<ImgPipe*>(h);
  ImgBatch* b = nullptr;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    if (p->next_consume >= p->n_batches) return -1;
    long want = p->next_consume;
    p->cv_ready.wait(lk, [&] { return p->stop || p->ready.count(want); });
    if (p->stop) return -1;
    b = p->ready[want];
    p->ready.erase(want);
    p->next_consume++;
  }
  p->cv_space.notify_all();
  memcpy(out_data, b->data.data(), b->data.size() * sizeof(float));
  memcpy(out_labels, b->labels.data(), b->labels.size() * sizeof(float));
  long bad = b->bad;
  delete b;
  return bad;
}

void img_pipe_reset(void* h, int reshuffle) {
  auto* p = static_cast<ImgPipe*>(h);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    for (auto& kv : p->ready) delete kv.second;
    p->ready.clear();
    p->next_consume = 0;
    p->next_produce = 0;
    p->epoch++;  // in-flight worker batches from the old epoch get discarded
    if (reshuffle && p->shuffle)
      std::shuffle(p->order.begin(), p->order.end(), p->rng);
  }
  p->cv_space.notify_all();
}

void img_pipe_destroy(void* h) { delete static_cast<ImgPipe*>(h); }

}  // extern "C"
