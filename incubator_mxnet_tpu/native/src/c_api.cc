// Flat C ABI — the first slice of the reference's c_api surface
// (ref include/mxnet/c_api.h: MXNDArrayCreate*, MXNDArraySyncCopyFromCPU,
// MXNDArraySyncCopyToCPU, MXNDArrayGetShape, MXNDArrayFree, MXDataIter*;
// error convention ref c_api_error.cc MXGetLastError).
//
// Scope decision (SURVEY §2.1): host-side array staging + native data
// iterators live behind this ABI so language bindings and the predict API
// have a stable flat surface; DEVICE arrays remain PJRT/JAX-owned by
// design — the ABI hands off contiguous host buffers, and the Python layer
// device_puts them (one copy, same as the reference's CPU->GPU path).
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* img_pipe_create(const char*, long, int, int, int, int, int, int,
                      const float*, const float*, float, int, int, int, long,
                      long, long);
long img_pipe_num_batches(void*);
long img_pipe_next(void*, float*, float*);
void img_pipe_reset(void*, int);
void img_pipe_destroy(void*);
}

namespace {
thread_local std::string g_last_error;

int fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

// dtype codes follow mshadow (ref 3rdparty/mshadow/mshadow/base.h:334-346)
size_t dtype_size(int dtype) {
  switch (dtype) {
    case 0: return 4;   // float32
    case 1: return 8;   // float64
    case 2: return 2;   // float16
    case 3: return 1;   // uint8
    case 4: return 4;   // int32
    case 5: return 1;   // int8
    case 6: return 8;   // int64
    case 7: return 1;   // bool
    case 12: return 2;  // bfloat16
    default: return 0;
  }
}

struct HostArray {
  std::vector<int64_t> shape;
  int dtype;
  std::vector<uint8_t> data;
  size_t nbytes() const {
    size_t n = dtype_size(dtype);
    for (auto d : shape) n *= (size_t)d;
    return n;
  }
};

struct IterHandle {
  void* pipe;
  long batch, h, w, label_width;
  HostArray data, label;
  long last_bad = -2;  // -2 = before first next
};
}  // namespace

extern "C" {

const char* MXTPUGetLastError() { return g_last_error.c_str(); }

int MXTPUNDArrayCreate(const int64_t* shape, int ndim, int dtype,
                       void** out) {
  if (ndim < 0 || !dtype_size(dtype)) return fail("bad ndim/dtype");
  auto* a = new HostArray();
  a->shape.assign(shape, shape + ndim);
  a->dtype = dtype;
  a->data.resize(a->nbytes());
  *out = a;
  return 0;
}

int MXTPUNDArraySyncCopyFromCPU(void* handle, const void* data,
                                size_t nbytes) {
  auto* a = static_cast<HostArray*>(handle);
  if (nbytes != a->nbytes())
    return fail("size mismatch: got " + std::to_string(nbytes) + ", want " +
                std::to_string(a->nbytes()));
  memcpy(a->data.data(), data, nbytes);
  return 0;
}

int MXTPUNDArraySyncCopyToCPU(void* handle, void* data, size_t nbytes) {
  auto* a = static_cast<HostArray*>(handle);
  if (nbytes != a->nbytes())
    return fail("size mismatch: got " + std::to_string(nbytes) + ", want " +
                std::to_string(a->nbytes()));
  memcpy(data, a->data.data(), nbytes);
  return 0;
}

int MXTPUNDArrayGetShape(void* handle, int* out_ndim, int64_t* out_shape) {
  auto* a = static_cast<HostArray*>(handle);
  *out_ndim = (int)a->shape.size();
  if (out_shape)
    for (size_t i = 0; i < a->shape.size(); ++i) out_shape[i] = a->shape[i];
  return 0;
}

int MXTPUNDArrayGetDType(void* handle, int* out_dtype) {
  *out_dtype = static_cast<HostArray*>(handle)->dtype;
  return 0;
}

int MXTPUNDArrayGetData(void* handle, void** out_ptr) {
  *out_ptr = static_cast<HostArray*>(handle)->data.data();
  return 0;
}

int MXTPUNDArrayFree(void* handle) {
  delete static_cast<HostArray*>(handle);
  return 0;
}

// ------------------------------------------------------------- data iter
// (ref c_api.h MXDataIterCreateIter family, specialized to ImageRecordIter)
int MXTPUImageRecordIterCreate(const char* rec_path, long batch_size, int h,
                               int w, int label_width, int resize_short,
                               int rand_crop, int rand_mirror,
                               const float* mean_rgb, const float* std_rgb,
                               float scale, int shuffle, int seed,
                               int num_threads, long part_index,
                               long num_parts, void** out) {
  void* pipe = img_pipe_create(rec_path, batch_size, h, w, label_width,
                               resize_short, rand_crop, rand_mirror, mean_rgb,
                               std_rgb, scale, shuffle, seed, num_threads, 4,
                               part_index, num_parts);
  if (!pipe) return fail(std::string("cannot open record file ") + rec_path);
  auto* it = new IterHandle();
  it->pipe = pipe;
  it->batch = batch_size;
  it->h = h;
  it->w = w;
  it->label_width = label_width > 0 ? label_width : 1;
  it->data.shape = {batch_size, 3, h, w};
  it->data.dtype = 0;
  it->data.data.resize(it->data.nbytes());
  it->label.shape = {batch_size, it->label_width};
  it->label.dtype = 0;
  it->label.data.resize(it->label.nbytes());
  *out = it;
  return 0;
}

// Advances; returns 1 with data ready, 0 at epoch end.
int MXTPUDataIterNext(void* handle, int* out_has_next) {
  auto* it = static_cast<IterHandle*>(handle);
  long bad = img_pipe_next(it->pipe, (float*)it->data.data.data(),
                           (float*)it->label.data.data());
  it->last_bad = bad;
  *out_has_next = bad >= 0 ? 1 : 0;
  return 0;
}

int MXTPUDataIterGetData(void* handle, void** out_array) {
  *out_array = &static_cast<IterHandle*>(handle)->data;
  return 0;
}

int MXTPUDataIterGetLabel(void* handle, void** out_array) {
  *out_array = &static_cast<IterHandle*>(handle)->label;
  return 0;
}

int MXTPUDataIterGetBadCount(void* handle, long* out_bad) {
  *out_bad = static_cast<IterHandle*>(handle)->last_bad;
  return 0;
}

int MXTPUDataIterReset(void* handle, int reshuffle) {
  auto* it = static_cast<IterHandle*>(handle);
  img_pipe_reset(it->pipe, reshuffle);
  return 0;
}

int MXTPUDataIterFree(void* handle) {
  auto* it = static_cast<IterHandle*>(handle);
  img_pipe_destroy(it->pipe);
  delete it;
  return 0;
}

int mxtpu_capi_abi_version() { return 1; }

}  // extern "C"
