// Native RecordIO + prefetching batch reader + pooled host allocator.
//
// TPU-native equivalents of the reference's native data path:
//  - RecordIO framing      (ref src/io/image_recordio.h, dmlc recordio):
//    [kMagic u32][lrec u32][payload][pad to 4]; lrec = cflag<<29 | len.
//  - Threaded batch reader (ref src/io/iter_image_recordio_2.cc +
//    iter_prefetcher.h): worker threads read record payloads ahead of the
//    consumer through a bounded double-buffered queue; no GIL involvement.
//  - Pooled host allocator (ref src/storage/pooled_storage_manager.h):
//    size-bucketed free lists for staging buffers.
//
// Exposed as a flat C ABI consumed via ctypes (python/native/lib.py).
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

static const uint32_t kMagic = 0xced7230a;

extern "C" {

// ---------------------------------------------------------------- framing
struct RioWriter {
  FILE* f;
};

void* rio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new RioWriter{f};
  return w;
}

long rio_writer_tell(void* h) { return ftell(static_cast<RioWriter*>(h)->f); }

int rio_write(void* h, const char* buf, uint32_t len) {
  FILE* f = static_cast<RioWriter*>(h)->f;
  uint32_t lrec = len;  // cflag 0
  if (fwrite(&kMagic, 4, 1, f) != 1) return -1;
  if (fwrite(&lrec, 4, 1, f) != 1) return -1;
  if (len && fwrite(buf, 1, len, f) != len) return -1;
  uint32_t pad = (4 - (len & 3)) & 3;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && fwrite(zeros, 1, pad, f) != pad) return -1;
  return 0;
}

void rio_writer_close(void* h) {
  auto* w = static_cast<RioWriter*>(h);
  fclose(w->f);
  delete w;
}

// Scan a record file, returning the number of records; offsets/lengths are
// written into caller-provided arrays when non-null (call twice: count, fill).
long rio_scan(const char* path, int64_t* offsets, int64_t* lengths, long cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  long n = 0;
  while (true) {
    long pos = ftell(f);
    uint32_t magic, lrec;
    if (fread(&magic, 4, 1, f) != 1) break;
    if (magic != kMagic) { n = -2; break; }
    if (fread(&lrec, 4, 1, f) != 1) { n = -2; break; }
    uint32_t len = lrec & ((1u << 29) - 1);
    if (offsets && n < cap) offsets[n] = pos;
    if (lengths && n < cap) lengths[n] = len;
    uint32_t pad = (4 - (len & 3)) & 3;
    if (fseek(f, len + pad, SEEK_CUR) != 0) { n = -2; break; }
    n++;
  }
  fclose(f);
  return n;
}

// ---------------------------------------------------------------- allocator
// Size-bucketed pooled allocator (power-of-two rounding like
// GPUPooledRoundedStorageManager, pooled_storage_manager.h:210).
struct HostPool {
  std::mutex mu;
  std::map<size_t, std::vector<void*>> free_list;
  std::atomic<size_t> used{0};
};

static size_t round_pow2(size_t n) {
  size_t p = 4096;
  while (p < n) p <<= 1;
  return p;
}

void* pool_create() { return new HostPool(); }

void* pool_alloc(void* h, size_t size) {
  auto* p = static_cast<HostPool*>(h);
  size_t bucket = round_pow2(size);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    auto it = p->free_list.find(bucket);
    if (it != p->free_list.end() && !it->second.empty()) {
      void* buf = it->second.back();
      it->second.pop_back();
      return buf;
    }
  }
  p->used += bucket;
  return malloc(bucket);
}

void pool_free(void* h, void* buf, size_t size) {
  auto* p = static_cast<HostPool*>(h);
  size_t bucket = round_pow2(size);
  std::lock_guard<std::mutex> lk(p->mu);
  p->free_list[bucket].push_back(buf);
}

size_t pool_used_bytes(void* h) { return static_cast<HostPool*>(h)->used.load(); }

void pool_destroy(void* h) {
  auto* p = static_cast<HostPool*>(h);
  for (auto& kv : p->free_list)
    for (void* b : kv.second) free(b);
  delete p;
}

// ---------------------------------------------------------------- batch reader
// Prefetching batch reader: N worker threads pull batch indices from a work
// queue, read the payloads, and push assembled batches into a bounded ready
// queue (double-buffered handoff, ref iter_prefetcher.h:47).
struct Batch {
  std::vector<char> data;           // concatenated payloads
  std::vector<int64_t> sizes;       // per-record payload size
  long seq;                          // batch sequence number for ordering
};

struct BatchReader {
  std::string path;
  std::vector<int64_t> offsets, lengths;
  std::vector<long> order;
  long batch_size;
  long cursor = 0;              // next batch seq to hand out to workers
  long n_batches;
  bool shuffle;
  std::mt19937 rng;
  int epoch_seed;

  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::map<long, Batch*> ready;   // seq -> batch
  long next_consume = 0;
  long next_produce = 0;
  long epoch = 0;  // bumped by reset(); stale in-flight batches are discarded
  long max_ready;
  bool stop = false;
  std::vector<std::thread> workers;

  ~BatchReader() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_ready.notify_all();
    cv_space.notify_all();
    for (auto& t : workers) t.join();
    for (auto& kv : ready) delete kv.second;
  }
};

static void reader_worker(BatchReader* r) {
  FILE* f = fopen(r->path.c_str(), "rb");
  if (!f) return;
  std::vector<long> idxs;
  while (true) {
    long seq, epoch;
    {
      std::unique_lock<std::mutex> lk(r->mu);
      r->cv_space.wait(lk, [&] {
        return r->stop ||
               (r->next_produce < r->n_batches &&
                (long)r->ready.size() < r->max_ready + 1);
      });
      if (r->stop) break;
      // predicate guarantees next_produce < n_batches here; workers persist
      // across epochs (reset() rewinds next_produce and re-notifies)
      seq = r->next_produce++;
      epoch = r->epoch;
      // snapshot record indices under the lock: reset() may reshuffle
      // r->order concurrently with the reads below
      idxs.resize(r->batch_size);
      long n = (long)r->order.size();
      for (long j = 0; j < r->batch_size; ++j)
        idxs[j] = r->order[(seq * r->batch_size + j) % n];
    }
    auto* b = new Batch();
    b->seq = seq;
    for (long j = 0; j < r->batch_size; ++j) {
      long idx = idxs[j];
      int64_t len = r->lengths[idx];
      size_t off = b->data.size();
      b->data.resize(off + len);
      fseek(f, r->offsets[idx] + 8, SEEK_SET);  // skip magic+lrec
      if (fread(b->data.data() + off, 1, len, f) != (size_t)len) {
        // Truncated/failed record: shrink the data region back so offsets
        // stay aligned with sizes, and surface the error as size -1 so the
        // consumer raises instead of training on an empty payload.
        b->data.resize(off);
        b->sizes.push_back(-1);
        continue;
      }
      b->sizes.push_back(len);
    }
    {
      std::lock_guard<std::mutex> lk(r->mu);
      if (epoch == r->epoch) {
        r->ready[seq] = b;
      } else {
        delete b;  // produced for a pre-reset epoch; discard
        b = nullptr;
      }
    }
    r->cv_ready.notify_all();
  }
  fclose(f);
}

void* rio_reader_create(const char* path, long batch_size, int shuffle,
                        int seed, int num_threads, long max_ready,
                        long part_index, long num_parts) {
  auto* r = new BatchReader();
  r->path = path;
  long n = rio_scan(path, nullptr, nullptr, 0);
  if (n <= 0) {
    delete r;
    return nullptr;
  }
  r->offsets.resize(n);
  r->lengths.resize(n);
  rio_scan(path, r->offsets.data(), r->lengths.data(), n);
  long shard = n / num_parts;
  long lo = part_index * shard;
  long hi = (part_index == num_parts - 1) ? n : lo + shard;
  for (long i = lo; i < hi; ++i) r->order.push_back(i);
  r->batch_size = batch_size;
  r->shuffle = shuffle != 0;
  r->rng.seed(seed);
  if (r->shuffle) std::shuffle(r->order.begin(), r->order.end(), r->rng);
  r->n_batches = (long)(r->order.size() + batch_size - 1) / batch_size;
  r->max_ready = max_ready > 0 ? max_ready : 2;
  for (int i = 0; i < (num_threads > 0 ? num_threads : 2); ++i)
    r->workers.emplace_back(reader_worker, r);
  return r;
}

long rio_reader_num_batches(void* h) {
  return static_cast<BatchReader*>(h)->n_batches;
}

long rio_reader_num_records(void* h) {
  return (long)static_cast<BatchReader*>(h)->order.size();
}

// Blocks for the next in-order batch. Returns total bytes (payloads are
// copied into out_buf up to cap); sizes into out_sizes (batch_size entries).
// Returns -1 at end of epoch. If total > cap the batch is NOT consumed:
// it stays queued so the caller can grow its buffer and retry the SAME
// batch (no silent data loss on oversized batches).
long rio_reader_next(void* h, char* out_buf, long cap, int64_t* out_sizes) {
  auto* r = static_cast<BatchReader*>(h);
  Batch* b = nullptr;
  {
    std::unique_lock<std::mutex> lk(r->mu);
    if (r->next_consume >= r->n_batches) return -1;
    long want = r->next_consume;
    r->cv_ready.wait(lk, [&] { return r->stop || r->ready.count(want); });
    if (r->stop) return -1;
    b = r->ready[want];
    long total = (long)b->data.size();
    if (total > cap) return total;  // keep queued; caller retries with bigger buf
    r->ready.erase(want);
    r->next_consume++;
  }
  r->cv_space.notify_all();
  long total = (long)b->data.size();
  memcpy(out_buf, b->data.data(), total);
  for (size_t i = 0; i < b->sizes.size(); ++i) out_sizes[i] = b->sizes[i];
  delete b;
  return total;
}

void rio_reader_reset(void* h, int reshuffle) {
  auto* r = static_cast<BatchReader*>(h);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    for (auto& kv : r->ready) delete kv.second;
    r->ready.clear();
    r->next_consume = 0;
    r->next_produce = 0;
    r->epoch++;  // in-flight worker batches from the old epoch get discarded
    if (reshuffle && r->shuffle)
      std::shuffle(r->order.begin(), r->order.end(), r->rng);
  }
  r->cv_space.notify_all();
}

void rio_reader_destroy(void* h) { delete static_cast<BatchReader*>(h); }

int mxtpu_native_abi_version() { return 1; }

}  // extern "C"
