"""Helper module for the embedded-interpreter C predict API
(native/src/c_predict_api.cc — ref src/c_api/c_predict_api.cc).

The C side keeps each predictor as an opaque PyObject (a ``_PredState``)
and calls the module-level functions below through the CPython C API. All
array traffic crosses the ABI as raw bytes (C-contiguous, row-major) — the
same contract as the reference's MXPredSetInput/MXPredGetOutput float
buffers, generalized to any dtype the artifact declares.

Kept deliberately free of framework imports at module load: the heavy
import (jax via contrib.serving) happens inside ``create`` so that merely
loading libmxtpu_predict.so stays cheap.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "create", "num_inputs", "num_outputs", "input_shape", "input_dtype",
    "output_shape", "output_dtype", "set_input", "forward", "output_bytes",
]


class _PredState:
    __slots__ = ("model", "inputs", "outputs")

    def __init__(self, model):
        self.model = model
        self.inputs = [None] * len(model.input_shapes)
        self.outputs = None


def create(path):
    """Load a .mxtpu serving artifact → predictor state (≙ MXPredCreate)."""
    import os
    plats = os.environ.get("JAX_PLATFORMS")
    if plats:
        # The deployment env's sitecustomize may pin jax_platforms after
        # reading the env var; re-assert the caller's choice explicitly so
        # `JAX_PLATFORMS=cpu ./client model.mxtpu ...` behaves as written.
        import jax
        jax.config.update("jax_platforms", plats)
    from incubator_mxnet_tpu.contrib import serving
    return _PredState(serving.load(path))


def num_inputs(st):
    return len(st.model.input_shapes)


def num_outputs(st):
    return len(st.model.output_shapes)


def input_shape(st, i):
    return tuple(int(d) for d in st.model.input_shapes[i])


def output_shape(st, i):
    return tuple(int(d) for d in st.model.output_shapes[i])


def input_dtype(st, i):
    return st.model._exp.in_avals[i].dtype.name


def output_dtype(st, i):
    return st.model._exp.out_avals[i].dtype.name


def set_input(st, i, view):
    """Stage input i from a C buffer (memoryview) — copies immediately."""
    shape = input_shape(st, i)
    dt = np.dtype(input_dtype(st, i))
    want = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    if view.nbytes != want:
        raise ValueError(
            "input %d: got %d bytes, want %d (shape %s dtype %s)"
            % (i, view.nbytes, want, shape, dt.name))
    st.inputs[i] = np.frombuffer(view, dtype=dt).reshape(shape).copy()


def forward(st):
    """Run the compiled program on the staged inputs (≙ MXPredForward)."""
    missing = [i for i, x in enumerate(st.inputs) if x is None]
    if missing:
        raise ValueError("inputs %s not set before forward" % missing)
    out = st.model._exp.call(*st.inputs)
    if not isinstance(out, (list, tuple)):
        out = (out,)
    st.outputs = [np.asarray(o) for o in out]


def output_bytes(st, i):
    """Output i as contiguous bytes (≙ MXPredGetOutput)."""
    if st.outputs is None:
        raise ValueError("forward has not been run")
    return np.ascontiguousarray(st.outputs[i]).tobytes()
