"""Helper module for the EXTENDED C API tier
(native/src/c_predict_api.cc MXTPUKVStore*/MXTPUProfiler*/MXTPUNDArraySave-
Load/MXTPUSymbolInferShape/... — ref include/mxnet/c_api.h MXKVStore*
(~30 fns), MXProfile*, MXNDArraySave/Load, MXSymbolInferShape,
MXListAllOpNames, MXRandomSeed, MXLoadLib regions of the 3,413-line
header).

Same layering as the graph/invoke slices: the C side marshals plain
types and opaque handles; these helpers do the Python-object work against
the SAME frontend stack the Python user calls.
"""
from __future__ import annotations

import json

__all__ = [
    "nd_save", "nd_load_bundle", "bundle_len", "bundle_name", "bundle_item",
    "sym_from_json", "sym_save_file", "sym_list_aux", "sym_infer_shape",
    "sym_get_attr", "sym_set_attr",
    "kv_create", "kv_type", "kv_rank", "kv_num_workers", "kv_init",
    "kv_push", "kv_pull", "kv_pushpull", "kv_broadcast", "kv_set_compression",
    "profiler_set_config", "profiler_set_state", "profiler_dump",
    "profiler_summary",
    "random_seed", "list_all_op_names", "load_lib", "wait_all",
]


def _mx():
    import incubator_mxnet_tpu as mx
    return mx


# ------------------------------------------------------- NDArray save/load
def nd_save(fname, names, arrays):
    """≙ MXNDArraySave: all names empty saves a positional list; named
    saves are all-or-none (mixed or duplicate names would silently drop
    arrays through the dict, so they are rejected)."""
    mx = _mx()
    if names and any(names):
        if not all(names):
            raise ValueError("nd_save: mixed empty/non-empty names")
        if len(set(names)) != len(names):
            raise ValueError("nd_save: duplicate names %s"
                             % sorted(n for n in set(names)
                                      if names.count(n) > 1))
        mx.nd.save(fname, dict(zip(names, arrays)))
    else:
        mx.nd.save(fname, list(arrays))


def nd_load_bundle(fname):
    """≙ MXNDArrayLoad: returns a (names, arrays) bundle object."""
    out = _mx().nd.load(fname)
    if isinstance(out, dict):
        keys = list(out)
        return (keys, [out[k] for k in keys])
    return ([""] * len(out), list(out))


def bundle_len(bundle):
    return len(bundle[1])


def bundle_name(bundle, i):
    return bundle[0][i]


def bundle_item(bundle, i):
    return bundle[1][i]


# ----------------------------------------------------------------- Symbol
def sym_from_json(js):
    return _mx().sym.load_json(js)


def sym_save_file(s, fname):
    s.save(fname)


def sym_list_aux(s):
    return json.dumps(list(s.list_auxiliary_states()))


def sym_infer_shape(s, shapes_json):
    """≙ MXSymbolInferShape: known input shapes in, (arg, out, aux) shape
    table out (JSON)."""
    shapes = {k: tuple(int(d) for d in v)
              for k, v in json.loads(shapes_json).items()}
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(**shapes)
    if arg_shapes is None:
        raise ValueError("infer_shape needs every input shape (got %s)"
                         % sorted(shapes))
    return json.dumps({"arg_shapes": [list(x) for x in arg_shapes],
                       "out_shapes": [list(x) for x in out_shapes],
                       "aux_shapes": [list(x) for x in (aux_shapes or [])]})


def sym_get_attr(s, key):
    v = s.attr(key)
    if v is None:
        raise KeyError(key)
    return str(v)


def sym_set_attr(s, key, value):
    s._set_attr(**{key: value})


# ---------------------------------------------------------------- KVStore
def kv_create(type_name):
    return _mx().kv.create(type_name)


def kv_type(kv):
    return getattr(kv, "type", getattr(kv, "name", "local"))


def kv_rank(kv):
    return int(getattr(kv, "rank", 0))


def kv_num_workers(kv):
    return int(getattr(kv, "num_workers", 1))


def kv_init(kv, keys, arrays):
    kv.init(list(keys), list(arrays))


def kv_push(kv, keys, arrays, priority):
    kv.push(list(keys), list(arrays), priority=priority)


def kv_pull(kv, keys, arrays):
    kv.pull(list(keys), out=list(arrays))


def kv_pushpull(kv, keys, values, outs):
    kv.pushpull(list(keys), list(values), out=list(outs))


def kv_broadcast(kv, keys, values, outs):
    kv.broadcast(list(keys), list(values), out=list(outs))


def kv_set_compression(kv, params_json):
    kv.set_gradient_compression(json.loads(params_json))


# --------------------------------------------------------------- Profiler
def profiler_set_config(params_json):
    _mx().profiler.set_config(**json.loads(params_json))


def profiler_set_state(state):
    _mx().profiler.set_state(state)


def profiler_dump(finished):
    _mx().profiler.dump(bool(finished))


def profiler_summary():
    return _mx().profiler.dumps()


# ------------------------------------------------------------------- misc
def random_seed(seed):
    _mx().nd.random.seed(int(seed))


def list_all_op_names():
    from incubator_mxnet_tpu.base import public_op_names
    return json.dumps(public_op_names(_mx().nd))


def load_lib(path):
    """≙ MXLoadLib: load a user extension library (registers custom ops)."""
    _mx().library.load(path)


def wait_all():
    _mx().nd.waitall()
