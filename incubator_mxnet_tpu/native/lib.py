"""ctypes bindings for the native library (built from src/*.cc).

Build: ``python -m incubator_mxnet_tpu.native.build`` (or import-time
auto-build). All users gate on ``available()`` and fall back to pure Python.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libmxtpu.so")
_LIB = None


def build(force=False):
    """Compile src/*.cc into libmxtpu.so with g++ -O3 -pthread."""
    src = os.path.join(_DIR, "src", "recordio.cc")
    if os.path.exists(_SO) and not force and \
            os.path.getmtime(_SO) >= os.path.getmtime(src):
        return _SO
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           src, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True)
    return _SO


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    try:
        build()  # mtime-gated: rebuilds when src/*.cc is newer than the .so,
        #          so a stale binary can't skew the Python<->C++ contract
        lib = ctypes.CDLL(_SO)
    except (OSError, subprocess.CalledProcessError):
        _LIB = False
        return False
    c = ctypes
    lib.rio_writer_open.restype = c.c_void_p
    lib.rio_writer_open.argtypes = [c.c_char_p]
    lib.rio_writer_tell.restype = c.c_long
    lib.rio_writer_tell.argtypes = [c.c_void_p]
    lib.rio_write.restype = c.c_int
    lib.rio_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32]
    lib.rio_writer_close.argtypes = [c.c_void_p]
    lib.rio_scan.restype = c.c_long
    lib.rio_scan.argtypes = [c.c_char_p, c.POINTER(c.c_int64),
                             c.POINTER(c.c_int64), c.c_long]
    lib.pool_create.restype = c.c_void_p
    lib.pool_alloc.restype = c.c_void_p
    lib.pool_alloc.argtypes = [c.c_void_p, c.c_size_t]
    lib.pool_free.argtypes = [c.c_void_p, c.c_void_p, c.c_size_t]
    lib.pool_used_bytes.restype = c.c_size_t
    lib.pool_used_bytes.argtypes = [c.c_void_p]
    lib.pool_destroy.argtypes = [c.c_void_p]
    lib.rio_reader_create.restype = c.c_void_p
    lib.rio_reader_create.argtypes = [c.c_char_p, c.c_long, c.c_int, c.c_int,
                                      c.c_int, c.c_long, c.c_long, c.c_long]
    lib.rio_reader_num_batches.restype = c.c_long
    lib.rio_reader_num_batches.argtypes = [c.c_void_p]
    lib.rio_reader_num_records.restype = c.c_long
    lib.rio_reader_num_records.argtypes = [c.c_void_p]
    lib.rio_reader_next.restype = c.c_long
    lib.rio_reader_next.argtypes = [c.c_void_p, c.c_char_p, c.c_long,
                                    c.POINTER(c.c_int64)]
    lib.rio_reader_reset.argtypes = [c.c_void_p, c.c_int]
    lib.rio_reader_destroy.argtypes = [c.c_void_p]
    _LIB = lib
    return lib


def available():
    lib = _load()
    return bool(lib)


def get():
    lib = _load()
    if not lib:
        raise RuntimeError("native library unavailable (g++ build failed)")
    return lib


class NativeBatchReader:
    """Prefetching record-batch reader backed by C++ worker threads."""

    def __init__(self, path, batch_size, shuffle=False, seed=0, num_threads=2,
                 max_ready=4, part_index=0, num_parts=1):
        self._lib = get()
        self._h = self._lib.rio_reader_create(
            path.encode(), batch_size, int(shuffle), seed, num_threads,
            max_ready, part_index, num_parts)
        if not self._h:
            raise IOError("cannot open record file %s" % path)
        self.batch_size = batch_size
        self._sizes = (ctypes.c_int64 * batch_size)()
        self._cap = 1 << 22
        self._buf = ctypes.create_string_buffer(self._cap)

    @property
    def num_batches(self):
        return self._lib.rio_reader_num_batches(self._h)

    @property
    def num_records(self):
        return self._lib.rio_reader_num_records(self._h)

    def next(self):
        """Returns list[bytes] payloads of the next batch, or None at epoch end."""
        total = self._lib.rio_reader_next(self._h, self._buf, self._cap,
                                          self._sizes)
        if total < 0:
            return None
        while total > self._cap:
            # Oversized batch: the C++ side kept it queued (did not consume),
            # so growing the buffer and retrying fetches the SAME batch.
            self._cap = 1 << max(total.bit_length(), 22)
            self._buf = ctypes.create_string_buffer(self._cap)
            total = self._lib.rio_reader_next(self._h, self._buf, self._cap,
                                              self._sizes)
            if total < 0:
                return None
        raw = self._buf.raw  # ONE copy of the buffer, not one per record
        out, off = [], 0
        for i in range(self.batch_size):
            n = self._sizes[i]
            if n < 0:
                raise IOError("truncated record in batch (record %d): file "
                              "shorter than its index claims" % i)
            out.append(raw[off:off + n])
            off += n
        return out

    def reset(self, reshuffle=True):
        self._lib.rio_reader_reset(self._h, int(reshuffle))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.rio_reader_destroy(self._h)
        except Exception:
            pass


class HostBufferPool:
    """Pooled host staging allocator (C++ size-bucketed free lists)."""

    def __init__(self):
        self._lib = get()
        self._h = self._lib.pool_create()

    def alloc(self, size):
        return self._lib.pool_alloc(self._h, size)

    def free(self, ptr, size):
        self._lib.pool_free(self._h, ptr, size)

    def used_bytes(self):
        return self._lib.pool_used_bytes(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pool_destroy(self._h)
        except Exception:
            pass


def scan_offsets(path):
    """Fast native scan: returns (offsets, lengths) numpy arrays."""
    import numpy as onp
    lib = get()
    n = lib.rio_scan(path.encode(), None, None, 0)
    if n < 0:
        raise IOError("scan failed for %s (code %d)" % (path, n))
    offs = (ctypes.c_int64 * n)()
    lens = (ctypes.c_int64 * n)()
    lib.rio_scan(path.encode(), offs, lens, n)
    return onp.frombuffer(offs, dtype=onp.int64).copy(), \
        onp.frombuffer(lens, dtype=onp.int64).copy()
