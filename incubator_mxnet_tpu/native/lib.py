"""ctypes bindings for the native library (built from src/*.cc).

Build: ``python -m incubator_mxnet_tpu.native.build`` (or import-time
auto-build). All users gate on ``available()`` and fall back to pure Python.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libmxtpu.so")
_LIB = None


def build(force=False):
    """Compile src/*.cc into libmxtpu.so with g++ -O3 -pthread -ljpeg.

    c_predict_api.cc is excluded — it embeds CPython and builds into its
    own libmxtpu_predict.so (see build_predict)."""
    srcs = sorted(
        os.path.join(_DIR, "src", f) for f in os.listdir(os.path.join(_DIR, "src"))
        if f.endswith(".cc") and f != "c_predict_api.cc")
    if os.path.exists(_SO) and not force and \
            os.path.getmtime(_SO) >= max(os.path.getmtime(s) for s in srcs):
        return _SO
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *srcs, "-o", _SO, "-ljpeg"]
    subprocess.run(cmd, check=True, capture_output=True)
    return _SO


_PREDICT_SO = os.path.join(_DIR, "libmxtpu_predict.so")


def build_predict(force=False):
    """Compile the C predict API (embedded CPython) into libmxtpu_predict.so.

    Include/link flags come from sysconfig of THIS interpreter, so the
    library embeds a matching libpython (ref c_predict_api deployment)."""
    import sysconfig
    src = os.path.join(_DIR, "src", "c_predict_api.cc")
    if os.path.exists(_PREDICT_SO) and not force and \
            os.path.getmtime(_PREDICT_SO) >= os.path.getmtime(src):
        return _PREDICT_SO
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    ver = "python" + (sysconfig.get_config_var("LDVERSION")
                      or "%d.%d" % sys.version_info[:2])
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           src, "-I", inc, "-L", libdir, "-Wl,-rpath," + libdir,
           "-l" + ver, "-ldl", "-o", _PREDICT_SO]
    subprocess.run(cmd, check=True, capture_output=True)
    return _PREDICT_SO


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    try:
        build()  # mtime-gated: rebuilds when src/*.cc is newer than the .so,
        #          so a stale binary can't skew the Python<->C++ contract
        lib = ctypes.CDLL(_SO)
    except (OSError, subprocess.CalledProcessError):
        _LIB = False
        return False
    c = ctypes
    lib.rio_writer_open.restype = c.c_void_p
    lib.rio_writer_open.argtypes = [c.c_char_p]
    lib.rio_writer_tell.restype = c.c_long
    lib.rio_writer_tell.argtypes = [c.c_void_p]
    lib.rio_write.restype = c.c_int
    lib.rio_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32]
    lib.rio_writer_close.argtypes = [c.c_void_p]
    lib.rio_scan.restype = c.c_long
    lib.rio_scan.argtypes = [c.c_char_p, c.POINTER(c.c_int64),
                             c.POINTER(c.c_int64), c.c_long]
    lib.pool_create.restype = c.c_void_p
    lib.pool_alloc.restype = c.c_void_p
    lib.pool_alloc.argtypes = [c.c_void_p, c.c_size_t]
    lib.pool_free.argtypes = [c.c_void_p, c.c_void_p, c.c_size_t]
    lib.pool_used_bytes.restype = c.c_size_t
    lib.pool_used_bytes.argtypes = [c.c_void_p]
    lib.pool_destroy.argtypes = [c.c_void_p]
    lib.rio_reader_create.restype = c.c_void_p
    lib.rio_reader_create.argtypes = [c.c_char_p, c.c_long, c.c_int, c.c_int,
                                      c.c_int, c.c_long, c.c_long, c.c_long]
    lib.rio_reader_num_batches.restype = c.c_long
    lib.rio_reader_num_batches.argtypes = [c.c_void_p]
    lib.rio_reader_num_records.restype = c.c_long
    lib.rio_reader_num_records.argtypes = [c.c_void_p]
    lib.rio_reader_next.restype = c.c_long
    lib.rio_reader_next.argtypes = [c.c_void_p, c.c_char_p, c.c_long,
                                    c.POINTER(c.c_int64)]
    lib.rio_reader_reset.argtypes = [c.c_void_p, c.c_int]
    lib.rio_reader_destroy.argtypes = [c.c_void_p]
    # image pipeline (src/image.cc)
    lib.img_pipe_create.restype = c.c_void_p
    lib.img_pipe_create.argtypes = [
        c.c_char_p, c.c_long, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.c_int, c.POINTER(c.c_float), c.POINTER(c.c_float), c.c_float,
        c.c_int, c.c_int, c.c_int, c.c_long, c.c_long, c.c_long]
    lib.img_pipe_num_batches.restype = c.c_long
    lib.img_pipe_num_batches.argtypes = [c.c_void_p]
    lib.img_pipe_num_records.restype = c.c_long
    lib.img_pipe_num_records.argtypes = [c.c_void_p]
    lib.img_pipe_next.restype = c.c_long
    lib.img_pipe_next.argtypes = [c.c_void_p, c.POINTER(c.c_float),
                                  c.POINTER(c.c_float)]
    lib.img_pipe_reset.argtypes = [c.c_void_p, c.c_int]
    lib.img_pipe_destroy.argtypes = [c.c_void_p]
    _LIB = lib
    return lib


def available():
    from ..config import get_env
    if get_env("MXTPU_NO_NATIVE"):
        return False
    lib = _load()
    return bool(lib)


def get():
    lib = _load()
    if not lib:
        raise RuntimeError("native library unavailable (g++ build failed)")
    return lib


class NativeBatchReader:
    """Prefetching record-batch reader backed by C++ worker threads."""

    def __init__(self, path, batch_size, shuffle=False, seed=0, num_threads=2,
                 max_ready=4, part_index=0, num_parts=1):
        self._lib = get()
        self._h = self._lib.rio_reader_create(
            path.encode(), batch_size, int(shuffle), seed, num_threads,
            max_ready, part_index, num_parts)
        if not self._h:
            raise IOError("cannot open record file %s" % path)
        self.batch_size = batch_size
        self._sizes = (ctypes.c_int64 * batch_size)()
        self._cap = 1 << 22
        self._buf = ctypes.create_string_buffer(self._cap)

    @property
    def num_batches(self):
        return self._lib.rio_reader_num_batches(self._h)

    @property
    def num_records(self):
        return self._lib.rio_reader_num_records(self._h)

    def next(self):
        """Returns list[bytes] payloads of the next batch, or None at epoch end."""
        total = self._lib.rio_reader_next(self._h, self._buf, self._cap,
                                          self._sizes)
        if total < 0:
            return None
        while total > self._cap:
            # Oversized batch: the C++ side kept it queued (did not consume),
            # so growing the buffer and retrying fetches the SAME batch.
            self._cap = 1 << max(total.bit_length(), 22)
            self._buf = ctypes.create_string_buffer(self._cap)
            total = self._lib.rio_reader_next(self._h, self._buf, self._cap,
                                              self._sizes)
            if total < 0:
                return None
        raw = self._buf.raw  # ONE copy of the buffer, not one per record
        out, off = [], 0
        for i in range(self.batch_size):
            n = self._sizes[i]
            if n < 0:
                raise IOError("truncated record in batch (record %d): file "
                              "shorter than its index claims" % i)
            out.append(raw[off:off + n])
            off += n
        return out

    def reset(self, reshuffle=True):
        self._lib.rio_reader_reset(self._h, int(reshuffle))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.rio_reader_destroy(self._h)
        except Exception:
            pass


class NativeImagePipeline:
    """C++ JPEG decode + augment + NCHW batch assembly (src/image.cc) — no
    Python in the decode loop (ref src/io/iter_image_recordio_2.cc:51)."""

    def __init__(self, path, batch_size, data_shape, label_width=1,
                 resize_short=0, rand_crop=False, rand_mirror=False,
                 mean_rgb=None, std_rgb=None, scale=1.0, shuffle=False,
                 seed=0, num_threads=4, part_index=0, num_parts=1):
        import numpy as onp
        self._lib = get()
        c, h, w = data_shape
        if c != 3:
            raise ValueError("native pipeline produces 3-channel RGB")
        mean = (ctypes.c_float * 3)(*(mean_rgb or (0., 0., 0.)))
        std = (ctypes.c_float * 3)(*(std_rgb or (1., 1., 1.)))
        self._h = self._lib.img_pipe_create(
            path.encode(), batch_size, h, w, label_width, resize_short,
            int(rand_crop), int(rand_mirror), mean, std, float(scale),
            int(shuffle), seed, num_threads, 4, part_index, num_parts)
        if not self._h:
            raise IOError("cannot open record file %s" % path)
        self.batch_size = batch_size
        self.data_shape = (batch_size, 3, h, w)
        self.label_shape = (batch_size, label_width)
        self._data = onp.empty(self.data_shape, onp.float32)
        self._labels = onp.empty(self.label_shape, onp.float32)

    @property
    def num_batches(self):
        return self._lib.img_pipe_num_batches(self._h)

    @property
    def num_records(self):
        return self._lib.img_pipe_num_records(self._h)

    def next(self):
        """Returns (data NCHW float32, labels, n_bad) or None at epoch end.
        The returned arrays are reused across calls — copy if you keep them."""
        bad = self._lib.img_pipe_next(
            self._h,
            self._data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if bad < 0:
            return None
        return self._data, self._labels, int(bad)

    def reset(self, reshuffle=True):
        self._lib.img_pipe_reset(self._h, int(reshuffle))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.img_pipe_destroy(self._h)
        except Exception:
            pass


class HostBufferPool:
    """Pooled host staging allocator (C++ size-bucketed free lists)."""

    def __init__(self):
        self._lib = get()
        self._h = self._lib.pool_create()

    def alloc(self, size):
        return self._lib.pool_alloc(self._h, size)

    def free(self, ptr, size):
        self._lib.pool_free(self._h, ptr, size)

    def used_bytes(self):
        return self._lib.pool_used_bytes(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pool_destroy(self._h)
        except Exception:
            pass


def scan_offsets(path):
    """Fast native scan: returns (offsets, lengths) numpy arrays."""
    import numpy as onp
    lib = get()
    n = lib.rio_scan(path.encode(), None, None, 0)
    if n < 0:
        raise IOError("scan failed for %s (code %d)" % (path, n))
    offs = (ctypes.c_int64 * n)()
    lens = (ctypes.c_int64 * n)()
    lib.rio_scan(path.encode(), offs, lens, n)
    return onp.frombuffer(offs, dtype=onp.int64).copy(), \
        onp.frombuffer(lens, dtype=onp.int64).copy()
