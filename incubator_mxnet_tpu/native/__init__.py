"""Native (C++) runtime components: RecordIO scan/batch-prefetch reader and
pooled host allocator. See src/recordio.cc; python bindings in lib.py."""
from . import lib  # noqa
