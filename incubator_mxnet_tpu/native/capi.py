"""ctypes bindings for the flat C ABI (src/c_api.cc) — the binding surface
other languages would link against (ref include/mxnet/c_api.h slice:
NDArray create/from-host/to-host/shape/free + ImageRecordIter create/next).

Python itself uses the richer internal paths; this module exists to
exercise and document the ABI the way an external binding would.
"""
from __future__ import annotations

import ctypes

import numpy as onp

from . import lib as _nlib

_DTYPE = {0: onp.float32, 1: onp.float64, 2: onp.float16, 3: onp.uint8,
          4: onp.int32, 5: onp.int8, 6: onp.int64, 7: onp.bool_}
_DTYPE_REV = {onp.dtype(v): k for k, v in _DTYPE.items()}

_BOUND = False


def _lib():
    global _BOUND
    lib = _nlib.get()
    if not _BOUND:
        c = ctypes
        lib.MXTPUGetLastError.restype = c.c_char_p
        lib.MXTPUNDArrayCreate.argtypes = [c.POINTER(c.c_int64), c.c_int,
                                           c.c_int, c.POINTER(c.c_void_p)]
        lib.MXTPUNDArraySyncCopyFromCPU.argtypes = [c.c_void_p, c.c_void_p,
                                                    c.c_size_t]
        lib.MXTPUNDArraySyncCopyToCPU.argtypes = [c.c_void_p, c.c_void_p,
                                                  c.c_size_t]
        lib.MXTPUNDArrayGetShape.argtypes = [c.c_void_p, c.POINTER(c.c_int),
                                             c.POINTER(c.c_int64)]
        lib.MXTPUNDArrayGetDType.argtypes = [c.c_void_p, c.POINTER(c.c_int)]
        lib.MXTPUNDArrayGetData.argtypes = [c.c_void_p, c.POINTER(c.c_void_p)]
        lib.MXTPUNDArrayFree.argtypes = [c.c_void_p]
        lib.MXTPUImageRecordIterCreate.argtypes = [
            c.c_char_p, c.c_long, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
            c.c_int, c.POINTER(c.c_float), c.POINTER(c.c_float), c.c_float,
            c.c_int, c.c_int, c.c_int, c.c_long, c.c_long,
            c.POINTER(c.c_void_p)]
        lib.MXTPUDataIterNext.argtypes = [c.c_void_p, c.POINTER(c.c_int)]
        lib.MXTPUDataIterGetData.argtypes = [c.c_void_p,
                                             c.POINTER(c.c_void_p)]
        lib.MXTPUDataIterGetLabel.argtypes = [c.c_void_p,
                                              c.POINTER(c.c_void_p)]
        lib.MXTPUDataIterReset.argtypes = [c.c_void_p, c.c_int]
        lib.MXTPUDataIterFree.argtypes = [c.c_void_p]
        _BOUND = True
    return lib


def _check(rc):
    if rc != 0:
        raise RuntimeError("C API error: %s" %
                           _lib().MXTPUGetLastError().decode())


class CArray:
    """Host array behind an opaque C handle."""

    def __init__(self, shape=None, dtype="float32", _handle=None, _owns=True):
        lib = _lib()
        if _handle is None:
            shp = (ctypes.c_int64 * len(shape))(*shape)
            h = ctypes.c_void_p()
            _check(lib.MXTPUNDArrayCreate(
                shp, len(shape), _DTYPE_REV[onp.dtype(dtype)],
                ctypes.byref(h)))
            _handle = h
        self._h = _handle
        self._owns = _owns

    @property
    def shape(self):
        lib = _lib()
        nd = ctypes.c_int()
        _check(lib.MXTPUNDArrayGetShape(self._h, ctypes.byref(nd), None))
        shp = (ctypes.c_int64 * nd.value)()
        _check(lib.MXTPUNDArrayGetShape(self._h, ctypes.byref(nd), shp))
        return tuple(shp)

    @property
    def dtype(self):
        dt = ctypes.c_int()
        _check(_lib().MXTPUNDArrayGetDType(self._h, ctypes.byref(dt)))
        return onp.dtype(_DTYPE[dt.value])

    def copy_from(self, arr):
        arr = onp.ascontiguousarray(arr, dtype=self.dtype)
        _check(_lib().MXTPUNDArraySyncCopyFromCPU(
            self._h, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes))
        return self

    def asnumpy(self):
        out = onp.empty(self.shape, self.dtype)
        _check(_lib().MXTPUNDArraySyncCopyToCPU(
            self._h, out.ctypes.data_as(ctypes.c_void_p), out.nbytes))
        return out

    def __del__(self):
        try:
            if self._owns and getattr(self, "_h", None):
                _lib().MXTPUNDArrayFree(self._h)
        except Exception:
            pass


class CImageRecordIter:
    """ImageRecordIter through the flat C ABI."""

    def __init__(self, rec_path, batch_size, data_shape, label_width=1,
                 resize_short=0, rand_crop=False, rand_mirror=False,
                 mean_rgb=None, std_rgb=None, scale=1.0, shuffle=False,
                 seed=0, num_threads=2, part_index=0, num_parts=1):
        lib = _lib()
        _, h, w = data_shape
        mean = (ctypes.c_float * 3)(*(mean_rgb or (0., 0., 0.)))
        std = (ctypes.c_float * 3)(*(std_rgb or (1., 1., 1.)))
        hd = ctypes.c_void_p()
        _check(lib.MXTPUImageRecordIterCreate(
            rec_path.encode(), batch_size, h, w, label_width, resize_short,
            int(rand_crop), int(rand_mirror), mean, std, float(scale),
            int(shuffle), seed, num_threads, part_index, num_parts,
            ctypes.byref(hd)))
        self._h = hd

    def next(self):
        """Returns (data, label) CArrays (views into iter-owned buffers),
        or None at epoch end."""
        lib = _lib()
        has = ctypes.c_int()
        _check(lib.MXTPUDataIterNext(self._h, ctypes.byref(has)))
        if not has.value:
            return None
        d = ctypes.c_void_p()
        l = ctypes.c_void_p()
        _check(lib.MXTPUDataIterGetData(self._h, ctypes.byref(d)))
        _check(lib.MXTPUDataIterGetLabel(self._h, ctypes.byref(l)))
        return (CArray(_handle=d, _owns=False), CArray(_handle=l, _owns=False))

    def reset(self, reshuffle=True):
        _check(_lib().MXTPUDataIterReset(self._h, int(reshuffle)))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                _lib().MXTPUDataIterFree(self._h)
        except Exception:
            pass
