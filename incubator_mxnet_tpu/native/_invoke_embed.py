"""Helper module for the embedded-interpreter imperative-invoke C API
(native/src/c_predict_api.cc MXTPUImperativeInvoke et al. — ref
include/mxnet/c_api.h MXImperativeInvokeEx + MXNDArrayCreateEx).

The C side holds each array as an opaque PyObject (an incubator_mxnet_tpu
NDArray) and calls the module-level functions below through the CPython C
API. This is the slice that lets non-Python frontends run EAGER ops by
name — the reference's imperative invoke — on top of the same embedded
interpreter the predict ABI already boots; op dispatch goes through the
same nd/nd.contrib registry the Python frontend uses, so every registered
operator is reachable from C (and from the Julia binding riding this ABI).

Array traffic crosses the ABI as raw C-contiguous bytes + (dtype, shape);
op attributes cross as a JSON object string (the reference passes
stringified attrs the same way)."""
from __future__ import annotations

import json

import numpy as np

__all__ = ["nd_create", "nd_shape", "nd_dtype", "nd_bytes", "invoke",
           "attach_grad", "record_begin", "record_end", "backward",
           "grad_of", "set_data"]


def _nd_mod():
    from incubator_mxnet_tpu import nd
    return nd


def nd_create(dtype, shape, view):
    """Host bytes -> NDArray (≙ MXNDArrayCreateEx + SyncCopyFromCPU)."""
    dt = np.dtype(dtype)
    shape = tuple(int(d) for d in shape)
    want = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    if view.nbytes != want:
        raise ValueError("got %d bytes, want %d (shape %s dtype %s)"
                         % (view.nbytes, want, shape, dt.name))
    arr = np.frombuffer(view, dtype=dt).reshape(shape).copy()
    return _nd_mod().array(arr, dtype=dt.name)


def nd_shape(h):
    return tuple(int(d) for d in h.shape)


def nd_dtype(h):
    return np.dtype(h.dtype).name


def nd_bytes(h):
    """≙ MXNDArraySyncCopyToCPU."""
    return np.ascontiguousarray(h.asnumpy()).tobytes()


# -- autograd slice (≙ MXAutogradSetIsRecording / MXAutogradBackwardEx /
# MXNDArrayGetGrad): with invoke() above, non-Python frontends can TRAIN —
# attach grads, record a tape scope, run ops, backward, read gradients,
# and write updated parameter values back (set_data).
_RECORD_SCOPES = []


def attach_grad(h):
    h.attach_grad()
    return True


def record_begin():
    from incubator_mxnet_tpu import autograd
    scope = autograd.record()
    scope.__enter__()
    _RECORD_SCOPES.append(scope)
    return True


def record_end():
    if not _RECORD_SCOPES:
        raise RuntimeError("record_end without record_begin")
    _RECORD_SCOPES.pop().__exit__(None, None, None)
    return True


def backward(h):
    h.backward()
    return True


def grad_of(h):
    g = h.grad
    if g is None:
        raise ValueError("no gradient: attach_grad not called or backward "
                         "not run")
    return g


def set_data(h, view, dtype):
    """Overwrite h's buffer from host bytes (the optimizer-update writeback
    path for C-side training loops)."""
    dt = np.dtype(dtype)
    want = int(np.prod(h.shape, dtype=np.int64)) * dt.itemsize
    if view.nbytes != want:
        raise ValueError("got %d bytes, want %d" % (view.nbytes, want))
    # .copy(): the view is a NON-OWNING window over the C caller's buffer
    # (freed right after the call returns); jax.device_put may take a
    # zero-copy path for aligned host arrays, so aliasing it would be a
    # use-after-free — same reason nd_create copies
    arr = np.frombuffer(view, dtype=dt).reshape(h.shape).copy()
    h._data = __import__("jax").numpy.asarray(arr)
    return True


def invoke(op_name, inputs, kwargs_json):
    """Name-dispatched eager op call (≙ MXImperativeInvokeEx).

    Resolves ``op_name`` on nd, then nd.contrib (dotted names like
    "contrib.ROIAlign" or "linalg.gemm2" also work); returns a tuple of
    NDArray outputs."""
    nd = _nd_mod()
    target = nd
    name = op_name
    if "." in name:
        prefix, name = name.rsplit(".", 1)
        for part in prefix.split("."):
            target = getattr(target, part)
    fn = getattr(target, name, None)
    if fn is None and target is nd:
        fn = getattr(nd.contrib, name, None)
    if fn is None:
        raise ValueError("unknown operator %r" % op_name)
    kwargs = json.loads(kwargs_json) if kwargs_json else {}
    kwargs = {k: (tuple(v) if isinstance(v, list) else v)
              for k, v in kwargs.items()}
    out = fn(*inputs, **kwargs)
    if isinstance(out, (list, tuple)):
        return tuple(out)
    return (out,)
