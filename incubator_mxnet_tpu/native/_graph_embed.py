"""Helper module for the embedded-interpreter GRAPH C API
(native/src/c_predict_api.cc MXTPUSymbol*/MXTPUExecutor* — ref
include/mxnet/c_api.h MXSymbolCreateAtomicSymbol/MXSymbolCompose and
MXExecutorSimpleBindEx/c_api_executor.cc:860).

The imperative-invoke slice lets C frontends run EAGER ops; this slice
lets them build and run a GRAPH — compose symbols, simple_bind an
executor, forward/backward, and read/update the bound arrays — which is
what cpp_package-style deployment and training actually want.

Handles crossing the ABI are opaque PyObjects: composed ``Symbol``s, an
uncomposed atomic-op token (``_Atomic``), ``Executor``s, and the NDArrays
the existing ND ABI already moves.  Reference parity notes: like
``MXSymbolCompose``, composing fills un-supplied operator inputs with
auto-named variables (fc1 -> fc1_weight/fc1_bias) via the symbol
frontend's own machinery; like ``MXExecutorSimpleBindEx``, simple_bind
allocates argument arrays from shape hints and grad buffers per grad_req.
"""
from __future__ import annotations

import json

import numpy as np

__all__ = ["sym_variable", "sym_atomic", "sym_compose", "sym_list_arguments",
           "sym_list_outputs", "sym_tojson", "executor_simple_bind",
           "executor_forward", "executor_num_outputs", "executor_output",
           "executor_backward", "executor_arg", "executor_arg_grad"]


class _Atomic:
    """An op + attrs awaiting composition (MXSymbolCreateAtomicSymbol)."""

    def __init__(self, op_name, attrs):
        self.op_name = op_name
        self.attrs = attrs


def _sym_mod():
    from incubator_mxnet_tpu import sym
    return sym


def sym_variable(name):
    return _sym_mod().Variable(name)


def sym_atomic(op_name, attrs_json):
    sym = _sym_mod()
    if not hasattr(sym, op_name):
        raise ValueError("unknown symbol op %r" % op_name)
    attrs = json.loads(attrs_json) if attrs_json else {}
    return _Atomic(op_name, attrs)


def sym_compose(atomic, name, keys, args):
    """MXSymbolCompose: bind named symbol inputs + attrs into a node."""
    if not isinstance(atomic, _Atomic):
        raise TypeError("compose target must be an uncomposed atomic "
                        "symbol (got %r)" % type(atomic).__name__)
    sym = _sym_mod()
    fn = getattr(sym, atomic.op_name)
    kwargs = dict(atomic.attrs)
    if name:
        kwargs["name"] = name
    positional = []
    for k, a in zip(keys, args):
        if k:
            kwargs[k] = a
        else:
            positional.append(a)
    return fn(*positional, **kwargs)


def sym_list_arguments(s):
    return json.dumps(list(s.list_arguments()))


def sym_list_outputs(s):
    return json.dumps(list(s.list_outputs()))


def sym_tojson(s):
    return s.tojson()


def executor_simple_bind(s, shapes_json, grad_req):
    shapes = {k: tuple(int(d) for d in v)
              for k, v in json.loads(shapes_json).items()}
    return s.simple_bind(grad_req=grad_req, **shapes)


def executor_forward(ex, is_train, names, arrays):
    feed = dict(zip(names, arrays))
    ex.forward(is_train=bool(is_train), **feed)


def executor_num_outputs(ex):
    return len(ex.outputs)


def executor_output(ex, i):
    return ex.outputs[i]


def executor_backward(ex, head_grads):
    ex.backward(head_grads if head_grads else None)


def executor_arg(ex, name):
    return ex.arg_dict[name]


def executor_arg_grad(ex, name):
    g = ex.grad_dict.get(name)
    if g is None:
        raise KeyError("no grad buffer for %r (grad_req/null or not an "
                       "argument)" % name)
    return g
