"""Custom operator protocol — CustomOp/CustomOpProp
(ref python/mxnet/operator.py:141 CustomOp, :524 CustomOpProp,
src/operator/custom/custom.cc).

TPU-native design: the reference trampolines C++ → Python callbacks through
the engine; here the eager path IS Python, so a custom op is dispatched
directly, and autograd integration rides the tape's custom-backward entry
(autograd.Function). Custom ops run eagerly (host Python) — they do not
fuse into jitted TrainStep programs; use pure-JAX ops (or autograd.Function
over jnp) for compiled-path custom math, matching the reference's guidance
that CustomOp is for prototyping.
"""
from __future__ import annotations

from . import autograd
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered",
           "Custom"]

_REGISTRY = {}


class CustomOp:
    """Base class for custom operator implementations (ref operator.py:141)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write src into dst honoring the grad_req (ref operator.py:159)."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst._data = src._data if isinstance(src, NDArray) else src
        elif req == "add":
            dst._data = dst._data + (src._data if isinstance(src, NDArray)
                                     else src)
        else:
            raise ValueError("unknown req %r" % req)


class CustomOpProp:
    """Operator properties: arguments/outputs/shapes (ref operator.py:524)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        t = in_type[0]
        return in_type, [t] * len(self.list_outputs()), \
            [t] * len(self.list_auxiliary_states())

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Decorator registering a CustomOpProp under ``op_type`` (ref :791)."""
    def do_register(prop_cls):
        _REGISTRY[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_all_registered():
    return dict(_REGISTRY)


class _CustomFunction(autograd.Function):
    def __init__(self, prop, op, n_in, n_out, aux):
        super().__init__()
        self.prop = prop
        self.op = op
        self.n_in = n_in
        self.n_out = n_out
        self.aux = aux

    def forward(self, *inputs):
        self._in_data = list(inputs)
        out_shapes = self.prop.infer_shape([list(x.shape) for x in inputs])[1]
        out_types = self.prop.infer_type([x.dtype for x in inputs])[1]
        self._out_data = [nd.zeros(tuple(s), dtype=t)
                          for s, t in zip(out_shapes, out_types)]
        self.op.forward(is_train=autograd.is_training(),
                        req=["write"] * self.n_out,
                        in_data=self._in_data, out_data=self._out_data,
                        aux=self.aux)
        outs = tuple(self._out_data)
        return outs[0] if len(outs) == 1 else outs

    def backward(self, *output_grads):
        in_grad = [nd.zeros(x.shape, dtype=x.dtype) for x in self._in_data]
        ograds = [g if g is not None else nd.zeros(o.shape, dtype=o.dtype)
                  for g, o in zip(output_grads, self._out_data)]
        self.op.backward(req=["write"] * self.n_in, out_grad=ograds,
                         in_data=self._in_data, out_data=self._out_data,
                         in_grad=in_grad, aux=self.aux)
        return tuple(in_grad)


def Custom(*inputs, op_type, **kwargs):
    """nd.Custom: run a registered custom op (ref ndarray Custom op).

    Extra kwargs are forwarded to the registered CustomOpProp constructor
    (string-valued in the reference; values pass through unchanged here).
    """
    if op_type not in _REGISTRY:
        raise ValueError("custom op %r not registered (use "
                         "@mx.operator.register)" % op_type)
    prop = _REGISTRY[op_type](**kwargs)
    n_in = len(prop.list_arguments())
    if len(inputs) != n_in:
        raise ValueError("custom op %r expects %d inputs (%s), got %d"
                         % (op_type, n_in, prop.list_arguments(), len(inputs)))
    aux = []
    op = prop.create_operator(None, [list(x.shape) for x in inputs],
                              [x.dtype for x in inputs])
    fn = _CustomFunction(prop, op, n_in, len(prop.list_outputs()), aux)
    return fn(*inputs)
