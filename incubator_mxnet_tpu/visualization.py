"""Network visualization (ref python/mxnet/visualization.py print_summary)."""
from __future__ import annotations

import json

import numpy as onp

__all__ = ["print_summary", "plot_network"]


def _node_shapes(symbol, shape):
    """Output shape per internal node + per-arg shapes, via one eval_shape."""
    import jax
    from .ndarray import NDArray

    # auto-created label vars (SoftmaxOutput etc.) have no deferred shape
    # rule — default them to (batch,); grad_req='null' skips grad buffers
    binds = dict(shape)
    batch = next(iter(shape.values()))[0]
    for v in symbol.get_internals():
        if v.is_var and getattr(v, "_is_label", False) and v.name not in binds:
            binds[v.name] = (batch,)
    ex = symbol.simple_bind(grad_req="null", **binds)
    arg_shapes = {k: tuple(v.shape) for k, v in ex.arg_dict.items()}
    internals = [s for s in symbol.get_internals() if not s.is_var]

    def fn(binds):
        b = {k: NDArray(v) for k, v in binds.items()}
        cache = {}
        outs = []
        for s in internals:
            o = s.eval_imperative(b, _cache=cache)
            outs.append(o[0]._data if isinstance(o, (list, tuple)) else o._data)
        return outs

    binds = {k: jax.ShapeDtypeStruct(v, onp.float32)
             for k, v in arg_shapes.items()}
    outs = jax.eval_shape(fn, binds)
    return arg_shapes, {s.name: tuple(o.shape)
                        for s, o in zip(internals, outs)}


def print_summary(symbol, shape=None, line_length=120,
                  positions=(0.44, 0.64, 0.74, 1.0)):
    """ref visualization.py print_summary — layer table with output shapes
    and parameter counts (needs ``shape={'data': (...), ...}``)."""
    nodes = json.loads(symbol.tojson())["nodes"]
    arg_shapes, out_shapes = ({}, {})
    if shape:
        arg_shapes, out_shapes = _node_shapes(symbol, shape)
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    positions = [int(line_length * p) for p in positions]

    def print_row(cells):
        line = ""
        for i, c in enumerate(cells):
            line += str(c)
            line = line[: positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)
    total = 0
    data_names = set(shape or ())
    for node in nodes:
        if node["op"] == "null":
            continue
        ins = [nodes[i[0]] for i in node["inputs"]]
        prev = ", ".join(n["name"] for n in ins)
        n_params = sum(int(onp.prod(arg_shapes[n["name"]])) for n in ins
                       if n["op"] == "null" and n["name"] in arg_shapes
                       and n["name"] not in data_names)
        total += n_params
        print_row(["%s (%s)" % (node["name"], node["op"]),
                   str(out_shapes.get(node["name"], "")), str(n_params), prev])
    print("=" * line_length)
    print("Total params: %d" % total)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None, dtype=None,
                 node_attrs=None, hide_weights=True):
    """DOT-source graph (graphviz rendering optional; returns the source)."""
    nodes = json.loads(symbol.tojson())["nodes"]
    lines = ["digraph %s {" % title, "  rankdir=BT;"]
    for i, node in enumerate(nodes):
        if node["op"] == "null" and hide_weights and node["name"] != "data":
            continue
        label = node["name"] if node["op"] == "null" else \
            "%s\\n%s" % (node["op"], node["name"])
        lines.append('  n%d [label="%s"];' % (i, label))
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for inp in node["inputs"]:
            src = nodes[inp[0]]
            if src["op"] == "null" and hide_weights and src["name"] != "data":
                continue
            lines.append("  n%d -> n%d;" % (inp[0], i))
    lines.append("}")
    return "\n".join(lines)
