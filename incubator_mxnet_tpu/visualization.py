"""Network visualization (ref python/mxnet/visualization.py print_summary)."""
from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """ref visualization.py print_summary — layer table of a Symbol graph."""
    nodes = json.loads(symbol.tojson())["nodes"]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    positions = [int(line_length * p) for p in positions]

    def print_row(cells):
        line = ""
        for i, c in enumerate(cells):
            line += str(c)
            line = line[: positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)
    for node in nodes:
        if node["op"] == "null":
            continue
        prev = ", ".join(nodes[i[0]]["name"] for i in node["inputs"])
        print_row(["%s (%s)" % (node["name"], node["op"]), "", "", prev])
    print("=" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None, dtype=None,
                 node_attrs=None, hide_weights=True):
    """DOT-source graph (graphviz rendering optional; returns the source)."""
    nodes = json.loads(symbol.tojson())["nodes"]
    lines = ["digraph %s {" % title, "  rankdir=BT;"]
    for i, node in enumerate(nodes):
        if node["op"] == "null" and hide_weights and node["name"] != "data":
            continue
        label = node["name"] if node["op"] == "null" else \
            "%s\\n%s" % (node["op"], node["name"])
        lines.append('  n%d [label="%s"];' % (i, label))
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for inp in node["inputs"]:
            src = nodes[inp[0]]
            if src["op"] == "null" and hide_weights and src["name"] != "data":
                continue
            lines.append("  n%d -> n%d;" % (inp[0], i))
    lines.append("}")
    return "\n".join(lines)
