"""Checkpoint save/load (ref python/mxnet/model.py:403-452)."""
from __future__ import annotations

import json
import os

from . import ndarray as nd

__all__ = ["save_checkpoint", "load_checkpoint", "load_params", "BatchEndParam"]


class BatchEndParam:
    """Callback payload (ref model.py BatchEndParam namedtuple)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """model-symbol.json + model-%04d.params (ref model.py:403)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    """ref model.py load_params."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """ref model.py:428 load_checkpoint → (symbol, arg_params, aux_params)."""
    symbol = None
    sym_file = "%s-symbol.json" % prefix
    if os.path.exists(sym_file):
        from .symbol import load as sym_load
        symbol = sym_load(sym_file)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
