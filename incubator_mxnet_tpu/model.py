"""Checkpoint save/load (ref python/mxnet/model.py:403-452)."""
from __future__ import annotations

import json
import os

from . import ndarray as nd

__all__ = ["save_checkpoint", "load_checkpoint", "load_params", "BatchEndParam"]


class BatchEndParam:
    """Callback payload (ref model.py BatchEndParam namedtuple)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """model-symbol.json + model-%04d.params (ref model.py:403)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    """ref model.py load_params."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """ref model.py:428 load_checkpoint → (symbol, arg_params, aux_params)."""
    symbol = None
    sym_file = "%s-symbol.json" % prefix
    if os.path.exists(sym_file):
        from .symbol import load as sym_load
        symbol = sym_load(sym_file)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy training API (ref python/mxnet/model.py:403 FeedForward) —
    a thin veneer over Module, kept for reference-era scripts; new code
    should use Module or Gluon."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, begin_epoch=0, **kwargs):
        from . import initializer as init_mod
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self._opt_kwargs = {k: v for k, v in kwargs.items()
                            if k in ("learning_rate", "momentum", "wd",
                                     "clip_gradient", "rescale_grad")}
        self._module = None

    def _mod(self):
        from .module import Module
        if self._module is None:
            self._module = Module(self.symbol, context=self.ctx)
        return self._module

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        """ref model.py FeedForward.fit."""
        from . import io as mx_io
        if not hasattr(X, "provide_data"):
            X = mx_io.NDArrayIter(X, y, batch_size=self.numpy_batch_size,
                                  shuffle=True)
        mod = self._mod()
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=self._opt_kwargs,
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch, monitor=monitor,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def _ensure_ready(self, data_iter):
        """Bind+install params for inference (the load() -> predict() path —
        ref FeedForward._init_predictor)."""
        mod = self._mod()
        if not mod.binded:
            mod.bind(data_shapes=data_iter.provide_data,
                     label_shapes=None, for_training=False)
        if not mod.params_initialized:
            if self.arg_params is None:
                raise ValueError("FeedForward has no parameters: call fit() "
                                 "or construct with arg_params")
            mod.set_params(self.arg_params, self.aux_params or {})
        return mod

    def predict(self, X, num_batch=None):
        """ref model.py FeedForward.predict (multi-output symbols return a
        list, matching the reference)."""
        from . import io as mx_io
        import numpy as onp

        def to_np(o):
            return o.asnumpy() if hasattr(o, "asnumpy") else onp.asarray(o)

        if not hasattr(X, "provide_data"):
            X = mx_io.NDArrayIter(X, None, batch_size=self.numpy_batch_size)
        outs = self._ensure_ready(X).predict(X, num_batch=num_batch)
        if isinstance(outs, (list, tuple)):
            return to_np(outs[0]) if len(outs) == 1 else [to_np(o) for o in outs]
        return to_np(outs)

    def score(self, X, eval_metric="acc", num_batch=None):
        from . import metric as metric_mod
        m = metric_mod.create(eval_metric)
        res = self._ensure_ready(X).score(X, m, num_batch=num_batch)
        return dict(res)[m.name] if res else None

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None else
                        (self.num_epoch or 0), self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        from .symbol import load as sym_load
        sym = sym_load("%s-symbol.json" % prefix)
        arg, aux = load_params(prefix, epoch)
        return FeedForward(sym, ctx=ctx, arg_params=arg, aux_params=aux,
                           begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, optimizer="sgd",
               initializer=None, **kwargs):
        """ref model.py FeedForward.create — construct + fit."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            optimizer=optimizer, initializer=initializer,
                            **kwargs)
        return model.fit(X, y)
