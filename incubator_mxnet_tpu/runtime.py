"""Runtime feature detection (ref python/mxnet/runtime.py, include/mxnet/libinfo.h)."""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return "[%s %s]" % ("✔" if self.enabled else "✖", self.name)


def _detect():
    import jax

    feats = {
        "TPU": any(d.platform in ("tpu", "axon") for d in _safe_devices(jax)),
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "XLA": True,
        "PALLAS": True,
        "BF16": True,
        "INT64_TENSOR_SIZE": True,
        "DIST_KVSTORE": True,
        "SPMD_SHARDING": True,
        "RING_ATTENTION": True,
        "OPENMP": True,
        "NATIVE_RECORDIO": _has_native(),
        "SSE": True,
        "F16C": True,
        "MKLDNN": False,
        "OPENCV": _has_pil(),
    }
    return {k: Feature(k, v) for k, v in feats.items()}


def _safe_devices(jax):
    try:
        return jax.devices()
    except RuntimeError:
        return []


def _has_native():
    try:
        from .native import lib as _lib
        return _lib.available()
    except Exception:
        return False


def _has_pil():
    try:
        import PIL  # noqa
        return True
    except ImportError:
        return False


class Features(dict):
    """ref runtime.py Features."""

    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("Feature '%s' is unknown" % feature_name)
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
