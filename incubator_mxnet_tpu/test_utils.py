"""Test utilities shipped with the package (ref python/mxnet/test_utils.py, 2,599 LoC).

Reference parity: assert_almost_equal, check_numeric_gradient (finite
differences vs autograd), check_consistency (cross-device/dtype), rand_ndarray,
default_context switching — the fixtures the whole reference test suite reuses.
"""
from __future__ import annotations

import numpy as onp

from . import autograd, context as ctx_mod
from . import ndarray as nd
from .context import Context, cpu, current_context
from .ndarray import NDArray

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "rand_ndarray", "rand_shape_2d", "rand_shape_3d",
           "rand_shape_nd", "check_numeric_gradient", "check_consistency",
           "numeric_grad", "simple_forward", "same", "random_seed",
           "op_consistency_sweep", "grad_consistency_sweep", "SWEEP_TOLS",
           "SWEEP_SKIP", "sweep_coverage", "sweep_inputs"]

_default_ctx = [None]


def default_context():
    return _default_ctx[0] if _default_ctx[0] is not None else current_context()


def set_default_context(ctx):
    _default_ctx[0] = ctx


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return onp.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"), equal_nan=False):
    a, b = _as_np(a), _as_np(b)
    if not onp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        index = onp.unravel_index(onp.argmax(onp.abs(a - b)), a.shape) if a.shape else ()
        rel = onp.abs(a - b) / (onp.abs(b) + atol + 1e-30)
        raise AssertionError(
            "Error %f exceeds tolerance rtol=%g atol=%g. Worst at %s: %s vs %s"
            % (float(rel.max()) if rel.size else 0.0, rtol, atol, index,
               a[index] if a.shape else a, b[index] if b.shape else b))


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype="float32", ctx=None,
                 scale=1.0):
    """ref test_utils.py rand_ndarray — density controls sparse fill."""
    dense = onp.random.uniform(-scale, scale, size=shape).astype(dtype)
    if stype == "default":
        return nd.array(dense, ctx=ctx)
    if density is None:
        density = 0.5
    if stype == "row_sparse":
        row_mask = onp.random.rand(shape[0]) < density
        dense = dense * row_mask.reshape((-1,) + (1,) * (len(shape) - 1))
        return nd.array(dense, ctx=ctx).tostype("row_sparse")
    if stype == "csr":
        mask = onp.random.rand(*shape) < density
        return nd.array(dense * mask, ctx=ctx).tostype("csr")
    raise ValueError("unknown stype %r" % stype)


def simple_forward(sym_or_fn, ctx=None, is_train=False, **inputs):
    outs = sym_or_fn(**{k: nd.array(v) for k, v in inputs.items()})
    if isinstance(outs, (list, tuple)):
        return [o.asnumpy() for o in outs]
    return outs.asnumpy()


def numeric_grad(f, xs, eps=1e-4):
    """Central finite differences of scalar-valued f over list of np arrays."""
    grads = []
    for i, x in enumerate(xs):
        g = onp.zeros_like(x, dtype=onp.float64)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(f(xs))
            flat[j] = orig - eps
            fm = float(f(xs))
            flat[j] = orig
            gf[j] = (fp - fm) / (2 * eps)
        grads.append(g.astype(x.dtype))
    return grads


def check_numeric_gradient(fn, inputs, rtol=1e-2, atol=1e-3, eps=1e-3):
    """Finite differences vs autograd (ref test_utils.py check_numeric_gradient).

    fn: callable taking NDArrays, returning one NDArray (summed to scalar).
    inputs: list of numpy arrays (float32/float64).
    """
    xs = [onp.asarray(x, dtype=onp.float64) for x in inputs]

    def f(arrs):
        vals = [nd.array(a.astype(onp.float32)) for a in arrs]
        return fn(*vals).sum().asscalar()

    expected = numeric_grad(f, xs, eps)

    arrs = [nd.array(x.astype(onp.float32)) for x in xs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrs).sum()
    out.backward()
    for a, e in zip(arrs, expected):
        assert_almost_equal(a.grad.asnumpy(), e.astype(onp.float32), rtol=rtol, atol=atol)


def check_consistency(fn, inputs, ctx_list=None, dtypes=("float32",), rtol=1e-3,
                      atol=1e-4):
    """Run fn under several contexts/dtypes and compare outputs
    (ref test_utils.py check_consistency — the de-facto cross-backend check)."""
    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    results = []
    for ctx in ctx_list:
        for dt in dtypes:
            with ctx:
                arrs = [nd.array(onp.asarray(x, dtype=dt), ctx=ctx) for x in inputs]
                results.append(fn(*arrs).asnumpy().astype("float32"))
    base = results[0]
    for r in results[1:]:
        assert_almost_equal(r, base, rtol=rtol, atol=atol)


# ----------------------------------------------------------------- sweep
#: ops excluded from the registry sweep, each with the reason — the
#: coverage test (tests/test_numerics_sweep.py) fails when a public nd
#: callable is neither in the table nor here, so a new op can't silently
#: skip the walk (round-4 verdict Next #3).
def _sweep_skip():
    # the host-side exclusions are the SAME table the symbolic
    # auto-registration uses (symbol/__init__.py) — one source of truth —
    # plus two sweep-only entries
    from .symbol import _SYM_EXCLUDE
    skip = dict(_SYM_EXCLUDE)
    skip["Custom"] = "needs a registered op_type; exercised in test_extension"
    skip["reset_arrays"] = "in-place void op; exercised in test_optimizer_ops"
    for _n in dir(nd):
        if _n.startswith("linalg_"):
            skip[_n] = ("flat alias of nd.linalg.%s (family numerics swept "
                        "via the linalg.gemm2 entry; ONNX MatMul import "
                        "rides linalg_gemm2)" % _n[len("linalg_"):])
    return skip


SWEEP_SKIP = _sweep_skip()


def _sweep_table():
    """Op table for the cross-backend numerics sweep (the reference's
    test_operator_gpu.py re-run-everything-on-device trick, distilled to
    an op walk over the WHOLE nd registry).

    Each entry: (name[@tag], fn(M, *arrays) -> output, input specs[, opts]).
    ``M`` is the namespace the op is drawn from — ``nd`` for the numeric
    sweeps, ``mx.sym`` for the symbolic-parity walk (tests/test_sym_parity.py)
    — the same table drives both, the way the reference generates both
    frontends from one registry. A spec is (shape, kind):
      'f' float in (-2,2)    'pos' |f|+0.5       'unit' (-0.9,0.9)
      'gt1' |f|+1.5          'perm' distinct floats (sortable)
      'b' 0/1 floats         'pmf' positive rows summing to 1
      'len' 1..dim0 lengths  ('i', hi) int32 in [0,hi)
      ('i1', hi) in [1,hi)   ('const', array) fixed payload
    opts: {'op': registry name if != entry name, 'nondiff': True to skip
    the grad walk, 'seed': True to reseed the framework PRNG per leg,
    'sym': False to skip the symbolic walk (reason string in 'symreason')}.
    """
    from .ndarray import linalg  # noqa: F401  (namespace touch)
    from .ndarray import rnn_param_size

    def f(*shape):
        return (shape, "f")

    def pos(*shape):
        return (shape, "pos")

    def idx(*shape, hi=4):
        return (shape, ("i", hi))

    def mk(name, *specs, call=None, tag=None, **opts):
        """Entry builder: op looked up on M by name at call time."""
        entry = name if tag is None else name + "@" + tag
        if call is None:
            def call_(M, *a, _n=name):
                return getattr(M, _n)(*a)
            call = call_
        return (entry, call, list(specs), opts)

    def kw(name, kwargs, *specs, tag=None, **opts):
        def call(M, *a, _n=name, _k=kwargs):
            return getattr(M, _n)(*a, **_k)
        return ((name if tag is None else name + "@" + tag), call,
                list(specs), opts)

    t = []

    # ---- unary elementwise, bulk families
    UNARY_F = ["abs", "sign", "square", "sin", "cos", "tan", "arctan",
               "sinh", "cosh", "tanh", "arcsinh", "sigmoid", "relu",
               "softsign", "erf", "negative", "identity", "zeros_like",
               "ones_like", "BlockGrad", "stop_gradient", "make_loss",
               "hard_sigmoid", "degrees", "radians", "exp", "expm1",
               "logical_not", "flatten", "Flatten"]
    UNARY_ND = ["round", "rint", "fix", "ceil", "floor", "trunc", "sign",
                "logical_not"]
    UNARY_POS = ["sqrt", "rsqrt", "cbrt", "rcbrt", "log", "log10", "log2",
                 "log1p", "reciprocal", "gamma", "gammaln", "digamma"]
    UNARY_UNIT = ["arcsin", "arccos", "arctanh", "erfinv"]
    TRANS = {"exp", "expm1", "log", "log10", "log2", "log1p", "sqrt",
             "rsqrt", "cbrt", "rcbrt", "sin", "cos", "tan", "arcsin",
             "arccos", "arctan", "sinh", "cosh", "tanh", "arcsinh",
             "arccosh", "arctanh", "sigmoid", "softsign", "erf", "erfinv",
             "gamma", "gammaln", "digamma", "reciprocal", "power",
             "hypot", "arctan2", "norm", "softmax", "log_softmax",
             "softmin"}
    for n in UNARY_F:
        if hasattr(nd, n):
            nondiff = n in UNARY_ND
            t.append(mk(n, f(4, 16), tag="trans" if n in TRANS else None,
                        nondiff=nondiff))
    for n in UNARY_ND:
        if hasattr(nd, n) and n not in UNARY_F:
            t.append(mk(n, f(4, 16), nondiff=True))
    for n in UNARY_POS:
        if hasattr(nd, n):
            t.append(mk(n, pos(4, 16), tag="trans"))
    for n in UNARY_UNIT:
        if hasattr(nd, n):
            t.append(mk(n, ((4, 16), "unit"), tag="trans"))
    t.append(mk("arccosh", ((4, 16), "gt1"), tag="trans"))
    t.append(kw("clip", dict(a_min=-1.0, a_max=1.0), f(4, 16)))
    t.append(kw("smooth_l1", dict(scalar=1.0), f(4, 16)))
    t.append(kw("IdentityAttachKLSparseReg", dict(sparseness_target=0.1),
                ((4, 16), "unit")))
    t.append(kw("cast", dict(dtype="float16"), f(4, 16)))
    t.append(kw("Cast", dict(dtype="float16"), f(4, 16)))
    t.append(kw("amp_cast", dict(dtype="float16"), f(4, 16)))
    t.append(mk("amp_multicast", f(4, 16), f(4, 16),
                call=lambda M, a, b: M.amp_multicast(a, b, num_outputs=2)))

    # ---- binary elementwise
    BIN_FF = ["add", "subtract", "multiply", "maximum", "minimum",
              "hypot", "arctan2", "elemwise_add", "elemwise_sub",
              "elemwise_mul"]
    BIN_FPOS = ["divide", "true_divide", "mod", "modulo", "elemwise_div"]
    BIN_CMP = ["equal", "not_equal", "greater", "greater_equal", "lesser",
               "lesser_equal"]
    BIN_LOGIC = ["logical_and", "logical_or", "logical_xor"]
    for n in BIN_FF:
        if hasattr(nd, n):
            t.append(mk(n, f(4, 16), f(4, 16),
                        tag="trans" if n in TRANS else None))
    for n in BIN_FPOS:
        if hasattr(nd, n):
            t.append(mk(n, f(4, 16), pos(4, 16)))
    for n in BIN_CMP:
        t.append(mk(n, f(4, 16), f(4, 16), nondiff=True))
    for n in BIN_LOGIC:
        t.append(mk(n, ((4, 16), "b"), ((4, 16), "b"), nondiff=True))
    t.append(mk("power", pos(4, 16), f(4, 16), tag="trans"))

    # ---- broadcast binary family
    BCAST_FF = ["broadcast_add", "broadcast_plus", "broadcast_sub",
                "broadcast_minus", "broadcast_subtract", "broadcast_mul",
                "broadcast_multiply", "broadcast_maximum",
                "broadcast_minimum", "broadcast_hypot",
                "broadcast_arctan2"]
    BCAST_FPOS = ["broadcast_div", "broadcast_divide", "broadcast_mod",
                  "broadcast_modulo"]
    BCAST_CMP = ["broadcast_equal", "broadcast_not_equal",
                 "broadcast_greater", "broadcast_greater_equal",
                 "broadcast_lesser", "broadcast_lesser_equal"]
    BCAST_LOGIC = ["broadcast_logical_and", "broadcast_logical_or",
                   "broadcast_logical_xor"]
    for n in BCAST_FF:
        if hasattr(nd, n):
            t.append(mk(n, f(4, 16), f(1, 16),
                        tag="trans" if n.replace("broadcast_", "") in TRANS
                        else None))
    for n in BCAST_FPOS:
        if hasattr(nd, n):
            t.append(mk(n, f(4, 16), pos(1, 16)))
    for n in BCAST_CMP:
        t.append(mk(n, f(4, 16), f(1, 16), nondiff=True))
    for n in BCAST_LOGIC:
        t.append(mk(n, ((4, 16), "b"), ((1, 16), "b"), nondiff=True))
    t.append(mk("broadcast_power", pos(4, 16), f(1, 16), tag="trans"))

    # ---- reductions
    for n in ["sum", "mean", "max", "min"]:
        t.append(kw(n, dict(axis=1), f(8, 64)))
    t.append(kw("prod", dict(axis=1), f(8, 8)))
    t.append(kw("norm", dict(axis=1), f(8, 64), tag="trans"))
    t.append(kw("argmax", dict(axis=1), ((8, 64), "perm"), nondiff=True))
    t.append(kw("argmin", dict(axis=1), ((8, 64), "perm"), nondiff=True))
    t.append(kw("moments", dict(axes=1), f(8, 16)))
    t.append(mk("all_finite", f(4, 16), nondiff=True))
    t.append(mk("multi_all_finite", f(4, 16), f(4, 16), nondiff=True,
                call=lambda M, a, b: M.multi_all_finite(a, b, num_arrays=2)))
    t.append(mk("multi_sum_sq", f(4, 16), f(4, 16),
                call=lambda M, a, b: M.multi_sum_sq(a, b, num_arrays=2)))
    t.append(mk("multi_lars", pos(4), pos(4), pos(4), pos(4),
                call=lambda M, lr, w, g, wd: M.multi_lars(
                    lr, w, g, wd, eta=0.001), nondiff=True))

    # ---- shape / layout
    t.append(kw("reshape", dict(shape=(8, 8)), f(4, 16)))
    t.append(mk("reshape_like", f(4, 16), f(8, 8)))
    t.append(kw("transpose", dict(axes=(1, 0, 2)), f(3, 4, 5)))
    t.append(kw("swapaxes", dict(dim1=0, dim2=1), f(3, 4, 5)))
    t.append(kw("SwapAxis", dict(dim1=0, dim2=1), f(3, 4, 5)))
    t.append(kw("expand_dims", dict(axis=1), f(4, 16)))
    t.append(kw("squeeze", dict(axis=1), f(4, 1, 16)))
    t.append(kw("tile", dict(reps=(2, 2)), f(3, 4)))
    t.append(kw("repeat", dict(repeats=2, axis=1), f(3, 4)))
    t.append(kw("pad", dict(mode="constant",
                            pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
                f(2, 3, 4, 4)))
    t.append(kw("flip", dict(axis=1), f(3, 4)))
    t.append(kw("reverse", dict(axis=1), f(3, 4)))
    t.append(kw("depth_to_space", dict(block_size=2), f(1, 8, 3, 3)))
    t.append(kw("space_to_depth", dict(block_size=2), f(1, 2, 6, 6)))
    t.append(kw("diag", dict(k=0), f(5, 5)))
    t.append(kw("moveaxis", dict(source=0, destination=1), f(3, 4, 5)))
    t.append(kw("broadcast_to", dict(shape=(4, 16)), f(1, 16)))
    t.append(mk("broadcast_like", f(1, 16), f(4, 16)))
    t.append(kw("broadcast_axis", dict(axis=0, size=4), f(1, 16)))
    t.append(kw("slice", dict(begin=(1, 2), end=(3, 10)), f(4, 16)))
    t.append(kw("slice_axis", dict(axis=1, begin=2, end=10), f(4, 16)))
    t.append(mk("slice_like", f(8, 16), f(4, 8)))
    t.append(kw("split", dict(num_outputs=2, axis=1), f(4, 16)))
    t.append(kw("SliceChannel", dict(num_outputs=2, axis=1), f(4, 16)))
    t.append(kw("split_v2", dict(indices_or_sections=2, axis=1), f(4, 16)))
    t.append(kw("stack", dict(axis=0), f(3, 4), f(3, 4)))
    t.append(kw("concat", dict(dim=1), f(4, 8), f(4, 8)))
    t.append(kw("Concat", dict(dim=1), f(4, 8), f(4, 8)))
    t.append(mk("concatenate", f(4, 8), f(4, 8),
                call=lambda M, a, b: M.concatenate([a, b], axis=0)))
    t.append(kw("Crop", dict(offset=(1, 1), h_w=(4, 4), num_args=1),
                f(1, 2, 8, 8)))
    t.append(mk("meshgrid", f(4), f(5)))
    t.append(kw("arange_like", dict(start=0.0, step=1.0), f(4, 16),
                nondiff=True))
    t.append(mk("shape_array", f(4, 16), nondiff=True))
    t.append(mk("size_array", f(4, 16), nondiff=True))
    t.append(mk("add_n", f(4, 16), f(4, 16), f(4, 16)))

    # ---- indexing / ordering
    t.append(mk("take", f(16, 8), idx(6, hi=16)))
    t.append(kw("pick", dict(axis=-1), f(4, 16), idx(4, hi=16)))
    t.append(kw("one_hot", dict(depth=16), idx(6, hi=16), nondiff=True))
    t.append(mk("gather_nd", f(5, 5), idx(2, 4, hi=5)))
    t.append(kw("scatter_nd", dict(shape=(5, 5)), f(4), idx(2, 4, hi=5)))
    t.append(mk("batch_take", f(4, 8), idx(4, hi=8)))
    t.append(kw("topk", dict(k=3, ret_typ="value"), ((4, 16), "perm")))
    t.append(kw("sort", dict(axis=-1), ((4, 16), "perm")))
    t.append(kw("argsort", dict(axis=-1), ((4, 16), "perm"), nondiff=True))
    t.append(mk("argmax_channel", ((4, 16), "perm"), nondiff=True))
    t.append(mk("where", ((4, 16), "b"), f(4, 16), f(4, 16)))
    t.append(kw("unravel_index", dict(shape=(4, 6)), idx(6, hi=24),
                nondiff=True))
    t.append(kw("ravel_multi_index", dict(shape=(4, 6)), idx(2, 6, hi=4),
                nondiff=True))
    t.append(mk("onehot_encode", idx(6, hi=8), f(6, 8), nondiff=True))
    t.append(kw("histogram", dict(bins=5, range=(-2.0, 2.0)), f(64),
                nondiff=True))
    t.append(mk("shuffle", f(8, 4), seed=True, nondiff=True))
    t.append(kw("multinomial", dict(shape=3), ((4, 8), "pmf"), seed=True,
                nondiff=True))
    # sequence family (float lengths, mask semantics)
    t.append(kw("sequence_mask", dict(use_sequence_length=True),
                f(5, 3, 4), ((3,), "len5"), nondiff=True))
    t.append(kw("SequenceMask", dict(use_sequence_length=True),
                f(5, 3, 4), ((3,), "len5"), nondiff=True))
    t.append(kw("SequenceLast", dict(use_sequence_length=True),
                f(5, 3, 4), ((3,), "len5"), nondiff=True))
    t.append(kw("SequenceReverse", dict(use_sequence_length=True),
                f(5, 3, 4), ((3,), "len5"), nondiff=True))

    # ---- matmul-class
    t.append(mk("dot", f(8, 32), f(32, 8), tag="mm"))
    t.append(mk("batch_dot", f(2, 8, 16), f(2, 16, 8), tag="mm"))
    t.append(mk("khatri_rao", f(4, 8), f(3, 8), tag="mm"))
    t.append(kw("trace", dict(offset=0, axis1=0, axis2=1), f(6, 6)))
    t.append(mk("linalg.gemm2", f(8, 32), f(32, 8), tag="mm",
                call=lambda M, a, b: (nd if M is nd else M).linalg.gemm2(a, b),
                op="linalg.gemm2"))

    # ---- nn layers
    t.append(mk("FullyConnected", f(4, 32), f(8, 32), f(8), tag="mm",
                call=lambda M, x, w, b: M.FullyConnected(x, w, b,
                                                         num_hidden=8)))
    t.append(mk("Convolution", f(2, 4, 8, 8), f(8, 4, 3, 3), tag="mm",
                call=lambda M, x, w: M.Convolution(
                    x, w, None, kernel=(3, 3), num_filter=8, pad=(1, 1),
                    no_bias=True)))
    t.append(mk("Convolution_v1", f(2, 4, 8, 8), f(8, 4, 3, 3), tag="mm",
                call=lambda M, x, w: M.Convolution_v1(
                    x, w, None, kernel=(3, 3), num_filter=8, pad=(1, 1),
                    no_bias=True)))
    t.append(mk("Deconvolution", f(2, 4, 8, 8), f(4, 8, 3, 3), tag="mm",
                call=lambda M, x, w: M.Deconvolution(
                    x, w, None, kernel=(3, 3), num_filter=8, pad=(1, 1),
                    no_bias=True)))
    t.append(mk("Pooling", f(2, 4, 8, 8),
                call=lambda M, x: M.Pooling(x, kernel=(2, 2),
                                            pool_type="max", stride=(2, 2))))
    t.append(mk("Pooling_avg", f(2, 4, 8, 8), op="Pooling",
                call=lambda M, x: M.Pooling(x, kernel=(2, 2),
                                            pool_type="avg", stride=(2, 2))))
    t.append(mk("Pooling_v1", f(2, 4, 8, 8),
                call=lambda M, x: M.Pooling_v1(x, kernel=(2, 2),
                                               pool_type="max",
                                               stride=(2, 2))))
    for bn_name in ["BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm"]:
        t.append(mk(bn_name, f(2, 4, 8, 8), f(4), pos(4), f(4), pos(4),
                    call=lambda M, x, g, b, mm, mv, _n=bn_name: getattr(M, _n)(
                        x, g, b, mm, mv, fix_gamma=False,
                        use_global_stats=True)))
    t.append(mk("LayerNorm", f(4, 16), f(16), f(16),
                call=lambda M, x, g, b: M.LayerNorm(x, g, b, axis=-1)))
    t.append(mk("GroupNorm", f(2, 4, 8, 8), f(4), f(4),
                call=lambda M, x, g, b: M.GroupNorm(x, g, b, num_groups=2)))
    t.append(mk("InstanceNorm", f(2, 4, 8, 8), f(4), f(4)))
    t.append(kw("Dropout", dict(p=0.0), f(4, 16)))
    t.append(kw("Activation", dict(act_type="softrelu"), f(4, 16),
                tag="trans"))
    t.append(kw("LeakyReLU", dict(act_type="leaky", slope=0.1), f(4, 16)))
    t.append(kw("SoftmaxActivation", dict(mode="instance"), f(4, 16),
                tag="trans"))
    for n in ["softmax", "log_softmax", "softmin"]:
        t.append(kw(n, dict(axis=-1), f(4, 16), tag="trans"))
    t.append(mk("softmax_cross_entropy", f(4, 16), idx(4, hi=16),
                tag="trans"))
    t.append(mk("Embedding", idx(6, hi=16), f(16, 8),
                call=lambda M, i, w: M.Embedding(i, w, input_dim=16,
                                                 output_dim=8)))
    t.append(mk("SoftmaxOutput", f(4, 8), idx(4, hi=8), tag="trans"))
    t.append(mk("LinearRegressionOutput", f(4, 8), f(4, 8)))
    t.append(mk("LogisticRegressionOutput", f(4, 8), ((4, 8), "b"),
                tag="trans"))
    t.append(mk("MAERegressionOutput", f(4, 8), f(4, 8)))
    t.append(mk("CTCLoss", f(6, 2, 5), ((2, 3), ("i1", 5)), tag="trans"))
    t.append(mk("ctc_loss", f(6, 2, 5), ((2, 3), ("i1", 5)), tag="trans"))
    t.append(kw("L2Normalization", dict(mode="instance"), f(4, 16)))
    t.append(kw("LRN", dict(nsize=3), f(2, 4, 6, 6)))
    t.append(kw("UpSampling", dict(scale=2, sample_type="nearest"),
                f(1, 2, 4, 4)))
    t.append(kw("BilinearResize2D", dict(height=6, width=6), f(1, 2, 4, 4)))
    t.append(kw("Correlation", dict(kernel_size=1, max_displacement=2),
                f(2, 3, 8, 8), f(2, 3, 8, 8)))
    t.append(kw("im2col", dict(kernel=(3, 3), pad=(1, 1)), f(2, 3, 8, 8)))
    t.append(kw("col2im", dict(output_size=(8, 8), kernel=(3, 3),
                               pad=(1, 1)), f(2, 27, 64)))
    t.append(kw("ROIPooling", dict(pooled_size=(2, 2), spatial_scale=1.0),
                f(1, 3, 8, 8),
                ((onp.array([[0, 0, 0, 4, 4], [0, 1, 1, 6, 6]],
                            dtype="float32"),), "const")))
    t.append(mk("BilinearSampler", f(1, 2, 6, 6), ((1, 2, 4, 4), "unit")))
    t.append(kw("GridGenerator", dict(transform_type="affine",
                                      target_shape=(4, 4)),
                ((2, 6), "unit")))
    t.append(kw("SpatialTransformer",
                dict(target_shape=(4, 4), transform_type="affine",
                     sampler_type="bilinear"),
                f(1, 2, 8, 8), ((1, 6), "unit")))
    _rnn_n = rnn_param_size("rnn_tanh", 4, 8, 1)
    t.append(mk("RNN", f(3, 2, 4), f(_rnn_n), f(1, 2, 8), tag="trans",
                call=lambda M, x, p, s: M.RNN(x, p, s, state_size=8,
                                              num_layers=1,
                                              mode="rnn_tanh")))

    # ---- optimizer update ops (nondiff: parity of the update rule itself)
    # spec kinds: variance-class accumulator states must be positive
    OPT2 = {"sgd_update": "fg", "signsgd_update": "fg",
            "mp_sgd_update": "fgf", "sgd_mom_update": "fgf",
            "signum_update": "fgf", "nag_mom_update": "fgf",
            "mp_sgd_mom_update": "fgff", "mp_nag_mom_update": "fgff",
            "rmsprop_update": "fgp", "adam_update": "fgfp",
            "ftrl_update": "fgfp", "ftml_update": "fgfpf"}
    for n, kinds in OPT2.items():
        specs = [f(4, 8) if k in "fg" else pos(4, 8) for k in kinds]
        t.append(mk(n, *specs, nondiff=True,
                    call=lambda M, *a, _n=n: getattr(M, _n)(*a, lr=0.1)))
    # centered RMSProp: the n state must dominate g^2 (n - g^2 under the
    # sqrt), so n starts >1.5 while the g state stays in the unit ball
    t.append(mk("rmspropalex_update", f(4, 8), f(4, 8), ((4, 8), "gt1"),
                ((4, 8), "unit"), f(4, 8), nondiff=True,
                call=lambda M, w, g, n_, gs, d: M.rmspropalex_update(
                    w, g, n_, gs, d, lr=0.1)))
    for n in ["lamb_update_phase1", "mp_lamb_update_phase1"]:
        t.append(mk(n, f(4, 8), f(4, 8), f(4, 8), pos(4, 8), nondiff=True,
                    call=lambda M, w, g, m, v, _n=n: getattr(M, _n)(
                        w, g, m, v, t=1)))
    for n in ["lamb_update_phase2", "mp_lamb_update_phase2"]:
        t.append(mk(n, f(4, 8), f(4, 8), pos(1), pos(1), nondiff=True,
                    call=lambda M, w, g, r1, r2, _n=n: getattr(M, _n)(
                        w, g, r1, r2, lr=0.1)))
    t.append(mk("multi_sgd_update", f(4, 8), f(4, 8), nondiff=True,
                call=lambda M, w, g: M.multi_sgd_update(
                    [w], [g], lrs=[0.1], wds=[0.0])))
    t.append(mk("multi_sgd_mom_update", f(4, 8), f(4, 8), f(4, 8),
                nondiff=True,
                call=lambda M, w, g, m: M.multi_sgd_mom_update(
                    [w], [g], [m], lrs=[0.1], wds=[0.0])))
    t.append(mk("multi_mp_sgd_update", f(4, 8), f(4, 8), f(4, 8),
                nondiff=True,
                call=lambda M, w, g, w32: M.multi_mp_sgd_update(
                    [w], [g], [w32], lrs=[0.1], wds=[0.0])))
    t.append(mk("multi_mp_sgd_mom_update", f(4, 8), f(4, 8), f(4, 8),
                f(4, 8), nondiff=True,
                call=lambda M, w, g, m, w32: M.multi_mp_sgd_mom_update(
                    [w], [g], [m], [w32], lrs=[0.1], wds=[0.0])))
    t.append(mk("preloaded_multi_sgd_update", f(4, 8), f(4, 8), pos(1),
                pos(1), nondiff=True,
                call=lambda M, w, g, lr, wd: M.preloaded_multi_sgd_update(
                    [w], [g], lr, wd)))
    t.append(mk("preloaded_multi_sgd_mom_update", f(4, 8), f(4, 8),
                f(4, 8), pos(1), pos(1), nondiff=True,
                call=lambda M, w, g, m, lr, wd:
                M.preloaded_multi_sgd_mom_update([w], [g], [m], lr, wd)))
    t.append(mk("preloaded_multi_mp_sgd_update", f(4, 8), f(4, 8), f(4, 8),
                pos(1), pos(1), nondiff=True,
                call=lambda M, w, g, w32, lr, wd:
                M.preloaded_multi_mp_sgd_update([w], [g], [w32], lr, wd)))
    t.append(mk("preloaded_multi_mp_sgd_mom_update", f(4, 8), f(4, 8),
                f(4, 8), f(4, 8), pos(1), pos(1), nondiff=True,
                call=lambda M, w, g, m, w32, lr, wd:
                M.preloaded_multi_mp_sgd_mom_update([w], [g], [m], [w32],
                                                    lr, wd)))

    # ---- creation ops (nullary; cross-leg determinism)
    t.append(mk("zeros", call=lambda M: M.zeros((3, 4)), nondiff=True))
    t.append(mk("ones", call=lambda M: M.ones((3, 4)), nondiff=True))
    t.append(mk("full", call=lambda M: M.full((3, 4), 2.5), nondiff=True))
    t.append(mk("eye", call=lambda M: M.eye(4), nondiff=True))
    t.append(mk("arange", call=lambda M: M.arange(0, 8), nondiff=True))
    t.append(mk("linspace", call=lambda M: M.linspace(0.0, 1.0, 5),
                nondiff=True))

    # ---- sparse storage round-trip (dense-comparable via tostype)
    t.append(mk("cast_storage", f(4, 16), nondiff=True, sym=False,
                symreason="sparse storage is eager-only (README Sparse)",
                call=lambda M, a: M.cast_storage(a, "row_sparse")))

    return t


#: per-dtype (rtol, atol) for the sweep; bf16 has 8 mantissa bits, fp16 10.
#: 'trans'-tagged ops (transcendentals) get the looser fp32 row — XLA
#: backends use different polynomial approximations, parity is ~1e-3 not
#: ULP. 'mm'-tagged ops run under jax.default_matmul_precision('highest')
#: so the sweep checks ARITHMETIC parity; the MXU's default bf16-multiply
#: fp32-accumulate mode is a documented perf trade (MXTPU_MATMUL_PRECISION).
SWEEP_TOLS = {"float32": (1e-4, 1e-5), "bfloat16": (4e-2, 2e-2),
              "float16": (1e-2, 2e-3)}
SWEEP_TOLS_TRANS = {"float32": (2e-3, 1e-4), "bfloat16": (4e-2, 2e-2),
                    "float16": (1e-2, 2e-3)}


def _norm_entry(entry):
    """Entries are (name, fn, specs) or (name, fn, specs, opts)."""
    if len(entry) == 3:
        name, fn, specs = entry
        return name, fn, specs, {}
    return entry


def _spec_is_float(kind):
    return kind in ("f", "pos", "unit", "gt1", "perm", "pmf", "b") or \
        (isinstance(kind, str) and kind.startswith("len"))


def _gen_input(rng, shape, kind):
    """Synthesize one input array for a spec kind (see _sweep_table doc)."""
    if kind == "const":
        return shape[0].copy()   # spec carries the payload in `shape`
    if isinstance(kind, tuple):
        k0 = kind[0]
        if k0 == "i":
            return rng.randint(0, kind[1], size=shape).astype("int32")
        if k0 == "i1":
            return rng.randint(1, kind[1], size=shape).astype("int32")
        raise ValueError("unknown spec kind %r" % (kind,))
    if kind == "b":
        return rng.randint(0, 2, size=shape).astype("float32")
    if kind == "perm":
        n = int(onp.prod(shape)) if shape else 1
        return (rng.permutation(n).astype("float32") / n).reshape(shape)
    if kind == "pmf":
        a = onp.abs(rng.uniform(0.1, 1.0, size=shape)).astype("float32")
        return a / a.sum(axis=-1, keepdims=True)
    if kind.startswith("len"):
        hi = int(kind[3:] or 4)
        return rng.randint(1, hi + 1, size=shape).astype("float32")
    a = rng.uniform(-2.0, 2.0, size=shape).astype("float32")
    if kind == "pos":
        a = onp.abs(a) + 0.5
    elif kind == "unit":
        a = onp.clip(a * 0.45, -0.9, 0.9)
    elif kind == "gt1":
        a = onp.abs(a) + 1.5
    return a


def sweep_inputs(specs, seed=0):
    """Public input-synthesis hook (shared with tests/test_sym_parity.py)."""
    rng = onp.random.RandomState(seed)
    return [_gen_input(rng, shape, kind) for shape, kind in specs]


def _norm_outputs(o):
    """Flatten an op result to a list of float32 numpy arrays (sparse
    densified, multi-output listed)."""
    from .ndarray.sparse import BaseSparseNDArray
    outs = o if isinstance(o, (list, tuple)) else [o]
    res = []
    for x in outs:
        if isinstance(x, BaseSparseNDArray):
            x = x.tostype("default")
        res.append(_as_np(x).astype("float32"))
    return res


def sweep_coverage():
    """(covered, skipped, uncovered) over the public nd registry — the
    completeness contract: every public nd callable is either in the op
    table or in SWEEP_SKIP with a reason. ``uncovered`` must be empty."""
    from .base import public_op_names
    covered = set()
    for entry in _sweep_table():
        name, _, _, opts = _norm_entry(entry)
        covered.add(opts.get("op", name.partition("@")[0]))
    eligible = set(public_op_names(nd, exclude=SWEEP_SKIP))
    return covered, set(SWEEP_SKIP), eligible - covered


def op_consistency_sweep(dtypes=("float32", "bfloat16", "float16"),
                         ctx_list=None, quick=False, seed=0):
    """Walk the FULL registry op table across contexts x dtypes; returns
    rows of (op, dtype, max_rel_err, status) where status is 'ok',
    'MISMATCH', or 'ERROR: ...'. ctx_list defaults to
    [cpu, default_context] — on TPU hosts that is the real CPU<->TPU
    cross-backend walk (the reference's GPU-suite re-run); on CPU-only
    hosts both legs are CPU and the sweep still catches dtype-lowering
    breaks."""
    table = _sweep_table()
    if quick:
        table = table[::6]
    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    rows = []
    inputs_cache = {}
    import contextlib
    import jax
    for entry in table:
        entry_name, fn, specs, opts = _norm_entry(entry)
        name, _, tag = entry_name.partition("@")
        if entry_name not in inputs_cache:
            inputs_cache[entry_name] = sweep_inputs(specs, seed)
        for dt in dtypes:
            if dt != "float32" and (opts.get("nondiff") and
                                    name not in TRANS_DTYPE_OK):
                # int-output / update-rule ops: one dtype leg is enough
                if dt != dtypes[0]:
                    continue
            rtol, atol = (SWEEP_TOLS_TRANS if tag == "trans"
                          else SWEEP_TOLS)[dt]
            prec = jax.default_matmul_precision("highest") if tag == "mm" \
                else contextlib.nullcontext()
            try:
                outs = []
                with prec:
                    for ctx in ctx_list:
                        arrs = []
                        for (shape, kind), x in zip(specs,
                                                    inputs_cache[entry_name]):
                            a = nd.array(x, ctx=ctx)
                            if kind in ("f", "pos", "unit", "gt1", "perm",
                                        "pmf") and dt != "float32":
                                a = a.astype(dt)
                            arrs.append(a)
                        if opts.get("seed"):
                            nd.random.seed(seed)
                        with ctx:
                            o = fn(nd, *arrs)
                        outs.append(_norm_outputs(o))
                ref = outs[0]
                err = 0.0
                ok = True
                for legs in outs[1:]:
                    for r, b in zip(legs, ref):
                        diff = onp.abs(r - b)
                        denom = onp.abs(b) + atol
                        err = max(err, float((diff / denom).max())
                                  if diff.size else 0.0)
                        ok = ok and onp.allclose(r, b, rtol=rtol, atol=atol)
                rows.append((entry_name.partition("@")[0], dt, err,
                             "ok" if ok else "MISMATCH"))
            except Exception as e:  # record, keep walking
                rows.append((entry_name.partition("@")[0], dt, None,
                             "ERROR: %s" % str(e).splitlines()[0][:120]))
    return rows


#: nondiff ops that still deserve the low-precision dtype legs
TRANS_DTYPE_OK = {"round", "floor", "ceil", "trunc", "rint", "fix", "sign"}


def grad_consistency_sweep(ctx_list=None, quick=False, seed=0):
    """Backward-pass companion to op_consistency_sweep: for every
    differentiable float op in the table, compare d(sum(op))/d(inputs)
    across contexts at float32 (matmul-class under 'highest' precision).
    Returns (op, max_rel_err, status) rows."""
    import contextlib
    import jax
    from . import autograd as _ag

    table = []
    for entry in _sweep_table():
        name, fn, specs, opts = _norm_entry(entry)
        if opts.get("nondiff") or not specs:
            continue
        if not all(_spec_is_float(kind) and kind != "b"
                   for _, kind in specs):
            continue
        table.append((name, fn, specs, opts))
    if quick:
        table = table[::6]
    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    rows = []
    for entry_name, fn, specs, opts in table:
        name, _, tag = entry_name.partition("@")
        inputs = sweep_inputs(specs, seed)
        rtol, atol = (2e-3, 1e-4) if tag == "trans" else (1e-4, 1e-5)
        prec = jax.default_matmul_precision("highest") if tag == "mm" \
            else contextlib.nullcontext()
        try:
            grads = []
            with prec:
                for ctx in ctx_list:
                    arrs = [nd.array(x, ctx=ctx) for x in inputs]
                    for a in arrs:
                        a.attach_grad()
                    if opts.get("seed"):
                        nd.random.seed(seed)
                    with ctx:
                        with _ag.record():
                            out = fn(nd, *arrs)
                            if isinstance(out, (list, tuple)):
                                s = out[0].sum()
                                for x in out[1:]:
                                    s = s + x.sum()
                            else:
                                s = out.sum()
                        s.backward()
                    grads.append([a.grad.asnumpy() if a.grad is not None
                                  else onp.zeros(1, "float32")
                                  for a in arrs])
            err = 0.0
            ok = True
            for g in grads[1:]:
                for a, b in zip(g, grads[0]):
                    diff = onp.abs(a - b)
                    err = max(err, float((diff / (onp.abs(b) + atol)).max())
                              if diff.size else 0.0)
                    ok = ok and onp.allclose(a, b, rtol=rtol, atol=atol)
            rows.append((name, err, "ok" if ok else "MISMATCH"))
        except Exception as e:
            rows.append((name, None,
                         "ERROR: %s" % str(e).splitlines()[0][:120]))
    return rows


class random_seed:
    """Context manager fixing framework + numpy seeds (ref common.py with_seed)."""

    def __init__(self, seed=None):
        self.seed = seed

    def __enter__(self):
        self._np_state = onp.random.get_state()
        s = self.seed if self.seed is not None else onp.random.randint(0, 2 ** 31)
        onp.random.seed(s)
        nd.random.seed(s)
        return s

    def __exit__(self, *a):
        onp.random.set_state(self._np_state)
