"""Test utilities shipped with the package (ref python/mxnet/test_utils.py, 2,599 LoC).

Reference parity: assert_almost_equal, check_numeric_gradient (finite
differences vs autograd), check_consistency (cross-device/dtype), rand_ndarray,
default_context switching — the fixtures the whole reference test suite reuses.
"""
from __future__ import annotations

import numpy as onp

from . import autograd, context as ctx_mod
from . import ndarray as nd
from .context import Context, cpu, current_context
from .ndarray import NDArray

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "rand_ndarray", "rand_shape_2d", "rand_shape_3d",
           "rand_shape_nd", "check_numeric_gradient", "check_consistency",
           "numeric_grad", "simple_forward", "same", "random_seed"]

_default_ctx = [None]


def default_context():
    return _default_ctx[0] if _default_ctx[0] is not None else current_context()


def set_default_context(ctx):
    _default_ctx[0] = ctx


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return onp.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"), equal_nan=False):
    a, b = _as_np(a), _as_np(b)
    if not onp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        index = onp.unravel_index(onp.argmax(onp.abs(a - b)), a.shape) if a.shape else ()
        rel = onp.abs(a - b) / (onp.abs(b) + atol + 1e-30)
        raise AssertionError(
            "Error %f exceeds tolerance rtol=%g atol=%g. Worst at %s: %s vs %s"
            % (float(rel.max()) if rel.size else 0.0, rtol, atol, index,
               a[index] if a.shape else a, b[index] if b.shape else b))


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype="float32", ctx=None,
                 scale=1.0):
    """ref test_utils.py rand_ndarray — density controls sparse fill."""
    dense = onp.random.uniform(-scale, scale, size=shape).astype(dtype)
    if stype == "default":
        return nd.array(dense, ctx=ctx)
    if density is None:
        density = 0.5
    if stype == "row_sparse":
        row_mask = onp.random.rand(shape[0]) < density
        dense = dense * row_mask.reshape((-1,) + (1,) * (len(shape) - 1))
        return nd.array(dense, ctx=ctx).tostype("row_sparse")
    if stype == "csr":
        mask = onp.random.rand(*shape) < density
        return nd.array(dense * mask, ctx=ctx).tostype("csr")
    raise ValueError("unknown stype %r" % stype)


def simple_forward(sym_or_fn, ctx=None, is_train=False, **inputs):
    outs = sym_or_fn(**{k: nd.array(v) for k, v in inputs.items()})
    if isinstance(outs, (list, tuple)):
        return [o.asnumpy() for o in outs]
    return outs.asnumpy()


def numeric_grad(f, xs, eps=1e-4):
    """Central finite differences of scalar-valued f over list of np arrays."""
    grads = []
    for i, x in enumerate(xs):
        g = onp.zeros_like(x, dtype=onp.float64)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(f(xs))
            flat[j] = orig - eps
            fm = float(f(xs))
            flat[j] = orig
            gf[j] = (fp - fm) / (2 * eps)
        grads.append(g.astype(x.dtype))
    return grads


def check_numeric_gradient(fn, inputs, rtol=1e-2, atol=1e-3, eps=1e-3):
    """Finite differences vs autograd (ref test_utils.py check_numeric_gradient).

    fn: callable taking NDArrays, returning one NDArray (summed to scalar).
    inputs: list of numpy arrays (float32/float64).
    """
    xs = [onp.asarray(x, dtype=onp.float64) for x in inputs]

    def f(arrs):
        vals = [nd.array(a.astype(onp.float32)) for a in arrs]
        return fn(*vals).sum().asscalar()

    expected = numeric_grad(f, xs, eps)

    arrs = [nd.array(x.astype(onp.float32)) for x in xs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrs).sum()
    out.backward()
    for a, e in zip(arrs, expected):
        assert_almost_equal(a.grad.asnumpy(), e.astype(onp.float32), rtol=rtol, atol=atol)


def check_consistency(fn, inputs, ctx_list=None, dtypes=("float32",), rtol=1e-3,
                      atol=1e-4):
    """Run fn under several contexts/dtypes and compare outputs
    (ref test_utils.py check_consistency — the de-facto cross-backend check)."""
    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    results = []
    for ctx in ctx_list:
        for dt in dtypes:
            with ctx:
                arrs = [nd.array(onp.asarray(x, dtype=dt), ctx=ctx) for x in inputs]
                results.append(fn(*arrs).asnumpy().astype("float32"))
    base = results[0]
    for r in results[1:]:
        assert_almost_equal(r, base, rtol=rtol, atol=atol)


class random_seed:
    """Context manager fixing framework + numpy seeds (ref common.py with_seed)."""

    def __init__(self, seed=None):
        self.seed = seed

    def __enter__(self):
        self._np_state = onp.random.get_state()
        s = self.seed if self.seed is not None else onp.random.randint(0, 2 ** 31)
        onp.random.seed(s)
        nd.random.seed(s)
        return s

    def __exit__(self, *a):
        onp.random.set_state(self._np_state)
