"""Test utilities shipped with the package (ref python/mxnet/test_utils.py, 2,599 LoC).

Reference parity: assert_almost_equal, check_numeric_gradient (finite
differences vs autograd), check_consistency (cross-device/dtype), rand_ndarray,
default_context switching — the fixtures the whole reference test suite reuses.
"""
from __future__ import annotations

import numpy as onp

from . import autograd, context as ctx_mod
from . import ndarray as nd
from .context import Context, cpu, current_context
from .ndarray import NDArray

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "rand_ndarray", "rand_shape_2d", "rand_shape_3d",
           "rand_shape_nd", "check_numeric_gradient", "check_consistency",
           "numeric_grad", "simple_forward", "same", "random_seed",
           "op_consistency_sweep", "SWEEP_TOLS"]

_default_ctx = [None]


def default_context():
    return _default_ctx[0] if _default_ctx[0] is not None else current_context()


def set_default_context(ctx):
    _default_ctx[0] = ctx


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return onp.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"), equal_nan=False):
    a, b = _as_np(a), _as_np(b)
    if not onp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        index = onp.unravel_index(onp.argmax(onp.abs(a - b)), a.shape) if a.shape else ()
        rel = onp.abs(a - b) / (onp.abs(b) + atol + 1e-30)
        raise AssertionError(
            "Error %f exceeds tolerance rtol=%g atol=%g. Worst at %s: %s vs %s"
            % (float(rel.max()) if rel.size else 0.0, rtol, atol, index,
               a[index] if a.shape else a, b[index] if b.shape else b))


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype="float32", ctx=None,
                 scale=1.0):
    """ref test_utils.py rand_ndarray — density controls sparse fill."""
    dense = onp.random.uniform(-scale, scale, size=shape).astype(dtype)
    if stype == "default":
        return nd.array(dense, ctx=ctx)
    if density is None:
        density = 0.5
    if stype == "row_sparse":
        row_mask = onp.random.rand(shape[0]) < density
        dense = dense * row_mask.reshape((-1,) + (1,) * (len(shape) - 1))
        return nd.array(dense, ctx=ctx).tostype("row_sparse")
    if stype == "csr":
        mask = onp.random.rand(*shape) < density
        return nd.array(dense * mask, ctx=ctx).tostype("csr")
    raise ValueError("unknown stype %r" % stype)


def simple_forward(sym_or_fn, ctx=None, is_train=False, **inputs):
    outs = sym_or_fn(**{k: nd.array(v) for k, v in inputs.items()})
    if isinstance(outs, (list, tuple)):
        return [o.asnumpy() for o in outs]
    return outs.asnumpy()


def numeric_grad(f, xs, eps=1e-4):
    """Central finite differences of scalar-valued f over list of np arrays."""
    grads = []
    for i, x in enumerate(xs):
        g = onp.zeros_like(x, dtype=onp.float64)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(f(xs))
            flat[j] = orig - eps
            fm = float(f(xs))
            flat[j] = orig
            gf[j] = (fp - fm) / (2 * eps)
        grads.append(g.astype(x.dtype))
    return grads


def check_numeric_gradient(fn, inputs, rtol=1e-2, atol=1e-3, eps=1e-3):
    """Finite differences vs autograd (ref test_utils.py check_numeric_gradient).

    fn: callable taking NDArrays, returning one NDArray (summed to scalar).
    inputs: list of numpy arrays (float32/float64).
    """
    xs = [onp.asarray(x, dtype=onp.float64) for x in inputs]

    def f(arrs):
        vals = [nd.array(a.astype(onp.float32)) for a in arrs]
        return fn(*vals).sum().asscalar()

    expected = numeric_grad(f, xs, eps)

    arrs = [nd.array(x.astype(onp.float32)) for x in xs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrs).sum()
    out.backward()
    for a, e in zip(arrs, expected):
        assert_almost_equal(a.grad.asnumpy(), e.astype(onp.float32), rtol=rtol, atol=atol)


def check_consistency(fn, inputs, ctx_list=None, dtypes=("float32",), rtol=1e-3,
                      atol=1e-4):
    """Run fn under several contexts/dtypes and compare outputs
    (ref test_utils.py check_consistency — the de-facto cross-backend check)."""
    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    results = []
    for ctx in ctx_list:
        for dt in dtypes:
            with ctx:
                arrs = [nd.array(onp.asarray(x, dtype=dt), ctx=ctx) for x in inputs]
                results.append(fn(*arrs).asnumpy().astype("float32"))
    base = results[0]
    for r in results[1:]:
        assert_almost_equal(r, base, rtol=rtol, atol=atol)


# ----------------------------------------------------------------- sweep
def _sweep_table():
    """Op table for the cross-backend numerics sweep (the reference's
    test_operator_gpu.py re-run-everything-on-device trick, distilled to an
    op walk). Each entry: (name, fn(*nd arrays) -> NDArray, input specs)
    where a spec is (shape, kind) and kind is 'f' (float, cast to the sweep
    dtype), 'pos' (positive float), or 'i' (int32 indices, never cast)."""
    from .ndarray import linalg  # noqa: F401  (namespace touch)

    def f(*shape):
        return (shape, "f")

    def pos(*shape):
        return (shape, "pos")

    def idx(*shape):
        return (shape, "i")

    t = [
        # elemwise unary
        ("exp@trans", lambda a: nd.exp(a), [f(4, 16)]),
        ("log@trans", lambda a: nd.log(a), [pos(4, 16)]),
        ("sqrt@trans", lambda a: nd.sqrt(a), [pos(4, 16)]),
        ("rsqrt@trans", lambda a: nd.rsqrt(a), [pos(4, 16)]),
        ("sigmoid@trans", lambda a: nd.sigmoid(a), [f(4, 16)]),
        ("tanh@trans", lambda a: nd.tanh(a), [f(4, 16)]),
        ("erf@trans", lambda a: nd.erf(a), [f(4, 16)]),
        ("abs", lambda a: nd.abs(a), [f(4, 16)]),
        ("square", lambda a: nd.square(a), [f(4, 16)]),
        ("cbrt@trans", lambda a: nd.cbrt(a), [pos(4, 16)]),
        ("round", lambda a: nd.round(a), [f(4, 16)]),
        ("floor", lambda a: nd.floor(a), [f(4, 16)]),
        ("sin@trans", lambda a: nd.sin(a), [f(4, 16)]),
        ("cos@trans", lambda a: nd.cos(a), [f(4, 16)]),
        ("log1p@trans", lambda a: nd.log1p(a), [pos(4, 16)]),
        ("expm1@trans", lambda a: nd.expm1(a), [f(4, 16)]),
        ("relu", lambda a: nd.relu(a), [f(4, 16)]),
        ("softsign@trans", lambda a: nd.softsign(a), [f(4, 16)]),
        ("clip", lambda a: nd.clip(a, -1.0, 1.0), [f(4, 16)]),
        # binary / broadcast
        ("broadcast_add", lambda a, b: nd.broadcast_add(a, b),
         [f(4, 16), f(1, 16)]),
        ("broadcast_sub", lambda a, b: nd.broadcast_sub(a, b),
         [f(4, 16), f(1, 16)]),
        ("broadcast_mul", lambda a, b: nd.broadcast_mul(a, b),
         [f(4, 16), f(1, 16)]),
        ("broadcast_div", lambda a, b: nd.broadcast_div(a, b),
         [f(4, 16), pos(1, 16)]),
        ("maximum", lambda a, b: nd.maximum(a, b), [f(4, 16), f(4, 16)]),
        ("minimum", lambda a, b: nd.minimum(a, b), [f(4, 16), f(4, 16)]),
        ("power@trans", lambda a, b: nd.power(a, b), [pos(4, 16), f(4, 16)]),
        # reductions
        ("sum", lambda a: nd.sum(a, axis=1), [f(8, 64)]),
        ("mean", lambda a: nd.mean(a, axis=1), [f(8, 64)]),
        ("max", lambda a: nd.max(a, axis=1), [f(8, 64)]),
        ("min", lambda a: nd.min(a, axis=1), [f(8, 64)]),
        ("prod", lambda a: nd.prod(a, axis=1), [f(8, 8)]),
        ("norm@trans", lambda a: nd.norm(a, axis=1), [f(8, 64)]),
        ("argmax", lambda a: nd.argmax(a, axis=1), [f(8, 64)]),
        ("argmin", lambda a: nd.argmin(a, axis=1), [f(8, 64)]),
        # linalg / nn
        ("dot@mm", lambda a, b: nd.dot(a, b), [f(8, 32), f(32, 8)]),
        ("linalg.gemm2@mm", lambda a, b: nd.linalg.gemm2(a, b),
         [f(8, 32), f(32, 8)]),
        ("FullyConnected@mm",
         lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=8),
         [f(4, 32), f(8, 32), f(8)]),
        ("Convolution@mm",
         lambda x, w: nd.Convolution(x, w, None, kernel=(3, 3),
                                     num_filter=8, pad=(1, 1), no_bias=True),
         [f(2, 4, 8, 8), f(8, 4, 3, 3)]),
        ("Pooling_max",
         lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="max",
                              stride=(2, 2)),
         [f(2, 4, 8, 8)]),
        ("Pooling_avg",
         lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="avg",
                              stride=(2, 2)),
         [f(2, 4, 8, 8)]),
        ("softmax@trans", lambda a: nd.softmax(a, axis=-1), [f(4, 16)]),
        ("log_softmax@trans", lambda a: nd.log_softmax(a, axis=-1), [f(4, 16)]),
        ("LayerNorm",
         lambda x, g, b: nd.LayerNorm(x, g, b, axis=-1),
         [f(4, 16), f(16), f(16)]),
        ("LeakyReLU", lambda a: nd.LeakyReLU(a, slope=0.1), [f(4, 16)]),
        ("Activation@trans",
         lambda a: nd.Activation(a, act_type="softrelu"), [f(4, 16)]),
        # indexing / shape
        ("take", lambda a, i: nd.take(a, i), [f(16, 8), idx(6)]),
        ("Embedding",
         lambda i, w: nd.Embedding(i, w, input_dim=16, output_dim=8),
         [idx(6), f(16, 8)]),
        ("one_hot", lambda i: nd.one_hot(i, 16), [idx(6)]),
        ("topk", lambda a: nd.topk(a, k=3, ret_typ="value"), [f(4, 16)]),
        ("sort", lambda a: nd.sort(a, axis=-1), [f(4, 16)]),
        ("transpose", lambda a: nd.transpose(a, axes=(1, 0, 2)),
         [f(3, 4, 5)]),
        ("where", lambda c, a, b: nd.where(c, a, b),
         [idx(4, 16), f(4, 16), f(4, 16)]),
    ]
    return t


#: per-dtype (rtol, atol) for the sweep; bf16 has 8 mantissa bits, fp16 10.
#: 'trans'-tagged ops (transcendentals) get the looser fp32 row — XLA
#: backends use different polynomial approximations, parity is ~1e-3 not
#: ULP. 'mm'-tagged ops run under jax.default_matmul_precision('highest')
#: so the sweep checks ARITHMETIC parity; the MXU's default bf16-multiply
#: fp32-accumulate mode is a documented perf trade (MXTPU_MATMUL_PRECISION).
SWEEP_TOLS = {"float32": (1e-4, 1e-5), "bfloat16": (4e-2, 2e-2),
              "float16": (1e-2, 2e-3)}
SWEEP_TOLS_TRANS = {"float32": (2e-3, 1e-4), "bfloat16": (4e-2, 2e-2),
                    "float16": (1e-2, 2e-3)}


def op_consistency_sweep(dtypes=("float32", "bfloat16", "float16"),
                         ctx_list=None, quick=False, seed=0):
    """Walk the op table across contexts x dtypes; returns rows of
    (op, dtype, max_rel_err, status) where status is 'ok', 'MISMATCH', or
    'ERROR: ...'. ctx_list defaults to [cpu, default_context] — on TPU
    hosts that is the real CPU<->TPU cross-backend walk (the reference's
    GPU-suite re-run); on CPU-only hosts both legs are CPU and the sweep
    still catches dtype-lowering breaks."""
    table = _sweep_table()
    if quick:
        table = table[::3]
    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    rows = []
    rng = onp.random.RandomState(seed)
    inputs_cache = {}
    import contextlib
    import jax
    for entry_name, fn, specs in table:
        name, _, tag = entry_name.partition("@")
        key = name
        if key not in inputs_cache:
            gen = []
            for shape, kind in specs:
                if kind == "i":
                    gen.append(rng.randint(0, 2, size=shape).astype("int32")
                               if name == "where"
                               else rng.randint(0, min(shape) if shape
                                                else 4, size=shape)
                               .astype("int32"))
                else:
                    a = rng.uniform(-2.0, 2.0, size=shape).astype("float32")
                    if kind == "pos":
                        a = onp.abs(a) + 0.5
                    gen.append(a)
            inputs_cache[key] = gen
        for dt in dtypes:
            rtol, atol = (SWEEP_TOLS_TRANS if tag == "trans"
                          else SWEEP_TOLS)[dt]
            prec = jax.default_matmul_precision("highest") if tag == "mm" \
                else contextlib.nullcontext()
            try:
                outs = []
                with prec:
                    for ctx in ctx_list:
                        arrs = []
                        for (shape, kind), x in zip(specs,
                                                    inputs_cache[key]):
                            a = nd.array(x, ctx=ctx)
                            if kind != "i" and dt != "float32":
                                a = a.astype(dt)
                            arrs.append(a)
                        with ctx:
                            o = fn(*arrs)
                        outs.append(o.asnumpy().astype("float32"))
                ref = outs[0]
                err = 0.0
                ok = True
                for r in outs[1:]:
                    diff = onp.abs(r - ref)
                    denom = onp.abs(ref) + atol
                    err = max(err, float((diff / denom).max())
                              if diff.size else 0.0)
                    ok = ok and onp.allclose(r, ref, rtol=rtol, atol=atol)
                rows.append((name, dt, err, "ok" if ok else "MISMATCH"))
            except Exception as e:  # record, keep walking
                rows.append((name, dt, None,
                             "ERROR: %s" % str(e).splitlines()[0][:120]))
    return rows


def grad_consistency_sweep(ctx_list=None, quick=False, seed=0):
    """Backward-pass companion to op_consistency_sweep: for every
    differentiable float op in the table, compare d(sum(op))/d(inputs)
    across contexts at float32 (matmul-class under 'highest' precision).
    Returns (op, max_rel_err, status) rows."""
    import contextlib
    import jax
    from . import autograd as _ag

    table = [e for e in _sweep_table()
             if all(kind != "i" for _, kind in e[2])]
    # non-differentiable / piecewise-constant outputs excluded
    skip = {"round", "floor", "argmax", "argmin", "one_hot"}
    table = [e for e in table if e[0].partition("@")[0] not in skip]
    if quick:
        table = table[::3]
    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    rows = []
    rng = onp.random.RandomState(seed)
    for entry_name, fn, specs in table:
        name, _, tag = entry_name.partition("@")
        inputs = []
        for shape, kind in specs:
            a = rng.uniform(-2.0, 2.0, size=shape).astype("float32")
            if kind == "pos":
                a = onp.abs(a) + 0.5
            inputs.append(a)
        rtol, atol = (2e-3, 1e-4) if tag == "trans" else (1e-4, 1e-5)
        prec = jax.default_matmul_precision("highest") if tag == "mm" \
            else contextlib.nullcontext()
        try:
            grads = []
            with prec:
                for ctx in ctx_list:
                    arrs = [nd.array(x, ctx=ctx) for x in inputs]
                    for a in arrs:
                        a.attach_grad()
                    with ctx:
                        with _ag.record():
                            out = fn(*arrs)
                            s = out.sum()
                        s.backward()
                    grads.append([a.grad.asnumpy() for a in arrs])
            err = 0.0
            ok = True
            for g in grads[1:]:
                for a, b in zip(g, grads[0]):
                    diff = onp.abs(a - b)
                    err = max(err, float((diff / (onp.abs(b) + atol)).max())
                              if diff.size else 0.0)
                    ok = ok and onp.allclose(a, b, rtol=rtol, atol=atol)
            rows.append((name, err, "ok" if ok else "MISMATCH"))
        except Exception as e:
            rows.append((name, None,
                         "ERROR: %s" % str(e).splitlines()[0][:120]))
    return rows


class random_seed:
    """Context manager fixing framework + numpy seeds (ref common.py with_seed)."""

    def __init__(self, seed=None):
        self.seed = seed

    def __enter__(self):
        self._np_state = onp.random.get_state()
        s = self.seed if self.seed is not None else onp.random.randint(0, 2 ** 31)
        onp.random.seed(s)
        nd.random.seed(s)
        return s

    def __exit__(self, *a):
        onp.random.set_state(self._np_state)
