"""Module — symbolic trainer (ref python/mxnet/module/module.py:40).

bind (:364) → Executor; init_optimizer (:474) → optimizer + kvstore;
forward/backward/update (:575,629,646). TPU-native: one logical executor
(data parallelism is an SPMD sharding on the compiled step, not per-ctx
executor copies — DataParallelExecutorGroup collapses away).
"""
from __future__ import annotations

import logging

import numpy as onp

from .. import initializer as init_mod
from .. import kvstore as kvs_mod
from .. import ndarray as nd
from .. import optimizer as opt_mod
from ..context import cpu, current_context
from ..model import load_params as _load_params, save_checkpoint
from ..ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger)
        self.symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._context = context if context is not None else current_context()
        # Multi-context DP (ref module/executor_group.py
        # DataParallelExecutorGroup): instead of slicing the batch into
        # per-context executors, ONE executor runs with the batch sharded
        # over a dp mesh built from the context list and params replicated —
        # per-op SPMD inserts the gradient all-reduce (sharding propagation),
        # which is the TPU-native form of the group's grad aggregation.
        self._dp_data_sharding = None
        self._dp_rep_sharding = None
        if isinstance(self._context, (list, tuple)):
            ctxs = list(self._context)
            if len(ctxs) > 1:
                import numpy as _onp
                import jax as _jax
                from jax.sharding import (Mesh as _Mesh,
                                          NamedSharding as _NS,
                                          PartitionSpec as _P)
                mesh = _Mesh(_onp.array([c.jax_device for c in ctxs]), ("dp",))
                self._dp_data_sharding = _NS(mesh, _P("dp"))
                self._dp_rep_sharding = _NS(mesh, _P())
            self._context = ctxs[0]
        self._fixed_param_names = set(fixed_param_names or [])
        self._exec = None
        self._optimizer = None
        self._kvstore = None
        self._updater_states = {}
        self._arg_names = symbol.list_arguments()
        self._param_names = [n for n in self._arg_names
                             if n not in self._data_names
                             and n not in self._label_names]

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..symbol import load as sym_load
        sym = sym_load("%s-symbol.json" % prefix)
        mod = Module(sym, **kwargs)
        arg_params, aux_params = _load_params(prefix, epoch)
        mod._preloaded_params = (arg_params, aux_params)
        return mod

    # -----------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """ref module.py:364."""
        if self.binded and not force_rebind:
            return
        from ..executor import Executor

        shapes = {}
        for desc in data_shapes:
            name, shape = desc[0], desc[1]
            shapes[name] = tuple(shape)
        if label_shapes:
            for desc in label_shapes:
                shapes[desc[0]] = tuple(desc[1])
        args = {k: nd.zeros(v) for k, v in shapes.items()}
        self._exec = Executor(self.symbol, self._context, args,
                              grad_req=grad_req if for_training else "null",
                              inputs_need_grad=inputs_need_grad)
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self.binded = True
        self.for_training = for_training
        if hasattr(self, "_preloaded_params"):
            arg_params, aux_params = self._preloaded_params
            self.init_params(arg_params=arg_params, aux_params=aux_params)

    @property
    def data_names(self):
        return list(self._data_names)

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def output_names(self):
        return self.symbol.list_outputs()

    @property
    def output_shapes(self):
        """(name, shape) of current outputs (ref module.py output_shapes);
        populated once the executor has run (bind zero-materializes)."""
        outs = getattr(self._exec, "outputs", None) if self._exec else None
        if not outs:
            return None
        return list(zip(self.symbol.list_outputs(),
                        [tuple(o.shape) for o in outs]))

    @property
    def param_names(self):
        return [n for n in self._exec.arg_dict
                if n not in self._data_names and n not in self._label_names
                and not n.endswith("_label")
                and n not in self._exec._aux_names] if self._exec else \
            self._param_names

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """ref module.py init_params."""
        assert self.binded
        if self.params_initialized and not force_init:
            return
        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        for name in self.param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                arr._data = arg_params[name].astype(arr.dtype)._data
            else:
                initializer(name, arr)
        for name in self._exec._aux_names:
            arr = self._exec.arg_dict[name]
            if aux_params and name in aux_params:
                arr._data = aux_params[name].astype(arr.dtype)._data
            else:
                initializer(name, arr)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy() for n in self.param_names}
        aux = {n: self._exec.arg_dict[n].copy() for n in self._exec._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(None, arg_params, aux_params, allow_missing, force_init,
                         allow_extra)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        """ref module.py:474."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        optimizer_params = dict(optimizer_params or {"learning_rate": 0.01})
        if isinstance(optimizer, str):
            # the reference defaults rescale_grad to 1/batch when it builds
            # the optimizer itself (module.py:497) — loss-layer grads are
            # batch SUMS, so without this fit() takes batch_size-times-too-
            # large steps and saturates
            if "rescale_grad" not in optimizer_params and \
                    getattr(self, "_data_shapes", None):
                batch = self._data_shapes[0][1][0]
                optimizer_params["rescale_grad"] = 1.0 / batch
            idx2name = {i: n for i, n in enumerate(self.param_names)}
            optimizer = opt_mod.create(optimizer, param_idx2name=idx2name,
                                       **optimizer_params)
        self._optimizer = optimizer
        self._update_on_kvstore = False
        if kvstore:
            kv = kvs_mod.create(kvstore) if isinstance(kvstore, str) else kvstore
            self._kvstore = kv
            # ref module.py:474 + model.py _update_params_on_kvstore: dist
            # stores own the update — push grads (the store aggregates
            # across workers), pull back the updated weight
            self._update_on_kvstore = kv.type.startswith("dist")
            if self._update_on_kvstore:
                kv.set_optimizer(optimizer)
                for i, name in enumerate(self.param_names):
                    if name not in self._fixed_param_names and \
                            self._exec.grad_dict.get(name) is not None:
                        kv.init(i, self._exec.arg_dict[name])
        self._updater_states = {}
        self.optimizer_initialized = True

    # -----------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """ref module.py:575."""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        if self._dp_data_sharding is not None:
            self._place_dp(feed)
        self._exec.forward(is_train=is_train, **feed)

    def _place_dp(self, feed):
        """Shard the feed over dp, keep params/grads replicated (cheap no-op
        once placed)."""
        import jax as _jax
        from .. import ndarray as _nd
        for name, arr in list(feed.items()):
            if not isinstance(arr, _nd.NDArray):
                arr = _nd.array(arr)
            feed[name] = _nd.NDArray(
                _jax.device_put(arr._data, self._dp_data_sharding))
        for d in (self._exec.arg_dict, self._exec.grad_dict):
            for name, arr in d.items():
                if name in feed:
                    continue
                sh = getattr(arr._data, "sharding", None)
                if sh != self._dp_rep_sharding:
                    arr._data = _jax.device_put(arr._data,
                                                self._dp_rep_sharding)

    def backward(self, out_grads=None):
        """ref module.py:629."""
        self._exec.backward(out_grads)

    def update(self):
        """ref module.py:646 — optimizer step on accumulated grads.

        With a dist kvstore the step is update-on-kvstore (model.py:151):
        grads are PUSHED (the store aggregates across workers and applies
        the optimizer to its copy) and the weight PULLED back."""
        assert self.optimizer_initialized
        if self._update_on_kvstore and self._kvstore is not None:
            # one multi-key push + one multi-key pull so the dist store's
            # dtype-batched allgather path handles all grads in one
            # collective instead of O(num_params) round-trips
            keys, grads, weights = [], [], []
            for i, name in enumerate(self.param_names):
                w = self._exec.arg_dict[name]
                g = self._exec.grad_dict.get(name)
                if g is None or name in self._fixed_param_names:
                    continue
                keys.append(i)
                grads.append(g)
                weights.append(w)
            if keys:
                # priority=-i as in ref module.py: earlier layers sync
                # first (the next forward needs them first); push accepts
                # a per-key sequence so P3 ordering survives the batch
                self._kvstore.push(keys, grads,
                                   priority=[-i for i in keys])
                self._kvstore.pull(keys, out=weights)
            return
        for i, name in enumerate(self.param_names):
            w = self._exec.arg_dict[name]
            g = self._exec.grad_dict.get(name)
            if g is None or name in self._fixed_param_names:
                continue
            if i not in self._updater_states:
                self._updater_states[i] = self._optimizer.create_state_multi_precision(i, w)
            new_state = self._optimizer.update_multi_precision(
                i, w, g, self._updater_states[i])
            if new_state is not None:
                self._updater_states[i] = new_state

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self._exec.outputs)

    # -----------------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        """ref module.py:165."""
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg, aux)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    def save_optimizer_states(self, fname):
        """ref module.py:793."""
        import pickle
        from ..optimizer.optimizer import _state_to_np
        with open(fname, "wb") as f:
            pickle.dump({k: _state_to_np(v) for k, v in self._updater_states.items()}, f)

    def load_optimizer_states(self, fname):
        import pickle
        from ..optimizer.optimizer import _state_from_np
        with open(fname, "rb") as f:
            st = pickle.load(f)
        self._updater_states = {k: _state_from_np(v) for k, v in st.items()}
