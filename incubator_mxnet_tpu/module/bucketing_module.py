"""BucketingModule — variable-length sequences via per-bucket executables
(ref python/mxnet/module/bucketing_module.py).

TPU-native: each bucket key is a distinct static shape → a distinct XLA
executable, shared parameters. This is the bucketed-executable-cache answer
to dynamic shapes (SURVEY §7 hard part b)."""
from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        super().__init__(logger)
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._kwargs = kwargs
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._opt_config = None

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    @symbol.setter
    def symbol(self, v):
        pass

    def _gen_module(self, bucket_key):
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(sym, data_names, label_names, self.logger,
                         self._context, **self._kwargs)
            self._buckets[bucket_key] = mod
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             force_rebind=False, **kw):
        self._curr_module = self._gen_module(self._default_bucket_key)
        self._curr_bucket_key = self._default_bucket_key
        self._curr_module.bind(data_shapes, label_shapes, for_training,
                               force_rebind=force_rebind)
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """ref bucketing_module.py switch_bucket — share params across buckets."""
        mod = self._gen_module(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes, self.for_training)
            if self._curr_module is not None and self._curr_module.params_initialized:
                arg, aux = self._curr_module.get_params()
                mod.init_params(arg_params=arg, aux_params=aux)
            if self._opt_config is not None:
                mod.init_optimizer(*self._opt_config)
        else:
            # re-sync shared params into this bucket's executor
            if self._curr_module is not None and self._curr_module is not mod \
                    and self._curr_module.params_initialized:
                arg, aux = self._curr_module.get_params()
                mod.set_params(arg, aux)
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def init_params(self, *args, **kwargs):
        self._curr_module.init_params(*args, **kwargs)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init)
        self._opt_config = (kvstore, self._curr_module._optimizer, None)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", self._curr_bucket_key)
        if key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)
