"""BaseModule — symbolic training API (ref python/mxnet/module/base_module.py).

``fit`` (ref :409-560) is the classic epoch/batch loop: bind → init_params →
init_optimizer → forward_backward/update/update_metric → checkpoints.
"""
from __future__ import annotations

import logging
import time

import numpy as onp

from .. import metric as metric_mod
from .. import ndarray as nd
from ..model import BatchEndParam

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.symbol = None

    # ---- things subclasses implement --------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True, **kw):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    # ---- composite helpers ------------------------------------------
    def forward_backward(self, data_batch):
        """ref base_module.py:193."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None,
              reset=True, epoch=0, sparse_row_id_fn=None):
        """ref base_module.py score."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch, nbatch, eval_metric)
                for cb in _as_list(batch_end_callback):
                    cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True, reset=True,
                always_output_list=False):
        """ref base_module.py predict."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [o[: o.shape[0] - pad] for o in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.concatenate([o[i] for o in output_list], axis=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None, monitor=None,
            sparse_row_id_fn=None):
        """The classic training loop (ref base_module.py:409-560)."""
        assert num_epoch is not None, "please specify number of epochs"
        from .. import initializer as init_mod

        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        # dist_async epoch contract (uneven shards stay deadlock-free):
        # agree on the staleness-round schedule at each epoch start, force
        # a full average at each epoch end (kvstore.DistAsyncKVStore)
        kv = getattr(self, "_kvstore", None)
        kv_async = kv is not None and hasattr(kv, "begin_epoch")

        for epoch in range(begin_epoch, num_epoch):
            # perf_counter: epoch cost is a duration — NTP-step safe (R006)
            tic = time.perf_counter()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            if kv_async:
                try:
                    planned = len(train_data)
                except TypeError:
                    planned = 0
                # unconditional: begin_epoch is a COLLECTIVE — a worker
                # with an empty shard (planned=0) must still join it or
                # the other workers' allgather deadlocks
                kv.begin_epoch(planned)
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch, nbatch, eval_metric)
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1
            if kv_async:
                kv.sync()
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.perf_counter() - tic)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)

    def install_monitor(self, monitor):
        pass

    def get_params(self):
        raise NotImplementedError


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
