"""mx.mod namespace (ref python/mxnet/module/__init__.py)."""
from .base_module import BaseModule  # noqa
from .module import Module  # noqa
from .bucketing_module import BucketingModule  # noqa
from .sequential_module import SequentialModule  # noqa
