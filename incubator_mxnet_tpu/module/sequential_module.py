"""SequentialModule — chain of Modules (ref python/mxnet/module/
sequential_module.py): module i's outputs feed module i+1's data; backward
runs the chain in reverse, threading input grads."""
from __future__ import annotations

import logging

from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger)
        self._modules = []
        self._metas = []
        self._label_modules = []

    def add(self, module, **kwargs):
        """ref sequential_module.py add(module, take_labels=..., auto_wiring=...)."""
        self._modules.append(module)
        self._metas.append(kwargs)
        if kwargs.get(self.META_TAKE_LABELS):
            self._label_modules.append(module)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert self._modules, "add modules first"
        from .. import ndarray as nd

        self._label_shapes = label_shapes
        cur_shapes = data_shapes
        for i, mod in enumerate(self._modules):
            take_labels = self._metas[i].get(self.META_TAKE_LABELS)
            mod.bind(cur_shapes,
                     label_shapes if take_labels else None,
                     for_training=for_training,
                     # intermediate modules must propagate input grads
                     inputs_need_grad=inputs_need_grad or i > 0,
                     force_rebind=force_rebind, grad_req=grad_req)
            # probe output shapes with one zero forward on the raw executor
            # (params are zero-materialized at bind; init_params comes later)
            # — the GraphExecutor shape-chaining analog
            feed = {name: nd.zeros(tuple(shape))
                    for name, shape, *_ in cur_shapes}
            outs = mod._exec.forward(is_train=False, **feed)
            cur_shapes = [("data", tuple(o.shape)) for o in outs]
        self.binded = True
        self.for_training = for_training

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        for mod in self._modules:
            mod.init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params, allow_missing=True,
                            force_init=force_init, allow_extra=True)
        self.params_initialized = True

    def get_params(self):
        arg, aux = {}, {}
        for mod in self._modules:
            a, x = mod.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        from ..io import DataBatch
        batch = data_batch
        for i, mod in enumerate(self._modules):
            mod.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                break
            outs = mod.get_outputs()
            label = data_batch.label if \
                self._metas[i + 1].get(self.META_TAKE_LABELS) else None
            batch = DataBatch(outs, label)

    def backward(self, out_grads=None):
        grads = out_grads
        for i, mod in reversed(list(enumerate(self._modules))):
            mod.backward(grads)
            if i > 0:
                grads = mod.get_input_grads()

    def update(self):
        for mod in self._modules:
            mod.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for i, mod in enumerate(self._modules):
            if self._metas[i].get(self.META_TAKE_LABELS) or \
                    i + 1 == len(self._modules):
                mod.update_metric(eval_metric, labels)
                return
