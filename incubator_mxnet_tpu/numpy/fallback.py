"""mx.np long-tail surface (ref python/mxnet/numpy/fallback.py:1).

The reference routes exactly this category of names through official NumPy
on the host when no native kernel exists. Here the design is strictly
better: nearly every one of these is jnp-native, so they run on device and
under jit like the rest of mx.np; only file io (genfromtxt), scalar/meta
queries (finfo, promote_types, ...), and the legacy financial functions
(npv, pv, ... — dropped from NumPy >= 1.20 but still part of the
reference's exported surface) execute on the host.

Like the reference's fallback ops, names in this module are not recorded
on the autograd tape.
"""
from __future__ import annotations

import numpy as onp

import jax
import jax.numpy as jnp

from ..ndarray import NDArray

#: jnp-native long-tail ops: device-resident, jit-compatible
_JNP_FNS = [
    "apply_along_axis", "apply_over_axes", "argpartition", "array_equiv",
    "choose", "correlate", "frexp", "histogram2d", "histogram_bin_edges",
    "histogramdd", "i0", "ix_", "lexsort", "modf", "nancumprod",
    "nanmedian", "nanpercentile", "nanquantile", "packbits", "partition",
    "piecewise", "poly", "polyadd", "polydiv", "polyfit", "polyint",
    "polymul", "polysub", "roots", "select", "setxor1d",
    "tril_indices_from", "triu_indices_from", "trim_zeros", "unpackbits",
    "unwrap",
]

__all__ = _JNP_FNS + [
    "alltrue", "msort", "genfromtxt", "spacing", "min_scalar_type",
    "promote_types", "result_type", "set_printoptions", "ndim", "size",
    "dtype", "finfo", "iinfo", "npv", "mirr", "pv", "ppmt",
    "rate", "NAN", "NaN", "NINF", "NZERO", "PINF", "PZERO", "bool",
    "bool_", "int8", "int16", "float16", "_NoValue", "_STR_2_DTYPE_",
    "__version__",
]


def _jx(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_jx(v) for v in x)
    return x


def _wrap_out(r):
    from . import ndarray as np_ndarray
    if isinstance(r, (tuple, list)):
        return type(r)(_wrap_out(v) for v in r)
    if isinstance(r, jax.Array):
        return np_ndarray(r)
    if isinstance(r, onp.ndarray) and r.dtype != object:
        return np_ndarray(jnp.asarray(r))
    return r


def _make(name, impl):
    def fn(*args, **kwargs):
        return _wrap_out(impl(*[_jx(a) for a in args],
                              **{k: _jx(v) for k, v in kwargs.items()}))
    fn.__name__ = name
    fn.__doc__ = "mx.np.%s (device-native long-tail op; ref fallback.py)" \
        % name
    return fn


_g = globals()
for _n in _JNP_FNS:
    _g[_n] = _make(_n, getattr(jnp, _n))

alltrue = _make("alltrue", jnp.all)                  # legacy alias
msort = _make("msort", lambda a: jnp.sort(a, axis=0))  # removed in np2
genfromtxt = _make("genfromtxt", onp.genfromtxt)     # host file io


# -------------------------------------------------- scalar / meta queries
def spacing(x):
    return onp.spacing(x.asnumpy() if isinstance(x, NDArray) else x)


def min_scalar_type(a):
    return onp.min_scalar_type(a.asnumpy() if isinstance(a, NDArray) else a)


def promote_types(t1, t2):
    return jnp.promote_types(t1, t2)


def result_type(*args):
    return jnp.result_type(*[_jx(a) for a in args])


set_printoptions = onp.set_printoptions


def ndim(a):
    return len(a.shape) if isinstance(a, NDArray) else onp.ndim(a)


def size(a, axis=None):
    if isinstance(a, NDArray):
        return a.shape[axis] if axis is not None else int(onp.prod(a.shape))
    return onp.size(a, axis)


# (`shape` deliberately NOT defined here — mx.np already exports it)
dtype = onp.dtype
finfo = onp.finfo
iinfo = onp.iinfo


# ---------------------------------------- legacy financial fns (host)
# NumPy >= 1.20 moved these to numpy-financial; the reference's exported
# surface still carries them, so the standard closed forms live here.
def npv(rate, values):
    v = onp.asarray(_as_host(values), dtype="float64")
    return float((v / (1.0 + rate) ** onp.arange(v.size)).sum())


def _as_host(x):
    return x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)


def mirr(values, finance_rate, reinvest_rate):
    v = onp.asarray(_as_host(values), dtype="float64")
    n = v.size
    pos, neg = onp.where(v > 0, v, 0.0), onp.where(v < 0, v, 0.0)
    if not (pos.any() and neg.any()):
        return float("nan")
    fv = npv(reinvest_rate, pos) * (1 + reinvest_rate) ** (n - 1)
    pv_ = npv(finance_rate, neg) * (1 + finance_rate)
    return float((fv / -pv_) ** (1.0 / (n - 1)) - 1)


def pv(rate, nper, pmt, fv=0, when=0):
    when = {"end": 0, "begin": 1}.get(when, when)
    if rate == 0:
        return -(fv + pmt * nper)
    tmp = (1 + rate) ** nper
    return -(fv + pmt * (1 + rate * when) * (tmp - 1) / rate) / tmp


def _pmt(rate, nper, pv_, fv=0, when=0):
    if rate == 0:
        return -(fv + pv_) / nper
    tmp = (1 + rate) ** nper
    return -(fv + pv_ * tmp) * rate / ((1 + rate * when) * (tmp - 1))


def ppmt(rate, per, nper, pv_, fv=0, when=0):
    when = {"end": 0, "begin": 1}.get(when, when)
    total = _pmt(rate, nper, pv_, fv, when)
    # interest part: remaining balance after per-1 periods times rate
    bal = pv_ * (1 + rate) ** (per - 1) + \
        total * (((1 + rate) ** (per - 1) - 1) / rate if rate else per - 1)
    ipmt = -bal * rate
    if when == 1:
        ipmt = ipmt / (1 + rate)
    return total - ipmt


def rate(nper, pmt, pv_, fv, when=0, guess=0.1, tol=1e-6, maxiter=100):
    """Newton iteration on the annuity identity (numpy-financial rate)."""
    when = {"end": 0, "begin": 1}.get(when, when)
    r = guess
    for _ in range(maxiter):
        t = (1 + r) ** nper
        f = fv + pv_ * t + pmt * (1 + r * when) * (t - 1) / r
        df = (nper * pv_ * (1 + r) ** (nper - 1)
              + pmt * (when * (t - 1) / r
                       + (1 + r * when) * (nper * (1 + r) ** (nper - 1) * r
                                           - (t - 1)) / (r * r)))
        step = f / df
        r -= step
        if abs(step) < tol:
            return r
    return float("nan")


# -------------------------------------------------------- np constants
NAN = NaN = float("nan")
NINF = float("-inf")
PINF = float("inf")
NZERO = -0.0
PZERO = 0.0
bool = onp.bool_    # noqa: A001  (ref multiarray exports `bool`)
bool_ = onp.bool_
int8 = onp.int8     # scalar-type style matches the existing exports
int16 = onp.int16
float16 = onp.float16
_NoValue = getattr(onp, "_NoValue", object())
#: ref multiarray._STR_2_DTYPE_: dtype-string lookup used by array()
_STR_2_DTYPE_ = {k: onp.dtype(k) for k in
                 ("int8", "uint8", "int16", "int32", "int64", "float16",
                  "float32", "float64", "bool")}
__version__ = "1.0.0"
