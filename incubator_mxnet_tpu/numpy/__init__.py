"""mx.np — NumPy-compatible array namespace (ref python/mxnet/numpy/,
"deepnumpy"). Backed by the same NDArray/jax machinery as nd; ops here follow
NumPy semantics (true scalars, 0-d arrays, numpy broadcasting/naming).
"""
from __future__ import annotations

import numpy as onp

import jax
import jax.numpy as jnp

from ..context import current_context
from ..ndarray import NDArray
from ..ndarray import ndarray as _nd_mod
from ..ndarray.ndarray import _apply, _ctx_put, _np_dtype

__all__ = ["ndarray", "array", "zeros", "ones", "full", "empty", "arange",
           "linspace", "logspace", "eye", "identity", "meshgrid", "concatenate",
           "stack", "vstack", "hstack", "dstack", "split", "expand_dims",
           "squeeze", "transpose", "swapaxes", "moveaxis", "reshape", "where",
           "einsum", "dot", "matmul", "tensordot", "inner", "outer", "kron",
           "trace", "diag", "tril", "triu", "cross", "clip", "unique", "sort",
           "argsort", "argmax", "argmin", "take", "repeat", "tile", "flip",
           "roll", "pad", "nonzero", "count_nonzero", "copysign", "isnan",
           "isinf", "isfinite", "random", "linalg"]


class ndarray(NDArray):
    """NumPy-semantics array (ref numpy/multiarray.py ndarray)."""

    __slots__ = ()  # layout-compatible with NDArray for in-place re-classing

    def __getitem__(self, key):
        key = _nd_mod._index_fixup(key)
        return _apply_np(lambda x: x[key], self)

    def _reduce(self, fn, axis=None, keepdims=False):
        ax = _nd_mod._norm_axis(axis)
        return _apply_np(lambda x: fn(x, axis=ax, keepdims=keepdims), self)

    def mean(self, axis=None, dtype=None, keepdims=False, **kw):
        return self._reduce(jnp.mean, axis, keepdims)

    def std(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.std, axis, keepdims)

    def var(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.var, axis, keepdims)

    def reshape(self, *shape, **kw):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _apply_np(lambda x: x.reshape(shape), self)

    def flatten(self, order="C"):
        return _apply_np(lambda x: x.reshape(-1), self)

    def item(self):
        return self.asnumpy().item()

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True):
        return _apply_np(lambda x: x.astype(_np_dtype(dtype)), self)

    def copy(self):
        # through _apply so the autograd tape links the copy to its source
        out = _apply_np(lambda x: jnp.array(x), self)
        return out

    def as_nd_ndarray(self):
        """Classic-NDArray view that STAYS ON THE TAPE (an identity op —
        constructing a bare NDArray here would silently cut gradients)."""
        out = _nd_mod._apply(lambda x: x, self)
        out.__class__ = NDArray
        return out

    @property
    def T(self):
        return _apply_np(jnp.transpose, self)

    # numpy semantics: comparisons yield BOOL masks (mx.nd yields float 0/1),
    # so `a[a > 0]` boolean-indexes correctly
    def _cmp(self, other, fn):
        o = other._data if isinstance(other, NDArray) else other
        return _apply_np(lambda x: fn(x, o), self)

    def __gt__(self, other):
        return self._cmp(other, jnp.greater)

    def __ge__(self, other):
        return self._cmp(other, jnp.greater_equal)

    def __lt__(self, other):
        return self._cmp(other, jnp.less)

    def __le__(self, other):
        return self._cmp(other, jnp.less_equal)

    def __eq__(self, other):
        if not isinstance(other, (NDArray, int, float, bool, complex,
                                  onp.ndarray, onp.generic, list, tuple)):
            return False  # numpy parity: `x == None` is falsy, not an error
        return self._cmp(other, jnp.equal)

    def __ne__(self, other):
        if not isinstance(other, (NDArray, int, float, bool, complex,
                                  onp.ndarray, onp.generic, list, tuple)):
            return True
        return self._cmp(other, jnp.not_equal)

    __hash__ = NDArray.__hash__


def _apply_np(fn, *inputs):
    """_apply but producing mx.np.ndarray outputs (keeps autograd taping).

    Re-classes the returned NDArray in place so the tape's object identity is
    preserved (backward is keyed by id(output))."""
    out = _nd_mod._apply(fn, *inputs)
    if isinstance(out, (list, tuple)):
        for o in out:
            o.__class__ = ndarray
        return out
    out.__class__ = ndarray
    return out


def _to(x):
    if isinstance(x, NDArray):
        return x
    return array(x)


# ------------------------------------------------------------ creation
def array(object, dtype=None, ctx=None):
    if isinstance(object, NDArray):
        data = object._data
        if dtype is not None:
            data = data.astype(_np_dtype(dtype))
        return ndarray(data)
    if dtype is None and isinstance(object, (list, tuple, int, float)):
        # MXNet deepnumpy semantics: python containers default to float32
        data = onp.asarray(object, dtype=onp.float32)
    else:
        data = onp.asarray(object, dtype=_np_dtype(dtype) if dtype else None)
        if data.dtype == onp.float64 and dtype is None:
            data = data.astype(onp.float32)
    return ndarray(_ctx_put(data, ctx))


def zeros(shape, dtype="float32", ctx=None, **kw):
    return ndarray(_ctx_put(jnp.zeros(shape, _np_dtype(dtype)), ctx))


def ones(shape, dtype="float32", ctx=None, **kw):
    return ndarray(_ctx_put(jnp.ones(shape, _np_dtype(dtype)), ctx))


def full(shape, fill_value, dtype="float32", ctx=None, **kw):
    return ndarray(_ctx_put(jnp.full(shape, fill_value, _np_dtype(dtype)), ctx))


def empty(shape, dtype="float32", ctx=None):
    return zeros(shape, dtype, ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    return ndarray(_ctx_put(jnp.arange(start, stop, step,
                                       _np_dtype(dtype) if dtype else None), ctx))


def linspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None, **kw):
    return ndarray(_ctx_put(jnp.linspace(start, stop, num, endpoint=endpoint,
                                         dtype=_np_dtype(dtype) if dtype else None), ctx))


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None, ctx=None):
    return ndarray(_ctx_put(jnp.logspace(start, stop, num, endpoint, base), ctx))


def eye(N, M=None, k=0, dtype="float32", ctx=None):
    return ndarray(_ctx_put(jnp.eye(N, M, k, dtype=_np_dtype(dtype)), ctx))


def identity(n, dtype="float32", ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def meshgrid(*xi, indexing="xy"):
    outs = jnp.meshgrid(*[_to(x)._data for x in xi], indexing=indexing)
    return [ndarray(o) for o in outs]


# ------------------------------------------------------------ generated ops
_UNARY_NP = ["abs", "absolute", "sign", "rint", "ceil", "floor", "trunc", "sqrt",
             "cbrt", "square", "exp", "expm1", "log", "log2", "log10", "log1p",
             "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh", "cosh",
             "tanh", "arcsinh", "arccosh", "arctanh", "degrees", "radians",
             "reciprocal", "negative", "isnan", "isinf", "isfinite", "sort",
             "nonzero"]
_BINARY_NP = ["add", "subtract", "multiply", "divide", "true_divide", "mod",
              "remainder", "power", "maximum", "minimum", "hypot", "arctan2",
              "copysign", "equal", "not_equal", "less", "less_equal", "greater",
              "greater_equal", "logical_and", "logical_or", "logical_xor",
              "float_power", "fmod", "gcd", "lcm"]
_REDUCE_NP = ["sum", "prod", "mean", "std", "var", "max", "min", "amax", "amin",
              "nansum", "nanprod", "nanmax", "nanmin", "median", "all", "any"]

_g = globals()
for _name in _UNARY_NP:
    def _mk(fn):
        def op(x, out=None, **kw):
            return _apply_np(fn, _to(x))
        return op
    _g[_name] = _mk(getattr(jnp, _name))
    if _name not in __all__:
        __all__.append(_name)

for _name in _BINARY_NP:
    def _mkb(fn):
        def op(x1, x2, out=None, **kw):
            if isinstance(x1, NDArray) and isinstance(x2, NDArray):
                return _apply_np(fn, x1, x2)
            if isinstance(x1, NDArray):
                return _apply_np(lambda a: fn(a, x2), x1)
            if isinstance(x2, NDArray):
                return _apply_np(lambda b: fn(x1, b), x2)
            return _apply_np(fn, _to(x1), _to(x2))
        return op
    _g[_name] = _mkb(getattr(jnp, _name))
    if _name not in __all__:
        __all__.append(_name)

for _name in _REDUCE_NP:
    def _mkr(fn):
        def op(a, axis=None, keepdims=False, out=None, **kw):
            ax = _nd_mod._norm_axis(axis)
            return _apply_np(lambda x: fn(x, axis=ax, keepdims=keepdims), _to(a))
        return op
    _g[_name] = _mkr(getattr(jnp, _name))
    if _name not in __all__:
        __all__.append(_name)


# ------------------------------------------------------------ shape/linalg ops
def concatenate(seq, axis=0, out=None):
    return _apply_np(lambda *xs: jnp.concatenate(xs, axis=axis), *[_to(s) for s in seq])


def stack(arrays, axis=0, out=None):
    return _apply_np(lambda *xs: jnp.stack(xs, axis=axis), *[_to(a) for a in arrays])


def vstack(tup):
    return _apply_np(lambda *xs: jnp.vstack(xs), *[_to(a) for a in tup])


def hstack(tup):
    return _apply_np(lambda *xs: jnp.hstack(xs), *[_to(a) for a in tup])


def dstack(tup):
    return _apply_np(lambda *xs: jnp.dstack(xs), *[_to(a) for a in tup])


def split(ary, indices_or_sections, axis=0):
    out = _apply_np(lambda x: jnp.split(x, indices_or_sections, axis=axis), _to(ary))
    return list(out)


def expand_dims(a, axis):
    return _apply_np(lambda x: jnp.expand_dims(x, axis), _to(a))


def squeeze(a, axis=None):
    return _apply_np(lambda x: jnp.squeeze(x, axis), _to(a))


def transpose(a, axes=None):
    return _apply_np(lambda x: jnp.transpose(x, axes), _to(a))


def swapaxes(a, axis1, axis2):
    return _apply_np(lambda x: jnp.swapaxes(x, axis1, axis2), _to(a))


def moveaxis(a, source, destination):
    return _apply_np(lambda x: jnp.moveaxis(x, source, destination), _to(a))


def reshape(a, newshape, order="C"):
    return _apply_np(lambda x: jnp.reshape(x, newshape), _to(a))


def where(condition, x=None, y=None):
    if x is None:
        return tuple(ndarray(o) for o in jnp.where(_to(condition)._data))
    return _apply_np(lambda c, a, b: jnp.where(c, a, b), _to(condition), _to(x), _to(y))


def einsum(subscripts, *operands, **kw):
    """ref numpy/np_einsum_op — jnp.einsum hits the MXU directly."""
    return _apply_np(lambda *xs: jnp.einsum(subscripts, *xs),
                     *[_to(o) for o in operands])


def dot(a, b, out=None):
    return _apply_np(jnp.dot, _to(a), _to(b))


def matmul(a, b, out=None):
    return _apply_np(jnp.matmul, _to(a), _to(b))


def tensordot(a, b, axes=2):
    return _apply_np(lambda x, y: jnp.tensordot(x, y, axes=axes), _to(a), _to(b))


def inner(a, b):
    return _apply_np(jnp.inner, _to(a), _to(b))


def outer(a, b):
    return _apply_np(jnp.outer, _to(a), _to(b))


def kron(a, b):
    return _apply_np(jnp.kron, _to(a), _to(b))


def trace(a, offset=0, axis1=0, axis2=1):
    return _apply_np(lambda x: jnp.trace(x, offset, axis1, axis2), _to(a))


def diag(v, k=0):
    return _apply_np(lambda x: jnp.diag(x, k), _to(v))


def tril(m, k=0):
    return _apply_np(lambda x: jnp.tril(x, k), _to(m))


def triu(m, k=0):
    return _apply_np(lambda x: jnp.triu(x, k), _to(m))


def cross(a, b, axis=-1):
    return _apply_np(lambda x, y: jnp.cross(x, y, axis=axis), _to(a), _to(b))


def clip(a, a_min, a_max, out=None):
    return _apply_np(lambda x: jnp.clip(x, a_min, a_max), _to(a))


def unique(ar, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    res = onp.unique(_to(ar).asnumpy(), return_index=return_index,
                     return_inverse=return_inverse, return_counts=return_counts,
                     axis=axis)
    if isinstance(res, tuple):
        return tuple(array(r) for r in res)
    return array(res)


def argsort(a, axis=-1, kind=None, order=None):
    return _apply_np(lambda x: jnp.argsort(x, axis=axis), _to(a))


def argmax(a, axis=None, out=None, keepdims=False):
    return _apply_np(lambda x: jnp.argmax(x, axis=axis, keepdims=keepdims), _to(a))


def argmin(a, axis=None, out=None, keepdims=False):
    return _apply_np(lambda x: jnp.argmin(x, axis=axis, keepdims=keepdims), _to(a))


def take(a, indices, axis=None, mode=None, out=None):
    return _apply_np(lambda x, i: jnp.take(x, i.astype(jnp.int32), axis=axis),
                     _to(a), _to(indices))


def repeat(a, repeats, axis=None):
    return _apply_np(lambda x: jnp.repeat(x, repeats, axis=axis), _to(a))


def tile(A, reps):
    return _apply_np(lambda x: jnp.tile(x, reps), _to(A))


def flip(m, axis=None):
    return _apply_np(lambda x: jnp.flip(x, axis), _to(m))


def roll(a, shift, axis=None):
    return _apply_np(lambda x: jnp.roll(x, shift, axis), _to(a))


def pad(array_, pad_width, mode="constant", **kw):
    return _apply_np(lambda x: jnp.pad(x, pad_width, mode=mode, **kw), _to(array_))


def count_nonzero(a, axis=None):
    return _apply_np(lambda x: jnp.count_nonzero(x, axis=axis), _to(a))


def zeros_like(a, dtype=None):
    return _apply_np(lambda x: jnp.zeros_like(x, dtype=_np_dtype(dtype) if dtype
                                              else None), _to(a))


def ones_like(a, dtype=None):
    return _apply_np(lambda x: jnp.ones_like(x, dtype=_np_dtype(dtype) if dtype
                                             else None), _to(a))


def full_like(a, fill_value, dtype=None):
    return _apply_np(lambda x: jnp.full_like(x, fill_value), _to(a))


__all__ += ["zeros_like", "ones_like", "full_like"]


# ------------------------------------------------------------ submodules
class _NPRandom:
    """mx.np.random (ref python/mxnet/numpy/random.py)."""

    @staticmethod
    def _key():
        from ..ndarray.random import _next_key
        return _next_key()

    def seed(self, s):
        from ..ndarray import random as _r
        _r.seed(s)

    def uniform(self, low=0.0, high=1.0, size=None, dtype=None, ctx=None):
        size = size if size is not None else ()
        return ndarray(jax.random.uniform(self._key(), size if isinstance(size, tuple)
                                          else (size,), _np_dtype(dtype or "float32"),
                                          low, high))

    def normal(self, loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
        size = size if size is not None else ()
        shp = size if isinstance(size, tuple) else (size,)
        return ndarray(loc + scale * jax.random.normal(
            self._key(), shp, _np_dtype(dtype or "float32")))

    def randint(self, low, high=None, size=None, dtype="int64", ctx=None):
        if high is None:
            low, high = 0, low
        shp = size if isinstance(size, tuple) else ((size,) if size else ())
        return ndarray(jax.random.randint(self._key(), shp, low, high,
                                          onp.dtype("int32")))

    def rand(self, *size):
        return self.uniform(size=size or ())

    def randn(self, *size):
        return self.normal(size=size or ())

    def choice(self, a, size=None, replace=True, p=None):
        arr = _to(a)._data if isinstance(a, (NDArray, onp.ndarray, list)) else jnp.arange(a)
        shp = size if isinstance(size, tuple) else ((size,) if size else ())
        return ndarray(jax.random.choice(self._key(), arr, shp, replace,
                                         None if p is None else _to(p)._data))

    def shuffle(self, x):
        x._data = jax.random.permutation(self._key(), x._data, axis=0)

    # -- distribution parity (ref numpy/random.py; np_random ops) -------
    @staticmethod
    def _shp(size):
        return size if isinstance(size, tuple) else (() if size is None else (size,))

    def beta(self, a, b, size=None):
        return ndarray(jax.random.beta(self._key(), a, b, self._shp(size)))

    def gamma(self, shape, scale=1.0, size=None):
        return ndarray(scale * jax.random.gamma(self._key(), shape, self._shp(size)))

    def exponential(self, scale=1.0, size=None):
        return ndarray(scale * jax.random.exponential(self._key(), self._shp(size)))

    def laplace(self, loc=0.0, scale=1.0, size=None):
        return ndarray(loc + scale * jax.random.laplace(self._key(), self._shp(size)))

    def logistic(self, loc=0.0, scale=1.0, size=None):
        return ndarray(loc + scale * jax.random.logistic(self._key(), self._shp(size)))

    def gumbel(self, loc=0.0, scale=1.0, size=None):
        return ndarray(loc + scale * jax.random.gumbel(self._key(), self._shp(size)))

    def pareto(self, a, size=None):
        return ndarray(jax.random.pareto(self._key(), a, self._shp(size)) - 1.0)

    def weibull(self, a, size=None):
        u = jax.random.uniform(self._key(), self._shp(size))
        return ndarray((-jnp.log1p(-u)) ** (1.0 / a))

    def chisquare(self, df, size=None):
        return ndarray(jax.random.chisquare(self._key(), df, self._shp(size)))

    def poisson(self, lam=1.0, size=None):
        return ndarray(jax.random.poisson(self._key(), lam, self._shp(size)))

    def multinomial(self, n, pvals, size=None):
        draws = jax.random.categorical(
            self._key(), jnp.log(jnp.asarray(pvals)), shape=self._shp(size) + (n,))
        return ndarray(jax.nn.one_hot(draws, len(pvals), dtype="int32").sum(-2))

    def dirichlet(self, alpha, size=None):
        return ndarray(jax.random.dirichlet(self._key(), jnp.asarray(alpha),
                                            self._shp(size)))

    def permutation(self, x):
        if isinstance(x, int):
            return ndarray(jax.random.permutation(self._key(), x))
        return ndarray(jax.random.permutation(self._key(), _to(x)._data, axis=0))

    def lognormal(self, mean=0.0, sigma=1.0, size=None):
        return ndarray(jnp.exp(mean + sigma * jax.random.normal(
            self._key(), self._shp(size))))

    def multivariate_normal(self, mean, cov, size=None):
        return ndarray(jax.random.multivariate_normal(
            self._key(), _to(mean)._data.astype(jnp.float32),
            _to(cov)._data.astype(jnp.float32), self._shp(size) or None))

    def power(self, a, size=None):
        # inverse-CDF of p(x) = a x^(a-1) on [0, 1]
        u = jax.random.uniform(self._key(), self._shp(size))
        return ndarray(u ** (1.0 / a))

    def rayleigh(self, scale=1.0, size=None):
        u = jax.random.uniform(self._key(), self._shp(size))
        return ndarray(scale * jnp.sqrt(-2.0 * jnp.log1p(-u)))


random = _NPRandom()


class _NPLinalg:
    """mx.np.linalg (ref python/mxnet/numpy/linalg.py)."""

    def norm(self, x, ord=None, axis=None, keepdims=False):
        return _apply_np(lambda a: jnp.linalg.norm(a, ord, axis, keepdims), _to(x))

    def inv(self, a):
        return _apply_np(jnp.linalg.inv, _to(a))

    def det(self, a):
        return _apply_np(jnp.linalg.det, _to(a))

    def slogdet(self, a):
        s, l = jnp.linalg.slogdet(_to(a)._data)
        return ndarray(s), ndarray(l)

    def cholesky(self, a):
        return _apply_np(jnp.linalg.cholesky, _to(a))

    def qr(self, a):
        q, r = jnp.linalg.qr(_to(a)._data)
        return ndarray(q), ndarray(r)

    def svd(self, a):
        u, s, vt = jnp.linalg.svd(_to(a)._data, full_matrices=False)
        return ndarray(u), ndarray(s), ndarray(vt)

    def eigh(self, a):
        w, v = jnp.linalg.eigh(_to(a)._data)
        return ndarray(w), ndarray(v)

    def solve(self, a, b):
        return _apply_np(jnp.linalg.solve, _to(a), _to(b))

    def lstsq(self, a, b, rcond="warn"):
        res = jnp.linalg.lstsq(_to(a)._data, _to(b)._data)
        return tuple(ndarray(r) for r in res)

    def pinv(self, a):
        return _apply_np(jnp.linalg.pinv, _to(a))

    def matrix_rank(self, a):
        return _apply_np(jnp.linalg.matrix_rank, _to(a))


linalg = _NPLinalg()

pi = onp.pi
e = onp.e
inf = onp.inf
nan = onp.nan
newaxis = None
float32 = onp.float32
float64 = onp.float64
int32 = onp.int32
int64 = onp.int64
uint8 = onp.uint8
bool_ = onp.bool_


# ------------------------------------------------------------ batch 2:
# boolean masking, insert/delete, stats, bit ops, index helpers
# (ref src/operator/numpy/np_insert_op*, np_delete_op*, np_percentile_op,
#  np_cross, np_diff, np_ediff1d, np_interp, np_bincount, np_pad ...)
def insert(arr, obj, values, axis=None):
    return ndarray(jnp.insert(_to(arr)._data, obj,
                              _to(values)._data if isinstance(values, (NDArray, list, onp.ndarray)) else values,
                              axis=axis))


def delete(arr, obj, axis=None):
    o = _to(obj)._data if isinstance(obj, (NDArray, list, onp.ndarray)) else obj
    return ndarray(jnp.delete(_to(arr)._data, onp.asarray(o), axis=axis))


def append(arr, values, axis=None):
    return _apply_np(lambda a, v: jnp.append(a, v, axis=axis), _to(arr), _to(values))


def ravel(a, order="C"):
    return _apply_np(lambda x: x.reshape(-1), _to(a))


def atleast_1d(*arys):
    out = [_apply_np(jnp.atleast_1d, _to(a)) for a in arys]
    return out[0] if len(out) == 1 else out


def atleast_2d(*arys):
    out = [_apply_np(jnp.atleast_2d, _to(a)) for a in arys]
    return out[0] if len(out) == 1 else out


def atleast_3d(*arys):
    out = [_apply_np(jnp.atleast_3d, _to(a)) for a in arys]
    return out[0] if len(out) == 1 else out


def broadcast_to(array_, shape):
    return _apply_np(lambda x: jnp.broadcast_to(x, shape), _to(array_))


def broadcast_arrays(*args):
    outs = jnp.broadcast_arrays(*[_to(a)._data for a in args])
    return [ndarray(o) for o in outs]


def searchsorted(a, v, side="left", sorter=None):
    return _apply_np(lambda x, q: jnp.searchsorted(x, q, side=side),
                     _to(a), _to(v))


def digitize(x, bins, right=False):
    return _apply_np(lambda a, b: jnp.digitize(a, b, right=right),
                     _to(x), _to(bins))


def bincount(x, weights=None, minlength=0):
    import builtins
    xd = _to(x)._data
    # NB: plain `max` here would resolve to this module's reduction op
    length = builtins.max(int(minlength), int(xd.max()) + 1 if xd.size else 0)
    w = None if weights is None else _to(weights)._data
    return ndarray(jnp.bincount(xd, w, length=length))


def histogram(a, bins=10, range=None, weights=None, density=None):
    h, edges = jnp.histogram(_to(a)._data, bins=bins, range=range,
                             weights=None if weights is None else _to(weights)._data,
                             density=density)
    return ndarray(h), ndarray(edges)


def cumsum(a, axis=None, dtype=None, out=None):
    return _apply_np(lambda x: jnp.cumsum(x, axis=axis, dtype=_np_dtype(dtype) if dtype else None), _to(a))


def cumprod(a, axis=None, dtype=None, out=None):
    return _apply_np(lambda x: jnp.cumprod(x, axis=axis), _to(a))


def diff(a, n=1, axis=-1, prepend=None, append=None):
    return _apply_np(lambda x: jnp.diff(x, n=n, axis=axis), _to(a))


def ediff1d(ary, to_end=None, to_begin=None):
    return _apply_np(lambda x: jnp.ediff1d(x, to_end, to_begin), _to(ary))


def gradient(f, *varargs, axis=None, edge_order=1):
    out = jnp.gradient(_to(f)._data, *varargs, axis=axis)
    if isinstance(out, (list, tuple)):
        return [ndarray(o) for o in out]
    return ndarray(out)


def trapz(y, x=None, dx=1.0, axis=-1):
    return ndarray(jnp.trapezoid(_to(y)._data,
                                 None if x is None else _to(x)._data,
                                 dx=dx, axis=axis))


def interp(x, xp, fp, left=None, right=None, period=None):
    return _apply_np(lambda a, b, c: jnp.interp(a, b, c, left, right, period),
                     _to(x), _to(xp), _to(fp))


def percentile(a, q, axis=None, interpolation=None, keepdims=False, **kw):
    method = interpolation or kw.get("method", "linear")
    return _apply_np(lambda x: jnp.percentile(x, jnp.asarray(q), axis=axis,
                                              method=method, keepdims=keepdims), _to(a))


def quantile(a, q, axis=None, interpolation=None, keepdims=False, **kw):
    method = interpolation or kw.get("method", "linear")
    return _apply_np(lambda x: jnp.quantile(x, jnp.asarray(q), axis=axis,
                                            method=method, keepdims=keepdims), _to(a))


def average(a, axis=None, weights=None, returned=False):
    if weights is None:
        out = jnp.mean(_to(a)._data, axis=axis)
        scl = jnp.asarray(onp.prod([_to(a)._data.shape[ax] for ax in
                                    (range(_to(a)._data.ndim) if axis is None
                                     else [axis])]), "float32")
    else:
        out, scl = jnp.average(_to(a)._data, axis=axis,
                               weights=_to(weights)._data, returned=True)
    return (ndarray(out), ndarray(scl)) if returned else ndarray(out)


def cov(m, y=None, rowvar=True, bias=False, ddof=None, fweights=None, aweights=None):
    return ndarray(jnp.cov(_to(m)._data, None if y is None else _to(y)._data,
                           rowvar=rowvar, bias=bias, ddof=ddof))


def corrcoef(x, y=None, rowvar=True):
    return ndarray(jnp.corrcoef(_to(x)._data,
                                None if y is None else _to(y)._data, rowvar))


def nanmean(a, axis=None, keepdims=False, **kw):
    return _apply_np(lambda x: jnp.nanmean(x, axis=axis, keepdims=keepdims), _to(a))


def nanstd(a, axis=None, keepdims=False, **kw):
    return _apply_np(lambda x: jnp.nanstd(x, axis=axis, keepdims=keepdims), _to(a))


def nanvar(a, axis=None, keepdims=False, **kw):
    return _apply_np(lambda x: jnp.nanvar(x, axis=axis, keepdims=keepdims), _to(a))


def nan_to_num(x, copy=True, nan=0.0, posinf=None, neginf=None):
    return _apply_np(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                              neginf=neginf), _to(x))


def around(a, decimals=0, out=None):
    return _apply_np(lambda x: jnp.round(x, decimals), _to(a))


round = around
round_ = around


def fix(x, out=None):
    return _apply_np(jnp.fix, _to(x))


def signbit(x, out=None):
    return _apply_np(jnp.signbit, _to(x))


def heaviside(x1, x2, out=None):
    return _apply_np(jnp.heaviside, _to(x1), _to(x2))


def exp2(x, out=None):
    return _apply_np(jnp.exp2, _to(x))


def deg2rad(x, out=None):
    return _apply_np(jnp.deg2rad, _to(x))


def rad2deg(x, out=None):
    return _apply_np(jnp.rad2deg, _to(x))


def logical_not(x, out=None):
    return _apply_np(jnp.logical_not, _to(x))


def invert(x, out=None):
    return _apply_np(jnp.invert, _to(x))


bitwise_not = invert


def bitwise_and(x1, x2, out=None):
    return _apply_np(jnp.bitwise_and, _to(x1), _to(x2))


def bitwise_or(x1, x2, out=None):
    return _apply_np(jnp.bitwise_or, _to(x1), _to(x2))


def bitwise_xor(x1, x2, out=None):
    return _apply_np(jnp.bitwise_xor, _to(x1), _to(x2))


def left_shift(x1, x2, out=None):
    return _apply_np(jnp.left_shift, _to(x1), _to(x2))


def right_shift(x1, x2, out=None):
    return _apply_np(jnp.right_shift, _to(x1), _to(x2))


def floor_divide(x1, x2, out=None):
    return _apply_np(jnp.floor_divide, _to(x1), _to(x2))


def flatnonzero(a):
    return ndarray(jnp.flatnonzero(_to(a)._data))


def argwhere(a):
    return ndarray(jnp.argwhere(_to(a)._data))


def extract(condition, arr):
    return ndarray(jnp.extract(_to(condition)._data, _to(arr)._data))


def compress(condition, a, axis=None):
    return ndarray(jnp.compress(_to(condition)._data, _to(a)._data, axis=axis))


def resize(a, new_shape):
    return ndarray(jnp.resize(_to(a)._data, new_shape))


def rot90(m, k=1, axes=(0, 1)):
    return _apply_np(lambda x: jnp.rot90(x, k, axes), _to(m))


def fliplr(m):
    return _apply_np(jnp.fliplr, _to(m))


def flipud(m):
    return _apply_np(jnp.flipud, _to(m))


def array_split(ary, indices_or_sections, axis=0):
    outs = jnp.array_split(_to(ary)._data, indices_or_sections, axis=axis)
    return [ndarray(o) for o in outs]


def vsplit(ary, indices_or_sections):
    return [ndarray(o) for o in jnp.vsplit(_to(ary)._data, indices_or_sections)]


def hsplit(ary, indices_or_sections):
    return [ndarray(o) for o in jnp.hsplit(_to(ary)._data, indices_or_sections)]


def dsplit(ary, indices_or_sections):
    return [ndarray(o) for o in jnp.dsplit(_to(ary)._data, indices_or_sections)]


def column_stack(tup):
    return _apply_np(lambda *xs: jnp.column_stack(xs), *[_to(a) for a in tup])


row_stack = vstack


def tri(N, M=None, k=0, dtype="float32"):
    return ndarray(jnp.tri(N, M, k, _np_dtype(dtype)))


def vander(x, N=None, increasing=False):
    return _apply_np(lambda a: jnp.vander(a, N, increasing), _to(x))


def unravel_index(indices, shape, order="C"):
    outs = jnp.unravel_index(_to(indices)._data, shape)
    return tuple(ndarray(o) for o in outs)


def ravel_multi_index(multi_index, dims, mode="raise", order="C"):
    mi = tuple(_to(m)._data for m in multi_index)
    return ndarray(jnp.ravel_multi_index(mi, dims, mode="wrap" if mode == "wrap" else "clip"))


def indices(dimensions, dtype="int32", sparse=False):
    out = jnp.indices(dimensions, _np_dtype(dtype), sparse)
    if sparse:
        return tuple(ndarray(o) for o in out)
    return ndarray(out)


def diag_indices(n, ndim=2):
    return tuple(ndarray(o) for o in jnp.diag_indices(n, ndim))


def tril_indices(n, k=0, m=None):
    return tuple(ndarray(o) for o in jnp.tril_indices(n, k, m))


def triu_indices(n, k=0, m=None):
    return tuple(ndarray(o) for o in jnp.triu_indices(n, k, m))


def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    import builtins
    return builtins.bool(jnp.allclose(_to(a)._data, _to(b)._data, rtol, atol,
                                      equal_nan))


def isclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return _apply_np(lambda x, y: jnp.isclose(x, y, rtol, atol, equal_nan),
                     _to(a), _to(b))


def array_equal(a1, a2, equal_nan=False):
    import builtins
    return builtins.bool(jnp.array_equal(_to(a1)._data, _to(a2)._data,
                                         equal_nan))


def ptp(a, axis=None, keepdims=False):
    return _apply_np(lambda x: jnp.ptp(x, axis=axis, keepdims=keepdims), _to(a))


def may_share_memory(a, b, max_work=None):
    return False  # jax arrays are immutable; views never alias writably


__all__ += [
    "insert", "delete", "append", "ravel", "atleast_1d", "atleast_2d",
    "atleast_3d", "broadcast_to", "broadcast_arrays", "searchsorted",
    "digitize", "bincount", "histogram", "cumsum", "cumprod", "diff",
    "ediff1d", "gradient", "trapz", "interp", "percentile", "quantile",
    "average", "cov", "corrcoef", "nanmean", "nanstd", "nanvar",
    "nan_to_num", "around", "round", "round_", "fix", "signbit",
    "heaviside", "exp2", "deg2rad", "rad2deg", "logical_not", "invert",
    "bitwise_not", "bitwise_and", "bitwise_or", "bitwise_xor", "left_shift",
    "right_shift", "floor_divide", "flatnonzero", "argwhere", "extract",
    "compress", "resize", "rot90", "fliplr", "flipud", "array_split",
    "vsplit", "hsplit", "dsplit", "column_stack", "row_stack", "tri",
    "vander", "unravel_index", "ravel_multi_index", "indices",
    "diag_indices", "tril_indices", "triu_indices", "allclose", "isclose",
    "array_equal", "ptp", "may_share_memory",
]


# ------------------------------------------------------------ batch 3:
# window fns, nan-reductions, linalg completion, misc parity
# (ref python/mxnet/numpy __all__ — blackman/hamming/hanning windows from
#  src/operator/numpy/np_window_op.cc; eig family np_eig.cc; tensorinv/
#  tensorsolve np_tensorinv_op.cc/np_tensorsolve_op.cc)
def blackman(M, dtype=None, ctx=None):
    return ndarray(_ctx_put(jnp.blackman(M), ctx))


def hamming(M, dtype=None, ctx=None):
    return ndarray(_ctx_put(jnp.hamming(M), ctx))


def hanning(M, dtype=None, ctx=None):
    return ndarray(_ctx_put(jnp.hanning(M), ctx))


def empty_like(prototype, dtype=None, order="C", ctx=None):
    # XLA has no uninitialized buffers; zeros is the deterministic choice
    return ndarray(jnp.zeros_like(_to(prototype)._data,
                                  dtype=_np_dtype(dtype) if dtype else None))


def fabs(x):
    return _apply_np(jnp.fabs, _to(x))


def isneginf(x):
    return ndarray(jnp.isneginf(_to(x)._data))


def isposinf(x):
    return ndarray(jnp.isposinf(_to(x)._data))


def ldexp(x1, x2):
    # exponent must be integral (jnp.ldexp contract); the reference's
    # np_ldexp accepts float exponents, so cast like it truncates
    def fn(a, b):
        return jnp.ldexp(a, b.astype(jnp.int32)
                         if not jnp.issubdtype(b.dtype, jnp.integer) else b)
    return _apply_np(fn, _to(x1), _to(x2))


def logaddexp(x1, x2):
    return _apply_np(jnp.logaddexp, _to(x1), _to(x2))


def polyval(p, x):
    return _apply_np(jnp.polyval, _to(p), _to(x))


def vdot(a, b):
    return _apply_np(jnp.vdot, _to(a), _to(b))


def shape(a):
    return tuple(_to(a).shape)


def shares_memory(a, b, max_work=None):
    return False  # immutable jax buffers: no writable aliasing (see may_share_memory)


def diag_indices_from(arr):
    idx = onp.diag_indices_from(onp.empty(_to(arr).shape))
    return tuple(ndarray(jnp.asarray(i)) for i in idx)


def median(a, axis=None, keepdims=False):
    return _apply_np(lambda x: jnp.median(x, axis=axis, keepdims=keepdims), _to(a))


def nansum(a, axis=None, keepdims=False):
    return _apply_np(lambda x: jnp.nansum(x, axis=axis, keepdims=keepdims), _to(a))


def nanmax(a, axis=None, keepdims=False):
    return _apply_np(lambda x: jnp.nanmax(x, axis=axis, keepdims=keepdims), _to(a))


def nanmin(a, axis=None, keepdims=False):
    return _apply_np(lambda x: jnp.nanmin(x, axis=axis, keepdims=keepdims), _to(a))


def nanargmax(a, axis=None):
    return ndarray(jnp.nanargmax(_to(a)._data, axis=axis))


def nanargmin(a, axis=None):
    return ndarray(jnp.nanargmin(_to(a)._data, axis=axis))


def nancumsum(a, axis=None):
    return _apply_np(lambda x: jnp.nancumsum(x, axis=axis), _to(a))


def take_along_axis(arr, indices, axis):
    return _apply_np(lambda x: jnp.take_along_axis(x, _to(indices)._data, axis),
                     _to(arr))


def isin(element, test_elements, invert=False):
    return ndarray(jnp.isin(_to(element)._data, _to(test_elements)._data,
                            invert=invert))


def in1d(ar1, ar2, invert=False):
    return ndarray(jnp.isin(_to(ar1)._data.ravel(), _to(ar2)._data,
                            invert=invert))


def union1d(ar1, ar2):
    # eager-only (result shape is data-dependent); host set-op like the
    # reference's CPU kernels
    return ndarray(jnp.asarray(onp.union1d(_to(ar1).asnumpy(), _to(ar2).asnumpy())))


def intersect1d(ar1, ar2):
    return ndarray(jnp.asarray(onp.intersect1d(_to(ar1).asnumpy(), _to(ar2).asnumpy())))


def setdiff1d(ar1, ar2):
    return ndarray(jnp.asarray(onp.setdiff1d(_to(ar1).asnumpy(), _to(ar2).asnumpy())))


def real(x):
    return _apply_np(jnp.real, _to(x))


def imag(x):
    return _apply_np(jnp.imag, _to(x))


def conj(x):
    return _apply_np(jnp.conj, _to(x))


def positive(x):
    return _apply_np(jnp.positive, _to(x))


def float_power(x1, x2):
    return _apply_np(jnp.float_power, _to(x1), _to(x2))


def fmod(x1, x2):
    return _apply_np(jnp.fmod, _to(x1), _to(x2))


def divmod(x1, x2):  # noqa: A001
    q = _apply_np(jnp.floor_divide, _to(x1), _to(x2))
    r = _apply_np(jnp.remainder, _to(x1), _to(x2))
    return q, r


def gcd(x1, x2):
    return ndarray(jnp.gcd(_to(x1)._data, _to(x2)._data))


def lcm(x1, x2):
    return ndarray(jnp.lcm(_to(x1)._data, _to(x2)._data))


def rollaxis(a, axis, start=0):
    return _apply_np(lambda x: jnp.rollaxis(x, axis, start), _to(a))


def sinc(x):
    return _apply_np(jnp.sinc, _to(x))


def copysign(x1, x2):
    return _apply_np(jnp.copysign, _to(x1), _to(x2))


def rint(x):
    return _apply_np(jnp.rint, _to(x))


def _linalg_eig(self, a):
    """General (non-symmetric) eig: XLA supports it on CPU only, so this is
    the host-fallback path (the reference's numpy_op_fallback.py idiom)."""
    w, v = onp.linalg.eig(_to(a).asnumpy())
    return ndarray(jnp.asarray(w)), ndarray(jnp.asarray(v))


def _linalg_eigvals(self, a):
    return ndarray(jnp.asarray(onp.linalg.eigvals(_to(a).asnumpy())))


def _linalg_eigvalsh(self, a, UPLO="L"):
    return ndarray(jnp.linalg.eigvalsh(_to(a)._data, UPLO=UPLO))


def _linalg_tensorinv(self, a, ind=2):
    return _apply_np(lambda x: jnp.linalg.tensorinv(x, ind=ind), _to(a))


def _linalg_tensorsolve(self, a, b, axes=None):
    return _apply_np(lambda x, y: jnp.linalg.tensorsolve(x, y, axes=axes),
                     _to(a), _to(b))


_NPLinalg.eig = _linalg_eig
_NPLinalg.eigvals = _linalg_eigvals
_NPLinalg.eigvalsh = _linalg_eigvalsh
_NPLinalg.tensorinv = _linalg_tensorinv
_NPLinalg.tensorsolve = _linalg_tensorsolve

__all__ += [
    "blackman", "hamming", "hanning", "empty_like", "fabs", "isneginf",
    "isposinf", "ldexp", "logaddexp", "polyval", "vdot", "shape",
    "shares_memory", "diag_indices_from", "median", "nansum", "nanmax",
    "nanmin", "nanargmax", "nanargmin", "nancumsum", "take_along_axis",
    "isin", "in1d", "union1d", "intersect1d", "setdiff1d", "real", "imag",
    "conj", "positive", "float_power", "fmod", "divmod", "gcd", "lcm",
    "rollaxis", "sinc", "copysign", "rint",
]


# ------------------------------------------------------------ batch 4:
# NumPy dispatch protocol (ref numpy_dispatch_protocol.py +
# numpy_op_fallback.py): official-numpy functions and ufuncs called ON
# mx.np arrays dispatch back into this namespace, falling back to host
# numpy (eager) for anything unimplemented — so onp.mean(a), onp.sin(a),
# onp.concatenate([a, b]) all work on mx.np.ndarray.
def _np_dispatch_lookup(name):
    fn = globals().get(name)
    if callable(fn):
        return fn
    return None


def _to_host(v):
    if isinstance(v, NDArray):
        return v.asnumpy()
    if isinstance(v, (list, tuple)):
        return type(v)(_to_host(x) for x in v)
    return v


def _ndarray_array_function(self, func, types, args, kwargs):
    ours = _np_dispatch_lookup(func.__name__)
    if ours is not None:
        try:
            return ours(*args, **kwargs)
        except TypeError:
            pass  # signature mismatch → host fallback below
    # numpy_op_fallback.py idiom: run official numpy on host copies
    res = func(*_to_host(args), **{k: _to_host(v) for k, v in kwargs.items()})
    if isinstance(res, onp.ndarray):
        return ndarray(jnp.asarray(res))
    return res


def _ndarray_array_ufunc(self, ufunc, method, *inputs, **kwargs):
    if method != "__call__":
        return NotImplemented
    out = kwargs.pop("out", None)
    ours = _np_dispatch_lookup(ufunc.__name__)
    if ours is not None and not kwargs:
        try:
            res = ours(*inputs)
            return _ufunc_apply_out(res, out)
        except TypeError:
            pass
    # Host fallback: forward remaining kwargs (where=, casting=, ...) to
    # official numpy instead of silently dropping them.  out= must ride
    # along as real host buffers seeded with the targets' current values so
    # where=False positions keep their prior contents (numpy's contract),
    # and so numpy itself enforces its output-casting rules.
    host_kwargs = {k: _to_host(v) for k, v in kwargs.items()}
    host_out = None
    if out is not None:
        targets = out if isinstance(out, tuple) else (out,)
        host_out = tuple(
            t.asnumpy().copy() if isinstance(t, ndarray) else t
            for t in targets)
        host_kwargs["out"] = host_out if isinstance(out, tuple) \
            else host_out[0]
    res = getattr(onp, ufunc.__name__)(*_to_host(inputs), **host_kwargs)
    if isinstance(res, onp.ndarray):
        res = ndarray(jnp.asarray(res))
    elif isinstance(res, tuple):
        res = tuple(ndarray(jnp.asarray(r)) if isinstance(r, onp.ndarray)
                    else r for r in res)
    return _ufunc_apply_out(res, out, checked=host_out is not None)


def _ufunc_apply_out(res, out, checked=False):
    """Honor ufunc out= by writing into the target mx ndarray(s) in place
    (functional update underneath) and returning the target, matching
    numpy's aliasing contract as closely as an immutable backend can.
    ``checked`` means official numpy already ran with out= host buffers
    and enforced its casting rules; otherwise the same_kind output-casting
    rule is enforced here (numpy raises on e.g. float->int out)."""
    if out is None:
        return res
    targets = out if isinstance(out, tuple) else (out,)
    results = res if isinstance(res, tuple) else (res,)
    if len(targets) != len(results):
        raise ValueError("out= arity mismatch")
    written = []
    for t, r in zip(targets, results):
        if t is None:  # numpy allows None = "allocate this output"
            written.append(r)
            continue
        if not isinstance(t, ndarray):
            raise TypeError("out= target must be an mx np ndarray, got %r"
                            % type(t))
        r_j = r._data if isinstance(r, ndarray) else jnp.asarray(r)
        if r_j.dtype != t.dtype:
            if not checked and not onp.can_cast(r_j.dtype, t.dtype,
                                                casting="same_kind"):
                raise TypeError(
                    "Cannot cast ufunc output from %s to %s with casting "
                    "rule 'same_kind'" % (r_j.dtype, t.dtype))
            r_j = r_j.astype(t.dtype)
        t._data = r_j
        written.append(t)
    # numpy normalizes out= to a tuple; hand back a bare array for the
    # single-output case (what nout==1 ufuncs expect)
    return written[0] if len(written) == 1 else tuple(written)


def _ndarray_array(self, dtype=None, copy=None):
    a = self.asnumpy()
    return a.astype(dtype) if dtype is not None else a


ndarray.__array_function__ = _ndarray_array_function
ndarray.__array_ufunc__ = _ndarray_array_ufunc
ndarray.__array__ = _ndarray_array


# -------------------------------------------------- long-tail surface
# (ref numpy/fallback.py category — here mostly device-native; see module
# docstring in fallback.py). Imported last: fallback wraps the ndarray
# class defined above.
from . import fallback  # noqa: E402
from .fallback import *  # noqa: F401,F403,E402

__all__ += fallback.__all__
# names long defined above but historically missing from __all__
__all__ += ["bool_", "e", "float32", "float64", "inf", "int32", "int64",
            "nan", "newaxis", "pi", "uint8"]
__all__ = list(dict.fromkeys(__all__))
