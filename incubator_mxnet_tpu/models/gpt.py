"""Decoder-only (GPT-style) causal language model — the long-context model
family (SURVEY §5 long-context/SP; no GPT in the reference's zoo, this is
the TPU-era completion of its LM lineup alongside models/lstm_lm.py).

TPU-first choices:
- causal flash attention (ops/attention.py Pallas kernels) by default — the
  O(S) memory path that makes S >= 8k trainable on one chip;
- ring attention over an ``sp`` mesh axis for sequences beyond one chip
  (attention='ring');
- pre-norm blocks + weight-tied LM head (matmul-dominated, MXU-friendly);
- learned positions (static shapes; no data-dependent control flow).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from .. import ndarray as nd
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray import _apply
from .bert import MultiHeadAttention
from .lm_head import ChunkedHeadLossBase

__all__ = ["GPTModel", "TransformerDecoderLayer"]


class TransformerDecoderLayer(HybridBlock):
    """Pre-norm decoder block: x + attn(ln(x)); x + ffn(ln(x))."""

    def __init__(self, units, hidden_size, num_heads, attention="flash",
                 tp_axis=None, sp_axis="sp", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.attn = MultiHeadAttention(units, num_heads,
                                           attention=attention, causal=True,
                                           sp_axis=sp_axis, tp_axis=tp_axis)
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.fc1 = nn.Dense(hidden_size, flatten=False, in_units=units,
                                activation=None)
            self.fc2 = nn.Dense(units, flatten=False, in_units=hidden_size)
        if tp_axis:
            self.fc1.weight.sharding = P(tp_axis, None)
            self.fc1.bias.sharding = P(tp_axis)
            self.fc2.weight.sharding = P(None, tp_axis)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        h = nd.LeakyReLU(self.fc1(self.ln2(x)), act_type="gelu")
        return x + self.fc2(h)


class GPTModel(HybridBlock):
    """Decoder-only LM: tokens (B, S) int -> logits (B, S, vocab).

    The LM head is weight-tied to the token embedding (ref-era LM practice;
    one (V, U) matrix serves both gather and projection — XLA reuses it on
    the MXU without a transposed copy).
    """

    def __init__(self, vocab_size=32768, units=768, hidden_size=None,
                 num_layers=12, num_heads=12, max_length=2048,
                 attention="flash", tp_axis=None, sp_axis="sp", **kwargs):
        super().__init__(**kwargs)
        hidden_size = hidden_size or 4 * units
        self._max_length = max_length
        with self.name_scope():
            self.tok_embed = nn.Embedding(vocab_size, units)
            self.pos_embed = nn.Embedding(max_length, units)
            self.layers = nn.HybridSequential()
            for _ in range(num_layers):
                self.layers.add(TransformerDecoderLayer(
                    units, hidden_size, num_heads, attention=attention,
                    tp_axis=tp_axis, sp_axis=sp_axis))
            self.ln_f = nn.LayerNorm(in_channels=units)

    def features(self, token_ids):
        """Trunk output (B, S, U) — the pre-head activations (pair with
        ChunkedLMLoss to avoid materializing (B*S, V) logits)."""
        B, S = token_ids.shape
        if S > self._max_length:
            raise ValueError(
                "sequence length %d exceeds max_length %d (position table); "
                "construct GPTModel(max_length=...) large enough" %
                (S, self._max_length))
        pos = nd.arange(S, dtype="int32").reshape((1, S))
        h = self.tok_embed(token_ids) + self.pos_embed(pos)
        h = self.layers(h)
        return self.ln_f(h)

    def forward(self, token_ids):
        h = self.features(token_ids)
        # weight-tied head: logits = h @ E^T
        return _apply(lambda hd, e: hd @ e.T.astype(hd.dtype), h,
                      self.tok_embed.weight.data())


class ChunkedLMLoss(ChunkedHeadLossBase):
    """Loss head that fuses the (weight-tied) LM projection with a CHUNKED
    softmax-CE (ops/lm_ce.py): the full (T, V) logits never materialize —
    the vocab-CE HBM lever identified in docs/PERF_BERT.md. Use with the
    model's ``features`` output:

        gpt = GPTModel(...)
        loss_fn = ChunkedLMLoss(gpt)          # chunk=None auto-routes
        step = jit.TrainStep(FeaturesView(gpt), loss_fn, trainer)

    Gradients flow into the tied embedding through ``weight.data()`` the
    same way they do for any parameter the traced step reads."""

    def _head_params(self):
        return self._model.tok_embed.weight.data(), None


class FeaturesView(HybridBlock):
    """Expose a model's ``features`` as its forward (so TrainStep's
    net(*inputs) -> loss_fn(out, y) contract pairs the trunk with a fused
    loss head like ChunkedLMLoss). Shares the wrapped model's params;
    variadic so multi-input features (BERT's token_types/mask) pass
    through."""

    def __init__(self, model, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.model = model

    def forward(self, *args):
        return self.model.features(*args)
