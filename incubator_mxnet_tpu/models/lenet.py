"""LeNet-5 (ref example/gluon/mnist/mnist.py — BASELINE config 1)."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock


class LeNet(HybridBlock):
    def __init__(self, classes=10, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(
                nn.Conv2D(20, kernel_size=5, activation="relu"),
                nn.MaxPool2D(pool_size=2, strides=2),
                nn.Conv2D(50, kernel_size=5, activation="relu"),
                nn.MaxPool2D(pool_size=2, strides=2),
                nn.Flatten(),
                nn.Dense(500, activation="relu"),
            )
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))
