"""Shared chunked LM loss-head base (ops/lm_ce.py wiring).

One forward for every vocab-projection head: subclasses provide
``_head_params() -> (weight (V, U), bias (V,) or None)`` — GPT's tied
embedding (models.gpt.ChunkedLMLoss), BERT's untied biased mlm_decoder
(models.bert.ChunkedMLMLoss). Lives in its own module so gpt.py and
bert.py (which import from each other's layer stacks) can both subclass
without a cycle."""
from __future__ import annotations

from ..ndarray import _apply

__all__ = ["ChunkedHeadLossBase"]


class ChunkedHeadLossBase:
    """Loss head fusing a (V, U) vocab projection with the CHUNKED
    softmax-CE (ops/lm_ce.py): the full (T, V) logits never materialize —
    the vocab-CE HBM lever measured in docs/PERF_BERT.md. Pair with
    ``FeaturesView(model)`` so TrainStep feeds the trunk activations."""

    def __init__(self, model, chunk=None):
        # chunk=None auto-routes (ops/lm_ce.py): dense below ~128 MB of
        # logits, ~32 MB chunks above — default-on for long-T/large-V
        self._model = model
        self._chunk = chunk

    def _head_params(self):
        raise NotImplementedError

    def forward(self, hidden, labels):
        from ..ops.lm_ce import chunked_lm_cross_entropy
        w, b = self._head_params()

        def fn(h, w, y, b=None):
            losses = chunked_lm_cross_entropy(h, w, y, self._chunk,
                                              head_b=b)
            # gluon loss contract: per-sample mean over non-batch axes
            return losses.reshape(losses.shape[0], -1).mean(axis=1)

        if b is None:
            return _apply(fn, hidden, w, labels)
        return _apply(lambda h, w, b, y: fn(h, w, y, b), hidden, w, b,
                      labels)

    __call__ = forward
