"""SSD object detector (ref example/ssd — BASELINE config 4).

Multi-scale conv heads over a downsampling backbone; anchors/targets/NMS via
ops.multibox (contrib MultiBox* op parity)."""
from __future__ import annotations

from .. import ndarray as nd
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ops.multibox import MultiBoxPrior


def _down_sample(channels):
    blk = nn.HybridSequential()
    for _ in range(2):
        blk.add(nn.Conv2D(channels, 3, padding=1, use_bias=False))
        blk.add(nn.BatchNorm())
        blk.add(nn.Activation("relu"))
    blk.add(nn.MaxPool2D(2, 2))
    return blk


class SSD(HybridBlock):
    """Compact SSD: backbone + 4 detection scales.

    sizes/ratios follow the example/ssd defaults (per-scale anchors)."""

    def __init__(self, num_classes=20, base_channels=64,
                 sizes=((0.2, 0.272), (0.37, 0.447), (0.54, 0.619), (0.71, 0.79)),
                 ratios=((1, 2, 0.5),) * 4, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.sizes = sizes
        self.ratios = ratios
        self.num_anchors = [len(s) + len(r) - 1 for s, r in zip(sizes, ratios)]
        with self.name_scope():
            self.backbone = nn.HybridSequential(prefix="backbone_")
            for ch in (base_channels, base_channels * 2):
                self.backbone.add(_down_sample(ch))
            self.stages, self.cls_heads, self.loc_heads = [], [], []
            for i in range(4):
                if i > 0:
                    stage = _down_sample(base_channels * 2)
                    self.register_child(stage, "stage%d" % i)
                    self.stages.append(stage)
                cls_head = nn.Conv2D(self.num_anchors[i] * (num_classes + 1), 3,
                                     padding=1)
                loc_head = nn.Conv2D(self.num_anchors[i] * 4, 3, padding=1)
                self.register_child(cls_head, "cls%d" % i)
                self.register_child(loc_head, "loc%d" % i)
                self.cls_heads.append(cls_head)
                self.loc_heads.append(loc_head)

    def forward(self, x):
        """Returns (anchors (1,A,4), cls_preds (N, num_cls+1, A), loc_preds (N, A*4))."""
        feat = self.backbone(x)
        anchors, cls_outs, loc_outs = [], [], []
        for i in range(4):
            if i > 0:
                feat = self.stages[i - 1](feat)
            anchors.append(MultiBoxPrior(feat, sizes=self.sizes[i],
                                         ratios=self.ratios[i]))
            c = self.cls_heads[i](feat)          # (N, A_i*(C+1), H, W)
            l = self.loc_heads[i](feat)
            N = c.shape[0]
            cls_outs.append(c.transpose((0, 2, 3, 1)).reshape(
                (N, -1, self.num_classes + 1)))
            loc_outs.append(l.transpose((0, 2, 3, 1)).reshape((N, -1)))
        anchors = nd.concat(*anchors, dim=1)
        cls_preds = nd.concat(*cls_outs, dim=1).transpose((0, 2, 1))
        loc_preds = nd.concat(*loc_outs, dim=1)
        return anchors, cls_preds, loc_preds
