"""BERT / Transformer encoder (BASELINE config 3 — GluonNLP BERT-base analog).

TPU-native design points:
- MXU-friendly: all projections are batched matmuls; bf16-ready (cast()).
- Tensor parallelism: ``tp_axis`` shards attention heads and FFN hidden over
  the mesh (Megatron pattern via GSPMD sharding annotations on the params).
- Sequence parallelism: ``attention='ring'`` computes attention with the
  ring-attention kernel over the ``sp`` mesh axis (parallel/ring_attention.py)
  — the long-context capability absent in the reference (SURVEY §5).
"""
from __future__ import annotations

import math

from jax.sharding import PartitionSpec as P

from .. import ndarray as nd
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray import NDArray, _apply
from .lm_head import ChunkedHeadLossBase


class MultiHeadAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.0, attention="dense",
                 sp_axis="sp", tp_axis=None, causal=False, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self._dropout = dropout
        self._attention = attention
        self._sp_axis = sp_axis
        self._causal = causal
        with self.name_scope():
            self.query = nn.Dense(units, flatten=False, in_units=units)
            self.key = nn.Dense(units, flatten=False, in_units=units)
            self.value = nn.Dense(units, flatten=False, in_units=units)
            self.proj = nn.Dense(units, flatten=False, in_units=units)
        if tp_axis:
            # shard heads over tp: qkv col-parallel, out proj row-parallel
            for lyr in (self.query, self.key, self.value):
                lyr.weight.sharding = P(tp_axis, None)
                lyr.bias.sharding = P(tp_axis)
            self.proj.weight.sharding = P(None, tp_axis)

    def forward(self, x, mask=None):
        B, S, U = x.shape
        H = self._num_heads
        D = U // H
        q = self.query(x).reshape((B, S, H, D)).transpose((0, 2, 1, 3))
        k = self.key(x).reshape((B, S, H, D)).transpose((0, 2, 1, 3))
        v = self.value(x).reshape((B, S, H, D)).transpose((0, 2, 1, 3))

        causal = self._causal
        if self._attention == "ring":
            from ..parallel.ring_attention import ring_attention
            from ..parallel.mesh import current_mesh
            mesh = current_mesh()
            out = _apply(lambda qd, kd, vd: ring_attention(
                qd, kd, vd, mesh=mesh, axis=self._sp_axis, causal=causal),
                q, k, v)
        elif self._attention == "ulysses":
            from ..parallel.ulysses import ulysses_attention
            from ..parallel.mesh import current_mesh
            mesh = current_mesh()
            out = _apply(lambda qd, kd, vd: ulysses_attention(
                qd, kd, vd, mesh=mesh, axis=self._sp_axis, causal=causal),
                q, k, v)
        elif self._attention == "flash":
            from ..ops.attention import flash_attention
            out = _apply(lambda qd, kd, vd: flash_attention(qd, kd, vd, causal),
                         q, k, v)
        else:
            scale = 1.0 / math.sqrt(D)
            scores = nd.batch_dot(q.reshape((B * H, S, D)),
                                  k.reshape((B * H, S, D)), transpose_b=True) * scale
            if causal:
                def causal_mask(sc):
                    import jax.numpy as jnp
                    qi = jnp.arange(S)[:, None]
                    ki = jnp.arange(S)[None, :]
                    return jnp.where(qi >= ki, sc, -1e9)
                scores = _apply(causal_mask, scores)
            if mask is not None:
                scores = scores.reshape((B, H, S, S)) + (1.0 - mask) * -1e9
                scores = scores.reshape((B * H, S, S))
            attn = nd.softmax(scores, axis=-1)
            if self._dropout:
                attn = nd.Dropout(attn, p=self._dropout)
            out = nd.batch_dot(attn, v.reshape((B * H, S, D))).reshape((B, H, S, D))
        out = out.transpose((0, 2, 1, 3)).reshape((B, S, U))
        return self.proj(out)


class TransformerEncoderLayer(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.1,
                 attention="dense", tp_axis=None, sp_axis="sp", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention_cell = MultiHeadAttention(units, num_heads, dropout,
                                                     attention, sp_axis, tp_axis)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ffn1 = nn.Dense(hidden_size, flatten=False, in_units=units)
            self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden_size)
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.dropout_layer = nn.Dropout(dropout) if dropout else None
        if tp_axis:
            self.ffn1.weight.sharding = P(tp_axis, None)
            self.ffn1.bias.sharding = P(tp_axis)
            self.ffn2.weight.sharding = P(None, tp_axis)

    def forward(self, x, mask=None):
        h = self.attention_cell(x, mask)
        if self.dropout_layer:
            h = self.dropout_layer(h)
        x = self.ln1(x + h)
        h = self.ffn2(nd.LeakyReLU(self.ffn1(x), act_type="gelu"))
        if self.dropout_layer:
            h = self.dropout_layer(h)
        return self.ln2(x + h)


class BERTEncoder(HybridBlock):
    """ref GluonNLP bert.BERTEncoder (structure parity)."""

    def __init__(self, units=768, hidden_size=3072, num_layers=12, num_heads=12,
                 max_length=512, dropout=0.1, attention="dense", tp_axis=None,
                 sp_axis="sp", **kwargs):
        super().__init__(**kwargs)
        self._max_length = max_length
        self._units = units
        with self.name_scope():
            self.position_weight = self.params.get("position_weight",
                                                   shape=(max_length, units),
                                                   init="normal")
            self.layers = []
            for i in range(num_layers):
                layer = TransformerEncoderLayer(units, hidden_size, num_heads,
                                                dropout, attention, tp_axis, sp_axis)
                self.register_child(layer, "layer%d" % i)
                self.layers.append(layer)

    def forward(self, x, mask=None):
        S = x.shape[1]
        pos = nd.slice_axis(self.position_weight.data(), 0, 0, S)
        x = x + pos.expand_dims(0)
        for layer in self.layers:
            x = layer(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT with embeddings + MLM head (pretraining objective)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072, num_layers=12,
                 num_heads=12, max_length=512, dropout=0.1, attention="dense",
                 tp_axis=None, sp_axis="sp", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units)
            self.token_type_embed = nn.Embedding(2, units)
            self.embed_ln = nn.LayerNorm(in_channels=units)
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(units, hidden_size, num_layers, num_heads,
                                       max_length, dropout, attention, tp_axis,
                                       sp_axis)
            self.mlm_dense = nn.Dense(units, flatten=False, activation="relu",
                                      in_units=units)
            self.mlm_ln = nn.LayerNorm(in_channels=units)
            self.mlm_decoder = nn.Dense(vocab_size, flatten=False, in_units=units)

    def forward(self, token_ids, token_types=None, mask=None):
        mlm = self.mlm_decoder(self.features(token_ids, token_types, mask))
        return mlm

    def features(self, token_ids, token_types=None, mask=None):
        """Pre-decoder MLM activations (B, S, U) — pair with
        ``ChunkedMLMLoss`` so the (B*S, V) logits never materialize (the
        vocab-CE HBM lever, docs/PERF_BERT.md)."""
        x = self.word_embed(token_ids)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = self.embed_ln(x)
        if self.embed_dropout:
            x = self.embed_dropout(x)
        h = self.encoder(x, mask)
        return self.mlm_ln(self.mlm_dense(h))


class ChunkedMLMLoss(ChunkedHeadLossBase):
    """BERT counterpart of models.gpt.ChunkedLMLoss — same chunked
    softmax-CE forward, but the head is the UNTIED, BIASED mlm_decoder.
    Use with ``FeaturesView(bert)`` (variadic: token_types/mask pass
    through to ``features``):

        bert = BERTModel(...)
        step = jit.TrainStep(FeaturesView(bert), ChunkedMLMLoss(bert), tr)
    """

    def _head_params(self):
        return (self._model.mlm_decoder.weight.data(),
                self._model.mlm_decoder.bias.data())
