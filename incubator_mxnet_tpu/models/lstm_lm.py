"""LSTM language model (ref example/rnn/word_lm — BASELINE config 5).

The fused gluon.rnn.LSTM lowers to lax.scan (the cuDNN RNN analog)."""
from __future__ import annotations

from ..gluon import nn, rnn
from ..gluon.block import HybridBlock


class LSTMLanguageModel(HybridBlock):
    def __init__(self, vocab_size=10000, embed_size=650, hidden_size=650,
                 num_layers=2, dropout=0.5, tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.embedding = nn.Embedding(vocab_size, embed_size)
            self.lstm = rnn.LSTM(hidden_size, num_layers=num_layers,
                                 dropout=dropout, input_size=embed_size)
            self.decoder = nn.Dense(vocab_size, flatten=False, in_units=hidden_size)

    def begin_state(self, batch_size):
        return self.lstm.begin_state(batch_size)

    def forward(self, inputs, states=None):
        """inputs: (T, N) int token ids → logits (T, N, V)."""
        emb = self.drop(self.embedding(inputs))
        if states is None:
            out = self.lstm(emb)
            out = self.drop(out)
            return self.decoder(out)
        out, states = self.lstm(emb, states)
        out = self.drop(out)
        return self.decoder(out), states
