"""Model families covering the BASELINE.json configs:

- lenet:      LeNet MNIST (config 1)
- resnet etc: via gluon.model_zoo.vision (config 2)
- bert:       BERT-base pretraining w/ TP + ring-attention SP (config 3)
- ssd:        SSD object detection w/ MultiBox ops (config 4)
- lstm_lm:    LSTM language model (config 5)
"""
from .lenet import LeNet  # noqa
from .bert import (BERTEncoder, BERTModel, TransformerEncoderLayer,  # noqa
                   MultiHeadAttention, ChunkedMLMLoss)
from .gpt import (GPTModel, TransformerDecoderLayer, ChunkedLMLoss,  # noqa
                  FeaturesView)
from .lstm_lm import LSTMLanguageModel  # noqa
from .ssd import SSD  # noqa
from ..gluon.model_zoo.vision import get_model  # noqa
