"""Device context — TPU-first re-design of MXNet's Context.

Reference parity: include/mxnet/base.h:102-128 (DeviceType kCPU/kGPU/...),
base.h:422-434 (Context::GPU()/CPU()), python/mxnet/context.py.

TPU-native design: ``tpu()`` is the first-class accelerator context. A Context
maps onto a ``jax.Device``; placement is realised with ``jax.device_put``
rather than per-device CUDA streams — XLA/PJRT owns streams and ordering.
``gpu()`` is accepted as a migration alias for the accelerator so existing
MXNet scripts run unchanged.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_tpus", "num_gpus"]

_DEVTYPE2ID = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
_ID2DEVTYPE = {v: k for k, v in _DEVTYPE2ID.items()}


def _accelerator_platform():
    """Best non-CPU platform available to JAX, else 'cpu'."""
    try:
        # local: under jax.distributed a context must resolve to a device
        # THIS process can address, never a peer's
        platforms = {d.platform for d in jax.local_devices()}
    except RuntimeError:
        return "cpu"
    for p in ("tpu", "axon", "gpu", "cuda", "rocm"):
        if p in platforms:
            return p
    return next(iter(platforms), "cpu")


def _local_devices(platform=None):
    """This process's addressable devices for a platform (multi-host safe)."""
    if platform is None:
        return jax.local_devices()
    return [d for d in jax.local_devices() if d.platform == platform] or \
        jax.devices(platform)


class Context:
    """A device context. Compare mxnet.context.Context.

    Parameters
    ----------
    device_type : {'cpu', 'tpu', 'gpu', 'cpu_pinned'}
        'tpu' is the native accelerator; 'gpu' aliases it when no GPU
        platform exists (migration compatibility).
    device_id : int
    """

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in _DEVTYPE2ID:
            raise ValueError("unknown device_type %r" % (device_type,))
        self.device_type = device_type
        self.device_id = device_id
        self._old_ctx = None

    @property
    def device_typeid(self):
        return _DEVTYPE2ID[self.device_type]

    # -- jax mapping ---------------------------------------------------
    @property
    def jax_device(self):
        """The jax.Device this context denotes."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                devs = _local_devices("cpu")
                return devs[self.device_id % len(devs)]
            except RuntimeError:
                # single-platform TPU-only runtime: fall back to default device
                return jax.local_devices()[0]
        plat = _accelerator_platform()
        if plat == "cpu":
            # no accelerator present (unit tests on CPU): map onto cpu devices
            devs = _local_devices("cpu")
            return devs[self.device_id % len(devs)]
        devs = _local_devices(plat)
        return devs[self.device_id % len(devs)]

    # -- scope ---------------------------------------------------------
    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, *args):
        Context._default_ctx.value = self._old_ctx

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def empty_cache(self):
        """Release cached device memory (ref: MXNet Context.empty_cache).

        XLA/PJRT owns the allocator; deleting unreferenced buffers is what
        frees memory, so this only triggers a GC-style sync point.
        """
        import gc

        gc.collect()


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def tpu(device_id=0):
    """First-class TPU context (the north-star device)."""
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Migration alias: on a TPU-only system this resolves to tpu(device_id)."""
    return Context("gpu", device_id)


def num_tpus():
    plat = _accelerator_platform()
    if plat == "cpu":
        return 0
    return len(jax.devices(plat))


def num_gpus():
    # Migration shim: report accelerators so ``if mx.num_gpus():`` scripts work.
    return num_tpus()


def current_context():
    if not hasattr(Context._default_ctx, "value"):
        # default to the accelerator when one exists — TPU-first
        Context._default_ctx.value = tpu(0) if num_tpus() > 0 else cpu(0)
    return Context._default_ctx.value
