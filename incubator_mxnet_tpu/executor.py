"""Executor — bound symbolic graph (ref include/mxnet/executor.h:53,
src/executor/graph_executor.cc).

TPU-native: ``bind`` materialises arg arrays (auto-creating deferred-shape
parameter variables), and Forward/Backward run the traced DAG through the
SAME compiled-step machinery as the imperative path. The NNVM pass pipeline
(fusion/memory planning/inplace) is XLA's job.
"""
from __future__ import annotations

import numpy as onp

from . import autograd
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, inputs_need_grad=True):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_dict = dict(args or {})
        self.grad_req = grad_req
        self._compiled = None
        self.outputs = []
        self._label_names = set()
        self._materialize()
        if args_grad is None and grad_req != "null":
            # ref simple_bind: grad buffers for all args incl. data inputs
            # (input grads work) unless inputs_need_grad=False (Module's
            # default — saves a batch-sized buffer + per-step write); label
            # vars always excluded (loss layers produce no label cotangent)
            labels = {v.name for v in self._walk_vars()
                      if getattr(v, "_is_label", False)
                      or v.name.endswith("_label")}
            skip = labels if inputs_need_grad else                 labels | self._data_names()
            args_grad = {k: nd.zeros(v.shape, dtype=v.dtype)
                         for k, v in self.arg_dict.items()
                         if k not in skip}
        self.grad_dict = dict(args_grad or {})
        self.aux_dict = {k: self.arg_dict[k] for k in self._aux_names}

    # -----------------------------------------------------------------
    def _walk_vars(self):
        seen, out = set(), []

        def visit(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                visit(i)
            if s.is_var:
                out.append(s)
        if hasattr(self._symbol, "_symbols"):
            for s in self._symbol._symbols:
                visit(s)
        else:
            visit(self._symbol)
        return out

    def _data_names(self):
        return {v.name for v in self._walk_vars()
                if not getattr(v, "_is_param", False)}

    def _materialize(self):
        """Create missing param arrays using deferred shape rules, by one
        incremental topo-order evaluation — the analog of GraphExecutor shape
        inference + InitDataEntryMemory (graph_executor.cc:831,1062)."""
        self._aux_names = []
        cache = {}

        def ev(s):
            key = (id(s), s._output_index)
            base_key = (id(s), None)
            if key in cache:
                return cache[key]
            if s.is_var:
                if s.name not in self.arg_dict:
                    # name-suffix heuristic covers JSON-reloaded graphs whose
                    # vars lost the _is_label attr (same rule as param_names)
                    if getattr(s, "_is_label", False) or \
                            s.name.endswith("_label"):
                        # inference binds (for_training=False) omit label
                        # shapes: default to zeros of (batch,) — loss-layer
                        # forwards ignore labels outside training
                        batch = next(a.shape[0]
                                     for a in self.arg_dict.values())
                        self.arg_dict[s.name] = nd.zeros((batch,))
                    else:
                        raise ValueError("unbound variable %r" % s.name)
                cache[key] = self.arg_dict[s.name]
                return cache[key]
            if base_key not in cache:
                args = []
                deferred = []
                for j, i in enumerate(s._inputs):
                    if (i.is_var and i.name not in self.arg_dict
                            and getattr(i, "_deferred_shape_fn", None)):
                        args.append(None)
                        deferred.append((j, i))
                    else:
                        args.append(ev(i))
                if deferred:
                    data_input = next(a for a in args if isinstance(a, NDArray))
                    for j, i in deferred:
                        shape = i._deferred_shape_fn(data_input.shape)
                        arr = nd.zeros(shape)
                        self.arg_dict[i.name] = arr
                        if getattr(i, "_is_aux", False):
                            self._aux_names.append(i.name)
                        args[j] = arr
                with autograd.pause():
                    cache[base_key] = s._op(*args, **s._kwargs)
            full = cache[base_key]
            out = full[s._output_index] if s._output_index is not None else full
            cache[key] = out
            return out

        roots = self._symbol._symbols if hasattr(self._symbol, "_symbols") \
            else [self._symbol]
        outs = [ev(r) for r in roots]
        # outputs (zero-valued) are live right after bind — output_shapes
        # works before the first forward (ref GraphExecutor behavior)
        flat = []
        for o in outs:
            flat.extend(o if isinstance(o, (list, tuple)) else [o])
        self.outputs = flat

    def _topo_nodes(self):
        seen, order = set(), []

        def visit(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                visit(i)
            order.append(s)
        if hasattr(self._symbol, "_symbols"):
            for s in self._symbol._symbols:
                visit(s)
        else:
            visit(self._symbol)
        return order

    # -----------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """ref executor.h Forward."""
        for k, v in kwargs.items():
            if not isinstance(v, NDArray):
                v = nd.array(v)
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data
            else:
                self.arg_dict[k] = v
        scope = autograd.record(train_mode=True) if is_train else autograd.pause(
            train_mode=False)
        if is_train:
            # mark params for grad
            for k, g in self.grad_dict.items():
                if k in self.arg_dict:
                    autograd.mark_variables([self.arg_dict[k]], [g],
                                            self.grad_req)
        with scope:
            out = self._symbol.eval_imperative(dict(self.arg_dict))
        self.outputs = out if isinstance(out, (list, tuple)) else [out]
        self.outputs = list(self.outputs)
        return self.outputs

    def backward(self, out_grads=None):
        """ref executor.h Backward."""
        heads = self.outputs
        if out_grads is not None and not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]
        autograd.backward(heads, out_grads)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v.astype(self.arg_dict[k].dtype)._data
            elif not allow_extra_params:
                raise ValueError("unknown arg %r" % k)
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._data = v.astype(self.aux_dict[k].dtype)._data
            elif not allow_extra_params:
                raise ValueError("unknown aux %r" % k)
