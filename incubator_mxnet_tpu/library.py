"""Custom-op library loading (ref python/mxnet/library.py MXLoadLib).

TPU-native: an "op library" is a python module registering ops into the nd /
sym namespaces (pure-JAX or Pallas implementations) — the dlopen'd C++ .so of
the reference maps to importable plugin modules (native C extensions welcome).
"""
from __future__ import annotations

import importlib.util
import os

from . import ndarray as nd

__all__ = ["load", "register_op", "unregister_op"]

_REGISTERED_OPS = {}   # name -> {module: shadowed attr or _ABSENT}; only
                       # these names may be unregistered (guards builtins)
_ABSENT = object()


def register_op(name, fn, gradient=None):
    """Register a custom operator into nd (and sym mirrors).

    fn operates on NDArrays; gradient (optional) supplies a custom VJP.
    """
    if gradient is not None:
        import jax

        @jax.custom_vjp
        def raw(*datas):
            from .ndarray import NDArray
            outs = fn(*[nd.NDArray(d) for d in datas])
            return outs._data if isinstance(outs, nd.NDArray) else tuple(o._data for o in outs)

        def fwd(*datas):
            out = raw(*datas)
            return out, datas

        def bwd(datas, g):
            from .ndarray import NDArray
            grads = gradient([nd.NDArray(d) for d in datas],
                             nd.NDArray(g) if not isinstance(g, tuple) else
                             [nd.NDArray(x) for x in g])
            return tuple(x._data for x in grads)

        raw.defvjp(fwd, bwd)

        def op(*args, **kwargs):
            from .ndarray import _apply
            return _apply(raw, *args)
    else:
        def op(*args, **kwargs):
            return fn(*args, **kwargs)

    saved = _REGISTERED_OPS.setdefault(name, {})
    saved.setdefault("ndarray", getattr(nd, name, _ABSENT))
    setattr(nd, name, op)
    try:
        from . import symbol as sym_mod
        from .symbol import _symbolize
        saved.setdefault("symbol", getattr(sym_mod, name, _ABSENT))
        setattr(sym_mod, name, _symbolize(op, name))
    except Exception:
        pass
    return op


def unregister_op(name):
    """Remove a custom operator previously registered via
    :func:`register_op` from the nd and sym namespaces, restoring whatever
    the name bound before (so a plugin that shadowed a builtin gives it
    back). Only names that went through register_op are removable —
    builtins are refused. Lets tests and short-lived plugins leave the
    registry the way they found it."""
    if name not in _REGISTERED_OPS:
        raise ValueError(
            "'%s' was not registered via register_op (builtin ops cannot "
            "be unregistered)" % name)
    saved = _REGISTERED_OPS.pop(name)
    for mod_name, prev in saved.items():
        try:
            mod = importlib.import_module("." + mod_name, __package__)
        except Exception:
            continue
        if prev is _ABSENT:
            if hasattr(mod, name):
                delattr(mod, name)
        else:
            setattr(mod, name, prev)


def load(path, verbose=True):
    """Load a plugin: a .py module calling register_op at import
    (ref library.py load / MXLoadLib)."""
    if not os.path.exists(path):
        raise ValueError("library %s not found" % path)
    if path.endswith(".py"):
        spec = importlib.util.spec_from_file_location(
            os.path.basename(path)[:-3], path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    raise ValueError("unsupported library type %s (use a .py plugin module; "
                     "C extensions load via normal python import)" % path)
