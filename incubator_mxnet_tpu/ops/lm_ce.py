"""Chunked LM cross-entropy — the vocab-softmax HBM lever
(docs/PERF_BERT.md: the fp32 (T, V) logits block is ~4 GB at 32k x 32k and
its reduce fusions run at pure HBM bandwidth, ~15% of the BERT step).

``chunked_lm_cross_entropy(hidden, head_w, labels, chunk)`` computes
per-token CE WITHOUT materializing the full (T, V) logits: a lax.map over
token chunks does (chunk, U) @ (U, V) -> LSE + label-logit gather per
chunk, so at most (chunk, V) logits exist at a time — small enough for
XLA to keep the matmul output in VMEM feeding the reduction. Backward is
jax autodiff through the map (the chunk logits are recomputed, the
classic memory/compute trade).

Numerics: LSE in fp32 with max subtraction; identical to dense softmax-CE
within bf16 matmul tolerance (tests/test_lm_ce.py pins parity and grads).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["chunked_lm_cross_entropy"]


# Auto-routing thresholds (bytes of the fp32 (T, V) logits block):
# below DENSE_BYTES one chunk (= the dense path, no map overhead) is used;
# above it, chunks are sized so each (chunk, V) block is ~BLOCK_BYTES —
# measured peak-HBM A/B in docs/PERF_BERT.md "Chunked CE: measured".
_DENSE_BYTES = 128 * 1024 * 1024
_BLOCK_BYTES = 32 * 1024 * 1024


def chunked_lm_cross_entropy(hidden, head_w, labels, chunk=None,
                             head_b=None):
    """hidden: (..., U) activations; head_w: (V, U) (embedding-tied or
    untied head); optional head_b: (V,) bias (BERT-style MLM decoders);
    labels: (...,) int. Returns per-token CE losses shaped like labels.

    ``chunk=None`` (default) auto-routes: the dense path when the full
    fp32 (T, V) logits block is under ~128 MB (no map overhead), else
    chunks sized to ~32 MB logits blocks — the default-on form of the
    vocab-CE HBM lever. Token dims are flattened, chunked, and restored;
    when chunk does not divide T, the token stream is zero-PADDED up to
    the next chunk multiple and the pad losses discarded (a divisor
    fallback would collapse to tiny chunks for odd/prime T — e.g. T=8193
    at chunk 256 has largest divisor 3 — and a thousands-iteration map)."""
    shape = labels.shape
    U = hidden.shape[-1]
    h = hidden.reshape(-1, U)
    y = labels.reshape(-1).astype(jnp.int32)
    T = h.shape[0]
    V = head_w.shape[0]
    if chunk is None:
        if T * V * 4 <= _DENSE_BYTES:
            chunk = T
        else:
            chunk = max(1, _BLOCK_BYTES // (V * 4))
    chunk = min(chunk, T)
    pad = (-T) % chunk
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, U), h.dtype)])
        y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
    n = (T + pad) // chunk
    hc = h.reshape(n, chunk, U)
    yc = y.reshape(n, chunk)

    def one(args):
        hb, yb = args
        logits = (hb @ head_w.T.astype(hb.dtype)).astype(jnp.float32)
        if head_b is not None:
            logits = logits + head_b.astype(jnp.float32)
        m = jnp.max(logits, axis=-1, keepdims=True)
        lse = (m + jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1,
                                   keepdims=True)))[:, 0]
        lab = jnp.take_along_axis(logits, yb[:, None], axis=-1)[:, 0]
        return lse - lab

    if n == 1:
        # true dense path: no map, no checkpoint — a rematerializing
        # single-chunk map would re-run the full (T,U)@(U,V) head matmul
        # in the backward for zero memory benefit
        losses = one((hc[0], yc[0]))
    else:
        # checkpoint: WITHOUT it, grad-of-map stacks each chunk's softmax
        # residuals into an (n, chunk, V) buffer — full-logits-sized,
        # exactly what this op exists to avoid. With it, the backward
        # recomputes the chunk logits from the (chunk, U) inputs.
        losses = lax.map(jax.checkpoint(one), (hc, yc)).reshape(-1)
    if pad:
        losses = losses[:T]
    return losses.reshape(shape)
