"""Chunked LM cross-entropy — the vocab-softmax HBM lever
(docs/PERF_BERT.md: the fp32 (T, V) logits block is ~4 GB at 32k x 32k and
its reduce fusions run at pure HBM bandwidth, ~15% of the BERT step).

``chunked_lm_cross_entropy(hidden, head_w, labels, chunk)`` computes
per-token CE WITHOUT materializing the full (T, V) logits: a lax.map over
token chunks does (chunk, U) @ (U, V) -> LSE + label-logit gather per
chunk, so at most (chunk, V) logits exist at a time — small enough for
XLA to keep the matmul output in VMEM feeding the reduction. Backward is
jax autodiff through the map (the chunk logits are recomputed, the
classic memory/compute trade).

Numerics: LSE in fp32 with max subtraction; identical to dense softmax-CE
within bf16 matmul tolerance (tests/test_lm_ce.py pins parity and grads).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["chunked_lm_cross_entropy"]


def chunked_lm_cross_entropy(hidden, head_w, labels, chunk=512):
    """hidden: (..., U) activations; head_w: (V, U) (embedding-tied head);
    labels: (...,) int. Returns per-token CE losses shaped like labels.
    Token dims are flattened, chunked, and restored; when chunk does not
    divide T, the largest divisor of T that is <= chunk is used (never a
    silent full-T fallback — the op exists to bound the logits block)."""
    shape = labels.shape
    U = hidden.shape[-1]
    h = hidden.reshape(-1, U)
    y = labels.reshape(-1).astype(jnp.int32)
    T = h.shape[0]
    if T % chunk:
        chunk = next(c for c in range(min(chunk, T), 0, -1) if T % c == 0)
    n = T // chunk
    hc = h.reshape(n, chunk, U)
    yc = y.reshape(n, chunk)

    # checkpoint: WITHOUT it, grad-of-map stacks each chunk's softmax
    # residuals into an (n, chunk, V) buffer — full-logits-sized, exactly
    # what this op exists to avoid. With it, the backward recomputes the
    # chunk logits from the (chunk, U) inputs.
    @jax.checkpoint
    def one(args):
        hb, yb = args
        logits = (hb @ head_w.T.astype(hb.dtype)).astype(jnp.float32)
        m = jnp.max(logits, axis=-1, keepdims=True)
        lse = (m + jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1,
                                   keepdims=True)))[:, 0]
        lab = jnp.take_along_axis(logits, yb[:, None], axis=-1)[:, 0]
        return lse - lab

    losses = lax.map(one, (hc, yc))
    return losses.reshape(shape)
