"""Deformable convolution v1/v2 (ref src/operator/contrib/
deformable_convolution.cc and modulated_deformable_convolution.cc,
Dai et al. 2017 / Zhu et al. 2018).

TPU-native lowering: instead of the reference's im2col-with-offsets CUDA
kernel, the kernel taps are gathered with the shared bilinear-sampling
helper (ops/detection.py) — one gather per kernel position, a static
Python loop XLA unrolls — and the accumulation over (in-channel, tap)
becomes a single einsum that lands on the MXU. Autograd falls out of the
gather/einsum VJPs; no custom backward needed.
"""
from __future__ import annotations

import jax.numpy as jnp

from .detection import _bilinear_gather

__all__ = ["deformable_conv2d"]


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def deformable_conv2d(x, offset, weight, bias=None, kernel=(3, 3), stride=(1, 1),
                      pad=(0, 0), dilate=(1, 1), num_deformable_group=1,
                      mask=None):
    """x (N,C,H,W); offset (N, ndg*2*KH*KW, Ho, Wo) with per-tap (y, x)
    pairs; weight (Co, C, KH, KW); optional DCNv2 mask
    (N, ndg*KH*KW, Ho, Wo), already sigmoid-activated by the caller.
    Returns (N, Co, Ho, Wo). All raw jnp — callers wrap with _apply.
    """
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    ph, pw = _pair(pad)
    dh, dw = _pair(dilate)
    N, C, H, W = x.shape
    Co = weight.shape[0]
    K = kh * kw
    ndg = num_deformable_group
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    assert offset.shape[1] == ndg * 2 * K, (offset.shape, ndg, K)
    assert C % ndg == 0, "channels not divisible by num_deformable_group"

    # base sampling grid per output position and tap
    oy = jnp.arange(Ho) * sh - ph                            # (Ho,)
    ox = jnp.arange(Wo) * sw - pw
    off = offset.reshape(N, ndg, K, 2, Ho, Wo)
    cg = C // ndg
    taps = []   # K entries of (N, C, Ho, Wo)
    for i in range(kh):
        for j in range(kw):
            k = i * kw + j
            per_group = []
            for g in range(ndg):
                ys = oy[None, :, None] + i * dh + off[:, g, k, 0]   # (N,Ho,Wo)
                xs = ox[None, None, :] + j * dw + off[:, g, k, 1]
                sampled = _bilinear_gather(x[:, g * cg:(g + 1) * cg], ys, xs)
                if mask is not None:
                    m = mask.reshape(N, ndg, K, Ho, Wo)[:, g, k]
                    sampled = sampled * m[:, None]
                per_group.append(sampled)
            taps.append(per_group[0] if ndg == 1
                        else jnp.concatenate(per_group, axis=1))
    stacked = jnp.stack(taps, axis=2)                        # (N, C, K, Ho, Wo)
    w = weight.reshape(Co, C, K)
    out = jnp.einsum("nckhw,ock->nohw", stacked, w)          # MXU contraction
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out
