"""Fused (flash) attention Pallas kernel for TPU.

The hot exception to "let XLA fuse" (SURVEY §7 table): attention's softmax
forces an HBM round-trip of the (S, S) score matrix under plain XLA. This
kernel tiles Q against K/V blocks in VMEM with an online-softmax accumulator,
so scores never leave VMEM. Used by models.bert MultiHeadAttention
(attention='flash'); falls back to the XLA composite off-TPU or for odd
shapes. Custom VJP recomputes blockwise (flash-style backward).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "flash_attention_supported"]


def _blocked_reference(q, k, v, causal, scale):
    """XLA fallback with fp32 softmax (numerics match the kernel)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def flash_attention_supported(q_shape, block_q=128, block_k=128):
    B, H, S, D = q_shape
    try:
        import jax.experimental.pallas  # noqa
    except ImportError:
        return False
    plat = jax.devices()[0].platform
    if plat not in ("tpu", "axon"):
        return False
    return S % block_q == 0 and S % block_k == 0 and D % 128 == 0


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, seq_len, causal, scale):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale            # (block_q, D)
    block_q = q.shape[0]
    qi = pl.program_id(1) * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)

    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_kb = seq_len // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T                                  # (block_q, block_k)
        if causal:
            ki = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(qi >= ki, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        alpha = jnp.where(jnp.isnan(alpha), 0.0, alpha)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + p @ v_blk
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m, l, acc))
    o_ref[0, :, :] = (acc / jnp.maximum(l, 1e-37)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=128, block_k=128):
    """q,k,v: (B, H, S, D) → (B, H, S, D)."""
    return _fa_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def _fa_call(q, k, v, causal, scale, block_q, block_k):
    from jax.experimental import pallas as pl

    B, H, S, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    grid = (B * H, S // block_q)
    kernel = functools.partial(_fa_kernel, block_k=block_k, seq_len=S,
                               causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
    )(qf, kf, vf)
    return out.reshape(B, H, S, D)


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if flash_attention_supported(q.shape, block_q, block_k):
        out = _fa_call(q, k, v, causal, scale, block_q, block_k)
    else:
        out = _blocked_reference(q, k, v, causal, scale)
    return out, (q, k, v, out)


def _fa_bwd(causal, scale, block_q, block_k, res, do):
    """Flash backward via recomputation (standard FA2 formulation in XLA —
    the score matrix is rematerialised blockwise by XLA fusion here)."""
    q, k, v, o = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    dof = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
