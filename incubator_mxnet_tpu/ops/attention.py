"""Fused (flash) attention Pallas kernels for TPU — forward AND backward.

The hot exception to "let XLA fuse" (SURVEY §7 table): attention's softmax
forces an HBM round-trip of the (S, S) score matrix under plain XLA. The
forward kernel tiles Q against K/V blocks in VMEM with an online-softmax
accumulator and saves the per-row log-sum-exp (LSE); the backward kernels
recompute probabilities blockwise from the LSE (FlashAttention-2
formulation) and accumulate dQ/dK/dV across sequential grid steps, so the
(S, S) score matrix NEVER materializes in HBM in either direction and VMEM
use is O(block^2 + block*D) — long sequences fit.

Used by models.bert MultiHeadAttention (attention='flash'); falls back to
the XLA composite off-TPU or for odd shapes. Set MXTPU_FLASH_INTERPRET=1 to
run the kernels in Pallas interpret mode on CPU (tests).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "flash_attention_supported",
           "flash_attention_legal", "flash_attention_lse",
           "attention_with_lse"]


def _interpret():
    from ..config import get_env
    return get_env("MXTPU_FLASH_INTERPRET")


def _auto_block(S):
    """Largest MXU-friendly block dividing S — measured on v5e (r3 sweep,
    fwd+bwd causal, D=128): 1024 beats 512 by ~1.3x at S=8k..32k (13.0 vs
    20.1 ms at 8k; 77 vs 97 ms at 32k), and 512 beats 128 by 1.3-3.5x
    (fewer grid steps, better VMEM reuse). None when no candidate divides
    S — such shapes are NOT kernel-legal and take the XLA composite
    fallback."""
    for b in (1024, 512, 256, 128):
        if S % b == 0:
            return b
    return None


def _resolve_blocks(S, block_q, block_k):
    from ..config import get_env
    block_q = block_q or get_env("MXTPU_FLASH_BLOCK_Q") or None
    block_k = block_k or get_env("MXTPU_FLASH_BLOCK_K") or None
    return (block_q or _auto_block(S)), (block_k or _auto_block(S))


def _blocked_reference(q, k, v, causal, scale):
    """XLA fallback with fp32 softmax (numerics match the kernel)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def flash_attention_legal(q_shape, block_q=None, block_k=None):
    """Capability: the kernels can run this shape. D rides each BlockSpec as
    the FULL last dim (legal for any size when equal to the array dim);
    8-alignment keeps sublanes packed."""
    B, H, S, D = q_shape
    block_q, block_k = _resolve_blocks(S, block_q, block_k)
    if block_q is None or block_k is None:
        return False
    try:
        import jax.experimental.pallas  # noqa
    except ImportError:
        return False
    if not _interpret():
        plat = jax.devices()[0].platform
        if plat not in ("tpu", "axon"):
            return False
    return S % block_q == 0 and S % block_k == 0 and D % 8 == 0


def flash_attention_supported(q_shape, block_q=None, block_k=None):
    """Legality AND profitability: D=64-style narrow heads leave MXU lanes
    half-empty, so the kernel only engages once S is long enough that the
    composite's (S,S) materialization hits HBM pressure (v5e, H=16, 512
    blocks: parity at ~2k, 2x at 4k, >6x at 8k — and the composite's score
    memory scales with B*H*S^2, so real batches hit the cliff earlier).
    Set MXTPU_FLASH_FORCE=1 to override the heuristic (e.g. large B*H at
    moderate S nearing OOM); interpret mode ignores it so CI exercises
    every legal shape."""
    if not flash_attention_legal(q_shape, block_q, block_k):
        return False
    B, H, S, D = q_shape
    if D % 128 != 0 and S < 2048 and not _interpret():
        from ..config import get_env
        return get_env("MXTPU_FLASH_FORCE")
    return True


# --------------------------------------------------------------- forward
def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
               block_k, causal, scale):
    """One (batch*head, q-block, k-block) program: K/V are STREAMED by the
    grid — VMEM holds only (block_q + 2*block_k) x D tiles plus the online
    softmax carry (m/l/acc scratch, persisted across the sequential k-block
    steps), so sequence length is bounded by HBM, not VMEM (S=32k+ on one
    chip).  Writes the per-row LSE (m + log l) the backward kernels consume.
    """
    from jax.experimental import pallas as pl

    qb, kb = pl.program_id(1), pl.program_id(2)
    block_q = q_ref.shape[1]

    @pl.when(kb == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, -jnp.inf)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # K/V blocks fully above the diagonal contribute nothing in causal mode
    live = ((qb + 1) * block_q - 1 >= kb * block_k) if causal else (kb >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale        # (block_q, D)
        k_blk = k_ref[0].astype(jnp.float32)            # (block_k, D)
        v_blk = v_ref[0].astype(jnp.float32)
        s = q @ k_blk.T                                  # (block_q, block_k)
        if causal:
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            ki = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(qi >= ki, s, -jnp.inf)
        m, l, acc = m_s[...], l_s[...], acc_s[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        alpha = jnp.where(jnp.isnan(alpha), 0.0, alpha)
        m_s[...] = m_new
        l_s[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[...] = acc * alpha + p @ v_blk

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finish():
        l = jnp.maximum(l_s[...], 1e-37)
        o_ref[0, :, :] = (acc_s[...] / l).astype(o_ref.dtype)
        # rows with l=0 cannot occur (causal keeps the diagonal; dense
        # keeps all)
        lse_ref[0, 0, :] = (m_s[...] + jnp.log(l))[:, 0]


def _fa_call(q, k, v, causal, scale, block_q, block_k):
    """Returns (out (B,H,S,D), lse (B*H,S) fp32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    grid = (B * H, S // block_q, S // block_k)
    kernel = functools.partial(_fa_kernel, block_k=block_k, causal=causal,
                               scale=scale)
    if causal:
        # dead blocks above the diagonal: clamp the index map so the grid
        # step re-uses the resident block instead of DMA-ing one it will
        # never read (compute is skipped by pl.when in the kernel)
        def kv_idx(b, i, j):
            return (b, jnp.minimum(j, ((i + 1) * block_q - 1) // block_k), 0)
    else:
        def kv_idx(b, i, j):
            return (b, j, 0)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
                   jax.ShapeDtypeStruct((B * H, 1, S), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), kv_idx),
            pl.BlockSpec((1, block_k, D), kv_idx),
        ],
        out_specs=(pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))),
        scratch_shapes=[pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_interpret(),
    )(qf, kf, vf)
    return out.reshape(B, H, S, D), lse


# --------------------------------------------------------------- backward
def _recompute_p_ds(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, qb, kb,
                    causal, scale, block_q, block_k):
    """Shared FA2 recompute: returns (q, do, k_blk, p, ds) for one block pair."""
    q = q_ref[0].astype(jnp.float32)                     # (block_q, D)
    do = do_ref[0].astype(jnp.float32)                   # (block_q, D)
    lse = lse_ref[0, 0][:, None]                         # (block_q, 1)
    delta = delta_ref[0, 0][:, None]                     # (block_q, 1)
    k_blk = k_ref[0].astype(jnp.float32)                 # (block_k, D)
    v_blk = v_ref[0].astype(jnp.float32)

    s = (q @ k_blk.T) * scale                            # (block_q, block_k)
    if causal:
        qi = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        ki = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(qi >= ki, s, -jnp.inf)
    p = jnp.exp(s - lse)                                 # (block_q, block_k)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    dp = do @ v_blk.T                                    # (block_q, block_k)
    ds = p * (dp - delta) * scale
    return q, do, k_blk, p, ds


def _fa_bwd_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                       dk_ref, dv_ref, *, causal, scale, block_q, block_k):
    """Grid (bh, kv-block, q-block): accumulate dK/dV over sequential q steps."""
    from jax.experimental import pallas as pl

    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_ref[0, :, :] = jnp.zeros_like(dk_ref[0])
        dv_ref[0, :, :] = jnp.zeros_like(dv_ref[0])

    kb = pl.program_id(1)
    # q-blocks fully above the diagonal contribute nothing in causal mode
    live = (qb + 1) * block_q - 1 >= kb * block_k if causal else qb >= 0

    @pl.when(live)
    def _compute():
        q, do, _k, p, ds = _recompute_p_ds(
            q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, qb, kb,
            causal, scale, block_q, block_k)
        dv_ref[0, :, :] += (p.T @ do).astype(dv_ref.dtype)
        dk_ref[0, :, :] += (ds.T @ q).astype(dk_ref.dtype)


def _fa_bwd_dq_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, *, causal, scale, block_q, block_k):
    """Grid (bh, q-block, kv-block): accumulate dQ over sequential kv steps."""
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_ref[0, :, :] = jnp.zeros_like(dq_ref[0])

    qb = pl.program_id(1)
    # K/V blocks fully above the diagonal contribute nothing in causal mode
    live = (qb + 1) * block_q - 1 >= kb * block_k if causal else kb >= 0

    @pl.when(live)
    def _compute():
        _q, _do, k_blk, _p, ds = _recompute_p_ds(
            q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, qb, kb,
            causal, scale, block_q, block_k)
        dq_ref[0, :, :] += (ds @ k_blk).astype(dq_ref.dtype)


def _fa_bwd_call(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                 g_lse=None):
    from jax.experimental import pallas as pl

    B, H, S, D = q.shape
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    dof = do.reshape(B * H, S, D)
    # delta_i = sum_d dO_i * O_i — O(S*D), computed by XLA
    delta = jnp.sum(dof.astype(jnp.float32) *
                    o.reshape(B * H, S, D).astype(jnp.float32),
                    axis=-1)[:, None, :]                 # (B*H, 1, S)
    if g_lse is not None:
        # When LSE is a second primal output (flash_attention_lse), its
        # cotangent enters ds exactly as -delta does: d lse_i/d s_ij = p_ij,
        # so ds_ij = p_ij*(dp_ij - delta_i + g_lse_i)*scale — fold it in.
        delta = delta - g_lse.astype(jnp.float32)

    if causal:
        # dkv grid streams q-blocks (j) per kv-block (i): q-blocks strictly
        # above the diagonal are dead — clamp to the first live one so no
        # DMA is issued for blocks pl.when will skip
        def q_idx(b, i, j):
            return (b, jnp.maximum(j, (i * block_k) // block_q), 0)

        def row_idx(b, i, j):
            return (b, 0, jnp.maximum(j, (i * block_k) // block_q))
    else:
        def q_idx(b, i, j):
            return (b, j, 0)

        def row_idx(b, i, j):
            return (b, 0, j)
    qspec = pl.BlockSpec((1, block_q, D), q_idx)
    rowspec = pl.BlockSpec((1, 1, block_q), row_idx)
    kvspec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0))
    dkv_kernel = functools.partial(_fa_bwd_dkv_kernel, causal=causal,
                                   scale=scale, block_q=block_q,
                                   block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=(jax.ShapeDtypeStruct((B * H, S, D), jnp.float32),
                   jax.ShapeDtypeStruct((B * H, S, D), jnp.float32)),
        grid=(B * H, S // block_k, S // block_q),
        in_specs=[qspec, qspec, rowspec, rowspec, kvspec, kvspec],
        out_specs=(kvspec, kvspec),
        interpret=_interpret(),
    )(qf, dof, lse, delta, kf, vf)

    if causal:
        # dq grid streams kv-blocks (j) per q-block (i): kv-blocks above
        # the diagonal are dead — clamp to the last live one
        def kv_idx2(b, i, j):
            return (b, jnp.minimum(j, ((i + 1) * block_q - 1) // block_k), 0)
    else:
        def kv_idx2(b, i, j):
            return (b, j, 0)
    qspec2 = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    rowspec2 = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))
    kvspec2 = pl.BlockSpec((1, block_k, D), kv_idx2)
    dq_kernel = functools.partial(_fa_bwd_dq_kernel, causal=causal,
                                  scale=scale, block_q=block_q,
                                  block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), jnp.float32),
        grid=(B * H, S // block_q, S // block_k),
        in_specs=[kvspec2, kvspec2, qspec2, qspec2, rowspec2, rowspec2],
        out_specs=qspec2,
        interpret=_interpret(),
    )(kf, vf, qf, dof, lse, delta)

    shape = (B, H, S, D)
    return (dq.reshape(shape).astype(q.dtype),
            dk.reshape(shape).astype(k.dtype),
            dv.reshape(shape).astype(v.dtype))


# --------------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None):
    """q,k,v: (B, H, S, D) → (B, H, S, D). Blocks default to the measured
    optimum (largest of 512/256/128 dividing S)."""
    return _fa_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    block_q, block_k = _resolve_blocks(q.shape[2], block_q, block_k)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # the kernels assume self-attention shapes (Sq == Sk); cross-attention
    # with mismatched lengths takes the composite (which handles it)
    if k.shape == q.shape and v.shape == q.shape \
            and flash_attention_supported(q.shape, block_q, block_k):
        out, lse = _fa_call(q, k, v, causal, scale, block_q, block_k)
    else:
        out, lse = _blocked_reference(q, k, v, causal, scale), None
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    block_q, block_k = _resolve_blocks(q.shape[2], block_q, block_k)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if lse is not None:
        return _fa_bwd_call(q, k, v, o, lse, do, causal, scale, block_q,
                            block_k)
    # XLA composite fallback (materializes (S,S); only off-TPU small shapes)
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    dof = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


# ------------------------------------------------- out + LSE (for SP paths)
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_lse(q, k, v, causal=False, scale=None, block_q=None,
                        block_k=None):
    """Like flash_attention but ALSO returns the per-row log-sum-exp
    (B, H, S) fp32 — the sufficient statistic ring attention's online
    combine needs. Both outputs are differentiable: the LSE cotangent
    folds into the existing backward kernels as a delta shift (see
    _fa_bwd_call). Requires flash_attention_supported(q.shape)."""
    return _fa_lse_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def _fa_lse_fwd(q, k, v, causal, scale, block_q, block_k):
    B, H, S, D = q.shape
    block_q, block_k = _resolve_blocks(S, block_q, block_k)
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    out, lse = _fa_call(q, k, v, causal, scale, block_q, block_k)
    return (out, lse.reshape(B, H, S)), (q, k, v, out, lse)


def _fa_lse_bwd(causal, scale, block_q, block_k, res, cts):
    do, dlse = cts
    q, k, v, o, lse = res
    B, H, S, D = q.shape
    block_q, block_k = _resolve_blocks(S, block_q, block_k)
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    return _fa_bwd_call(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                        g_lse=dlse.reshape(B * H, 1, S))


flash_attention_lse.defvjp(_fa_lse_fwd, _fa_lse_bwd)


def _dense_with_lse(q, k, v, causal, scale):
    """Differentiable XLA fallback returning (out, lse) — same contract as
    flash_attention_lse for shapes/platforms the kernels can't take."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-37)
    out = jnp.einsum("bhqk,bhkd->bhqd", (p / l).astype(v.dtype), v)
    lse = (m_safe + jnp.log(l))[..., 0]
    return out.astype(q.dtype), lse


def attention_with_lse(q, k, v, causal=False, scale=None):
    """(out, lse) via the Pallas kernels when supported, dense otherwise.
    The local step of ring/Ulysses sequence parallelism — per-shard memory
    is O(block^2), not O((S/n)^2), when the kernel engages."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if k.shape == q.shape and v.shape == q.shape \
            and flash_attention_supported(q.shape):
        return flash_attention_lse(q, k, v, causal, scale)
    return _dense_with_lse(q, k, v, causal, scale)
