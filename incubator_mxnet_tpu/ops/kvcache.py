"""Paged (blocked) KV cache for autoregressive decode — the vLLM-style
HBM pool behind serving/generate.py's continuous-batching engine.

The problem this layout solves: a naive per-sequence KV cache allocates
``max_seq_len`` of HBM per request up front, so the in-flight batch is
sized by the WORST-CASE context even though most sequences retire early
— the dominant HBM waste in production generative serving. Here the
cache is one preallocated pool of fixed-size **blocks**
(``MXTPU_GEN_BLOCK_SIZE`` token slots each); a sequence owns a list of
block ids (its **block table**) that grows one block at a time as it
decodes and returns to the free list the moment it retires, so pool
occupancy tracks the LIVE token count, not the worst case.

Split of responsibilities:

- ``BlockAllocator`` — host-side free-list bookkeeping (alloc/free/used;
  LIFO reuse so tests can pin reuse determinism). Pure Python, lock
  guarded: only the decode loop and join path touch it.
- the pure functions — jit-safe pool updates and reads
  (``write_seq`` / ``append_token`` / ``gather_layer`` /
  ``paged_attention``), all expressed as XLA scatter/gather on a pool
  argument that is **donated** by the decode program
  (``donate_argnums``), so steady-state decode updates the cache
  in place instead of copying the whole pool every step. hlolint's
  H002 decode generalization (tools/hlolint/rules.py) lints exactly
  this: a compiled decode program whose pool does not alias
  input→output is an error-severity finding at the load gate.

Out-of-range index convention: scatters use ``mode="drop"`` with
``num_blocks`` (one past the last block) as the "nowhere" index, so
padded positions and inactive batch slots write NOTHING rather than
corrupting block 0 of a live sequence; gathers use the default clamp
mode and mask by length instead. Both conventions are jit-safe (no
host-side branching on traced values).

Pool layout: ``(num_blocks, block_size, layers, 2, heads, head_dim)``
— the leading two dims are the paging geometry (one scatter/gather
covers every layer), the trailing ``2`` is K/V.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

__all__ = ["KVCacheOOM", "BlockAllocator", "pool_shape", "make_pool",
           "pool_bytes", "blocks_for", "write_seq", "append_token",
           "gather_layer", "paged_attention"]


class KVCacheOOM(RuntimeError):
    """The pool has fewer free blocks than the allocation needs — the
    engine's join path turns this into admission backpressure (the
    request waits; decode of the live batch keeps freeing blocks)."""


class BlockAllocator:
    """Host-side free-list over ``num_blocks`` pool blocks.

    LIFO reuse (the most recently freed block is handed out first) —
    deterministic, so tests can assert a retired sequence's blocks are
    the exact ones a joining sequence receives. All methods are
    thread-safe; the invariant ``used + free == total`` holds at every
    exit and double-frees raise instead of silently corrupting the
    free list.
    """

    def __init__(self, num_blocks):
        if num_blocks <= 0:
            raise ValueError("need at least one block, got %d" % num_blocks)
        self.total = int(num_blocks)
        self._lock = threading.Lock()
        # stack order: block 0 on top so first alloc is [0, 1, ...]
        self._free = list(range(self.total - 1, -1, -1))
        self._held = set()

    @property
    def used(self):
        with self._lock:
            return self.total - len(self._free)

    @property
    def free_count(self):
        with self._lock:
            return len(self._free)

    def alloc(self, n):
        """Take ``n`` blocks or raise KVCacheOOM taking NONE (an
        admission decision must never half-allocate)."""
        n = int(n)
        if n < 0:
            raise ValueError("alloc(%d)" % n)
        with self._lock:
            if n > len(self._free):
                raise KVCacheOOM(
                    "need %d KV block(s), %d free of %d — raise "
                    "MXTPU_GEN_KV_BLOCKS or lower the admission load"
                    % (n, len(self._free), self.total))
            taken = [self._free.pop() for _ in range(n)]
            self._held.update(taken)
        return taken

    def free(self, blocks):
        """Return blocks to the free list (newest freed reused first)."""
        with self._lock:
            for b in blocks:
                b = int(b)
                if b not in self._held:
                    raise ValueError("double free of KV block %d" % b)
                self._held.discard(b)
                self._free.append(b)


def blocks_for(tokens, block_size):
    """Blocks needed to hold ``tokens`` positions (ceil division)."""
    return max(1, -(-int(tokens) // int(block_size)))


def pool_shape(num_blocks, block_size, layers, heads, head_dim):
    """The pool's array shape — the one place the layout is spelled."""
    return (num_blocks, block_size, layers, 2, heads, head_dim)


def make_pool(num_blocks, block_size, layers, heads, head_dim,
              dtype=jnp.float32):
    """The preallocated HBM pool, zero-filled (unwritten slots read as
    zeros — finite, so a masked row never produces NaN scores)."""
    return jnp.zeros(pool_shape(num_blocks, block_size, layers, heads,
                                head_dim), dtype=dtype)


def pool_bytes(num_blocks, block_size, layers, heads, head_dim,
               dtype=jnp.float32):
    """Planning math for docs/GENERATE.md sizing against devstats
    ``hbm_capacity()``: bytes one pool occupies."""
    n = num_blocks * block_size * layers * 2 * heads * head_dim
    return int(n) * jnp.dtype(dtype).itemsize


def _nowhere(pool):
    """The drop index: one past the last block (mode='drop' discards)."""
    return pool.shape[0]


def write_seq(pool, blocks, k, v, length):
    """Write one sequence's prefill K/V into its blocks (jit-safe).

    ``blocks``: (max_blocks,) int32 block table row; ``k``/``v``:
    (L_pad, layers, heads, head_dim) — positions ``>= length`` are
    padding and are dropped (their scatter index is out of range).
    Returns the updated pool; the compiled join program donates ``pool``
    so this is an in-place block write on device.
    """
    L_pad = k.shape[0]
    bs = pool.shape[1]
    pos = jnp.arange(L_pad, dtype=jnp.int32)
    blk = jnp.where(pos < length, blocks[pos // bs], _nowhere(pool))
    off = pos % bs
    kv = jnp.stack([k, v], axis=2)      # (L_pad, layers, 2, heads, hd)
    return pool.at[blk, off].set(kv, mode="drop")


def append_token(pool, block_tables, lengths, layer, k, v, active=None):
    """Append one decode step's K/V at position ``lengths[i]`` for every
    batch row (jit-safe, one layer at a time — layer ``l``'s K/V only
    exists after layer ``l-1``'s attention ran).

    ``block_tables``: (B, max_blocks) int32; ``lengths``: (B,) int32
    (the position being written); ``k``/``v``: (B, heads, head_dim).
    Rows where ``active`` is False (padded batch slots) write nothing.
    """
    bs = pool.shape[1]
    b_idx = jnp.arange(block_tables.shape[0])
    blk = block_tables[b_idx, lengths // bs]
    if active is not None:
        blk = jnp.where(active, blk, _nowhere(pool))
    off = lengths % bs
    kv = jnp.stack([k, v], axis=1)      # (B, 2, heads, hd)
    return pool.at[blk, off, layer].set(kv, mode="drop")


def gather_layer(pool, block_tables, layer):
    """One layer's cached K and V for every row, block-table order =
    position order: -> (keys, values), each (B, T, heads, head_dim)
    where ``T = max_blocks * block_size``. Out-of-range table entries
    clamp (default gather mode) — callers mask by length, so clamped
    garbage never reaches the softmax unmasked."""
    g = pool[block_tables]              # (B, max_blocks, bs, layers, 2, h, d)
    B, mb, bs = g.shape[0], g.shape[1], g.shape[2]
    g = g[:, :, :, layer]               # (B, max_blocks, bs, 2, h, d)
    g = g.reshape(B, mb * bs, 2, g.shape[4], g.shape[5])
    return g[:, :, 0], g[:, :, 1]


def paged_attention(q, keys, values, lengths):
    """Masked single-token attention over the gathered cache (jit-safe).

    ``q``: (B, heads, head_dim) — the current position's query;
    ``keys``/``values``: (B, T, heads, head_dim) from ``gather_layer``;
    ``lengths``: (B,) int32 — the number of VALID positions (including
    the token just appended). Softmax runs in fp32 (the flash-kernel
    numerics convention, ops/attention.py) and positions ``>= length``
    score ``-inf`` — with length >= 1 guaranteed by the caller the row
    sum is always finite.
    """
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bhd,bthd->bht", q, keys).astype(jnp.float32) * scale
    t = jnp.arange(keys.shape[1], dtype=jnp.int32)
    mask = t[None, :] < lengths[:, None]            # (B, T)
    s = jnp.where(mask[:, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(values.dtype)
    return jnp.einsum("bht,bthd->bhd", p, values)
