"""Custom TPU ops: Pallas kernels + composite HLO ops (multibox, ctc)."""
from . import multibox  # noqa
from .multibox import MultiBoxPrior, MultiBoxTarget, MultiBoxDetection  # noqa
