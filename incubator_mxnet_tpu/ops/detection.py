"""Detection & spatial-sampling ops (ref src/operator/contrib/roi_align.cc,
proposal.cc, src/operator/roi_pooling.cc, bilinear_sampler.cc,
spatial_transformer.cc, tensor/bounding_box.cc).

TPU-native notes: everything is static-shape and vectorized — bilinear
sampling is a flat gather (take_along_axis) the TPU executes as dynamic
slices; NMS is an O(N^2) suppression matrix + lax.fori_loop greedy scan
(the reference's sorted pairwise loop, compiler-friendly); ROIPooling's
data-dependent bin quantization is realized as max over a fixed sample grid
per bin (documented divergence: matches as sample density grows).
DeformableConvolution is intentionally not provided (documented cut — no
model family in the zoo uses it; its im2col+offset gather would follow the
same sampling core below).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray import NDArray, _apply

__all__ = ["roi_align", "roi_pooling", "bilinear_sampler", "grid_generator",
           "spatial_transformer", "box_iou", "box_nms", "bipartite_matching",
           "multi_proposal", "fft", "ifft"]


def _bilinear_gather(img, ys, xs):
    """img (N,C,H,W); ys/xs (N,hs,ws) float pixel coords → (N,C,hs,ws)."""
    N, C, H, W = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0

    def at(yi, xi):
        yi = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        flat = img.reshape(N, C, H * W)
        idx = (yi * W + xi).reshape(N, 1, -1)
        out = jnp.take_along_axis(flat, jnp.broadcast_to(idx, (N, C, idx.shape[-1])), axis=2)
        return out.reshape(N, C, ys.shape[1], ys.shape[2])

    v = (at(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
         + at(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
         + at(y0 + 1, x0) * (wy * (1 - wx))[:, None]
         + at(y0 + 1, x0 + 1) * (wy * wx)[:, None])
    # zero outside the source image (ref bilinear_sampler zero-padding)
    inside = ((ys > -1) & (ys < H) & (xs > -1) & (xs < W))[:, None]
    return jnp.where(inside, v, 0.0)


def _roi_sample_grid(rois, pooled_size, spatial_scale, samples, align):
    """Per-ROI sample coordinates (N, PH*s, PW*s) for y and x."""
    PH, PW = pooled_size
    s = samples
    off = 0.5 if align else 0.0  # ROIAlign's half-pixel alignment
    x1 = rois[:, 1] * spatial_scale - off
    y1 = rois[:, 2] * spatial_scale - off
    x2 = rois[:, 3] * spatial_scale - off
    y2 = rois[:, 4] * spatial_scale - off
    roi_w = jnp.maximum(x2 - x1, 1.0)
    roi_h = jnp.maximum(y2 - y1, 1.0)
    # sample centers: bin i, subsample j → start + (i + (j+0.5)/s) * bin
    iy = (jnp.arange(PH)[:, None] + (jnp.arange(s)[None, :] + 0.5) / s) \
        .reshape(-1)                                        # (PH*s,)
    ix = (jnp.arange(PW)[:, None] + (jnp.arange(s)[None, :] + 0.5) / s) \
        .reshape(-1)
    ys = y1[:, None] + iy[None, :] * (roi_h / PH)[:, None]  # (N, PH*s)
    xs = x1[:, None] + ix[None, :] * (roi_w / PW)[:, None]
    Y = jnp.broadcast_to(ys[:, :, None], ys.shape + (xs.shape[1],))
    X = jnp.broadcast_to(xs[:, None, :], (xs.shape[0], ys.shape[1],
                                          xs.shape[1]))
    return Y, X


def _roi_fn(data, rois, pooled_size, spatial_scale, sample_ratio, reduce,
            align):
    PH, PW = pooled_size
    s = max(int(sample_ratio), 1)
    bidx = jnp.clip(rois[:, 0].astype(jnp.int32), 0, data.shape[0] - 1)
    img = data[bidx]                                        # (N,C,H,W)
    Y, X = _roi_sample_grid(rois, pooled_size, spatial_scale, s, align)
    sampled = _bilinear_gather(img, Y, X)                   # (N,C,PH*s,PW*s)
    N, C = sampled.shape[:2]
    blocks = sampled.reshape(N, C, PH, s, PW, s)
    return blocks.max((3, 5)) if reduce == "max" else blocks.mean((3, 5))


def roi_align(data, rois, pooled_size, spatial_scale, sample_ratio=2):
    """ref src/operator/contrib/roi_align.cc ROIAlignForward."""
    return _apply(lambda d, r: _roi_fn(d, r, tuple(pooled_size),
                                       spatial_scale, sample_ratio, "mean",
                                       align=True), data, rois)


def roi_pooling(data, rois, pooled_size, spatial_scale):
    """ref src/operator/roi_pooling.cc — max over each bin; realized as max
    over a 2x2 bilinear sample grid per bin (static-shape divergence from
    the reference's exact integer-bin max; converges with sample density)."""
    return _apply(lambda d, r: _roi_fn(d, r, tuple(pooled_size),
                                       spatial_scale, 2, "max", align=False),
                  data, rois)


def grid_generator(data, transform_type="affine", target_shape=None):
    """ref spatial_transformer.cc GridGenerator: affine (N,6) → flow grid
    (N, 2, H, W) in [-1, 1] (x then y, MXNet order)."""
    H, W = target_shape

    def fn(theta):
        t = theta.reshape(-1, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gx, gy = jnp.meshgrid(xs, ys)                        # (H,W)
        ones = jnp.ones_like(gx)
        src = jnp.stack([gx, gy, ones], 0).reshape(3, -1)    # (3, H*W)
        out = jnp.einsum("nij,jk->nik", t, src)              # (N,2,H*W)
        return out.reshape(-1, 2, H, W)

    if transform_type != "affine":
        raise ValueError("grid_generator supports affine (ref parity: "
                         "warp type takes a precomputed flow)")
    return _apply(fn, data)


def bilinear_sampler(data, grid):
    """ref bilinear_sampler.cc: sample data (N,C,H,W) at grid (N,2,Ho,Wo)
    with x/y in [-1, 1]; zero padding outside."""

    def fn(d, g):
        N, C, H, W = d.shape
        xs = (g[:, 0] + 1.0) * (W - 1) / 2.0
        ys = (g[:, 1] + 1.0) * (H - 1) / 2.0
        return _bilinear_gather(d, ys, xs)

    return _apply(fn, data, grid)


def spatial_transformer(data, loc, target_shape, transform_type="affine",
                        sampler_type="bilinear"):
    """ref spatial_transformer.cc: affine loc (N,6) warps data to
    target_shape."""
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


# ------------------------------------------------------------- boxes
def _iou_matrix(a, b, fmt="corner"):
    # reviewed retrace: fmt is a two-value static config ("corner" |
    # "center") fixed per call site — at most two trace variants ever,
    # the CachedOp-style specialization idiom, not a per-call retrace
    if fmt == "center":  # mxtpulint: disable=R011
        a = jnp.concatenate([a[..., :2] - a[..., 2:] / 2,
                             a[..., :2] + a[..., 2:] / 2], -1)
        b = jnp.concatenate([b[..., :2] - b[..., 2:] / 2,
                             b[..., :2] + b[..., 2:] / 2], -1)
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = ((a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1]))[..., :, None]
    area_b = ((b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1]))[..., None, :]
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


def box_iou(lhs, rhs, format="corner"):
    """ref tensor/bounding_box.cc box_iou."""
    return _apply(lambda a, b: _iou_matrix(a, b, format), lhs, rhs)


def _nms_keep(boxes, scores, iou_threshold, topk, cls=None):
    """Greedy NMS: returns keep mask (N,) — sorted scan over scores.
    With ``cls``, suppression only happens within the same class id."""
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _iou_matrix(b, b)
    if cls is not None:
        c = cls[order]
        iou = jnp.where(c[:, None] == c[None, :], iou, 0.0)
    n = boxes.shape[0]

    def body(i, keep):
        # suppress i if any kept higher-scoring box overlaps too much
        over = (iou[i] > iou_threshold) & keep & (jnp.arange(n) < i)
        return keep.at[i].set(~jnp.any(over))

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
    # reviewed retrace: topk is a per-model constant (box_nms config),
    # so this specializes one trace per deployed topk value — bounded by
    # construction; a traced cap (cumsum <= topk as an array) would drag
    # the whole op into dynamic-shape territory for no production gain
    if topk is not None and topk > 0:  # mxtpulint: disable=R011
        keep_sorted = keep_sorted & (jnp.cumsum(keep_sorted) <= topk)
    keep = jnp.zeros(n, bool).at[order].set(keep_sorted)
    return keep


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """ref tensor/bounding_box.cc box_nms: (..., N, K) rows
    [id, score, x1, y1, x2, y2, ...]; suppressed rows become -1.
    Default force_suppress=False (ref parity): suppression is per-class
    when id_index is given; force_suppress=True ignores class ids."""

    def one(rows):
        scores = rows[:, score_index]
        boxes = rows[:, coord_start:coord_start + 4]
        cls = None
        if not force_suppress and id_index >= 0:
            cls = rows[:, id_index]
        valid = scores > valid_thresh
        keep = _nms_keep(boxes, jnp.where(valid, scores, -jnp.inf),
                         overlap_thresh, topk if topk > 0 else None, cls)
        keep = keep & valid
        return jnp.where(keep[:, None], rows, -jnp.ones_like(rows))

    def fn(x):
        flat = x.reshape((-1,) + x.shape[-2:])
        out = jax.vmap(one)(flat)
        return out.reshape(x.shape)

    return _apply(fn, data)


def bipartite_matching(data, threshold, is_ascend=False, topk=-1):
    """ref tensor/bounding_box.cc bipartite_matching: greedy best-first
    matching over a (N, M) score matrix → (row_match (N,), col_match (M,))."""

    def fn(s):
        N, M = s.shape
        blank = jnp.inf if is_ascend else -jnp.inf
        k = min(N, M) if topk <= 0 else min(topk, N, M)

        def body(_, carry):
            row, col, sc = carry
            flat = jnp.argmin(sc) if is_ascend else jnp.argmax(sc)
            i, j = flat // M, flat % M
            ok = (sc[i, j] <= threshold) if is_ascend else \
                (sc[i, j] >= threshold)
            row = jnp.where(ok, row.at[i].set(j.astype(row.dtype)), row)
            col = jnp.where(ok, col.at[j].set(i.astype(col.dtype)), col)
            sc = sc.at[i, :].set(blank)
            sc = sc.at[:, j].set(blank)
            return row, col, sc

        row0 = -jnp.ones(N, jnp.float32)
        col0 = -jnp.ones(M, jnp.float32)
        row, col, _ = jax.lax.fori_loop(0, k, body, (row0, col0, s))
        return row, col

    return _apply(fn, data)


def multi_proposal(cls_prob, bbox_pred, im_info, feature_stride=16,
                   scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                   rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
                   threshold=0.7, rpn_min_size=16):
    """ref src/operator/contrib/multi_proposal.cc — RPN proposal generation:
    anchors + deltas → clip → NMS → top-N rois (N, 5)."""

    def fn(scores, deltas, info):
        B, A2, H, W = scores.shape
        A = A2 // 2
        if A != len(scales) * len(ratios):
            raise ValueError(
                "cls_prob has %d anchors/position but scales x ratios = %d"
                % (A, len(scales) * len(ratios)))
        base = _generate_anchors(feature_stride, scales, ratios)  # (A,4)
        sx = jnp.arange(W) * feature_stride
        sy = jnp.arange(H) * feature_stride
        shift = jnp.stack(jnp.meshgrid(sx, sy), -1).reshape(-1, 2)  # (H*W,2)
        shifts = jnp.concatenate([shift, shift], -1)                # (H*W,4)
        anchors = (base[None] + shifts[:, None]).reshape(-1, 4)     # (H*W*A,4)

        def one(sc, dl, inf):
            fg = sc[A:].reshape(A, H * W).T.reshape(-1)             # (H*W*A,)
            d = dl.reshape(A, 4, H * W).transpose(2, 0, 1).reshape(-1, 4)
            boxes = _apply_deltas(anchors, d)
            boxes = jnp.stack([
                jnp.clip(boxes[:, 0], 0, inf[1] - 1),
                jnp.clip(boxes[:, 1], 0, inf[0] - 1),
                jnp.clip(boxes[:, 2], 0, inf[1] - 1),
                jnp.clip(boxes[:, 3], 0, inf[0] - 1)], -1)
            ws = boxes[:, 2] - boxes[:, 0] + 1
            hs = boxes[:, 3] - boxes[:, 1] + 1
            fg = jnp.where((ws >= rpn_min_size) & (hs >= rpn_min_size),
                           fg, -jnp.inf)
            n_pre = min(rpn_pre_nms_top_n, fg.shape[0])
            top_sc, top_i = jax.lax.top_k(fg, n_pre)
            top_boxes = boxes[top_i]
            keep = _nms_keep(top_boxes, top_sc, threshold,
                             rpn_post_nms_top_n)
            n_post = min(rpn_post_nms_top_n, n_pre)
            sel_sc, sel_i = jax.lax.top_k(jnp.where(keep, top_sc, -jnp.inf),
                                          n_post)
            return top_boxes[sel_i]

        rois = jax.vmap(one)(scores, deltas, info)          # (B, n_post, 4)
        bidx = jnp.broadcast_to(jnp.arange(B, dtype=rois.dtype)[:, None, None],
                                rois.shape[:2] + (1,))
        return jnp.concatenate([bidx, rois], -1).reshape(-1, 5)

    return _apply(fn, cls_prob, bbox_pred, im_info)


def _generate_anchors(stride, scales, ratios):
    base = jnp.array([0, 0, stride - 1, stride - 1], jnp.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    out = []
    for r in ratios:
        size = w * h
        ws = jnp.round(jnp.sqrt(size / r))
        hs = jnp.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            out.append(jnp.stack([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                                  cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)]))
    return jnp.stack(out)


def _apply_deltas(anchors, deltas):
    w = anchors[:, 2] - anchors[:, 0] + 1
    h = anchors[:, 3] - anchors[:, 1] + 1
    cx = anchors[:, 0] + 0.5 * (w - 1)
    cy = anchors[:, 1] + 0.5 * (h - 1)
    ncx = deltas[:, 0] * w + cx
    ncy = deltas[:, 1] * h + cy
    nw = jnp.exp(deltas[:, 2]) * w
    nh = jnp.exp(deltas[:, 3]) * h
    return jnp.stack([ncx - 0.5 * (nw - 1), ncy - 0.5 * (nh - 1),
                      ncx + 0.5 * (nw - 1), ncy + 0.5 * (nh - 1)], -1)


# ------------------------------------------------------------- fft
def fft(data, compute_size=None):
    """ref src/operator/contrib/fft.cc: last-axis FFT; output interleaves
    real/imag → (..., 2n) (the reference's cuFFT layout)."""

    def fn(x):
        c = jnp.fft.fft(x.astype(jnp.complex64), axis=-1)
        return jnp.stack([c.real, c.imag], -1).reshape(x.shape[:-1]
                                                       + (2 * x.shape[-1],))

    return _apply(fn, data)


def ifft(data, compute_size=None):
    """ref src/operator/contrib/fft.cc IFFT: interleaved (..., 2n) → (..., n).

    Matches the reference: returns the REAL part scaled by n (cuFFT's
    unnormalized inverse)."""

    def fn(x):
        n = x.shape[-1] // 2
        c = x.reshape(x.shape[:-1] + (n, 2))
        z = c[..., 0] + 1j * c[..., 1]
        return jnp.fft.ifft(z, axis=-1).real * n

    return _apply(fn, data)
